"""omnilint engine: one AST walk per file, dispatching to rule visitors.

The analysis layer is the JAX/TPU-aware counterpart of a stock linter:
stock tools see valid Python where this codebase sees staged-out traces,
donated buffers, host↔device sync points, and cross-process frame
protocols.  Each rule family (``rules/``) encodes one of those invisible
contracts; the engine owns everything rule-agnostic:

- parsing each file ONCE and walking its AST once, dispatching nodes to
  every applicable rule's ``visit`` (rules declare ``node_types``);
  rules that need whole-file aggregation emit from ``finish``
- suppression comments (same line or the line above a finding)::

      x = foo()  # omnilint: disable=OL2
      # omnilint: disable=OL1,OL3   (suppresses the next line)
      # omnilint: disable-file=OL4  (anywhere: suppresses the whole file)

- the committed baseline (``analysis/baseline.json``): pre-existing
  findings fingerprinted by (rule, path, symbol, message) — NOT line
  numbers, so unrelated edits don't invalidate it — with per-fingerprint
  counts.  The gate fails only on findings *beyond* the baselined count.

No jax import anywhere in this package: the CLI must run in any lane
(the same stance as scripts/check_metrics_names.py, which rule OL6
absorbed).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field, replace
from typing import Iterable, Optional

# repo root == parent of the vllm_omni_tpu package dir; fingerprints use
# paths relative to it so the baseline is stable across checkouts/cwd
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*omnilint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``fingerprint`` deliberately omits the line
    number: the baseline must survive unrelated edits above a finding."""

    rule: str      # "OL1".."OL6" ("OL0" = file failed to parse)
    path: str      # repo-relative posix path
    line: int
    message: str
    symbol: str = ""          # enclosing def/class qualname, "" = module
    suppressed: bool = False  # matched a disable comment
    baselined: bool = False   # absorbed by the committed baseline
    # line span of the enclosing statement: a suppression anywhere in it
    # applies (multi-line calls anchor findings on continuation lines)
    stmt_span: tuple = ()
    # OL12/OL13 chain report: ((line, note), ...) waypoints of the
    # leaking path (acquire site -> exception crossings -> escape
    # point).  Rendering only — NOT part of the fingerprint, so the
    # baseline survives path-shape churn from unrelated edits.
    trace: tuple = ()

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.symbol}|{self.message}"

    def render(self) -> str:
        tag = (" [suppressed]" if self.suppressed
               else " [baselined]" if self.baselined else "")
        sym = f" ({self.symbol})" if self.symbol else ""
        out = (f"{self.path}:{self.line}: {self.rule}{tag} "
               f"{self.message}{sym}")
        if self.trace:
            out += "".join(f"\n    {self.path}:{ln}: {note}"
                           for ln, note in self.trace)
        return out


class FileContext:
    """Everything rules need about one file: source, tree, parent links,
    and qualname resolution — built once, shared by every rule."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # ------------------------------------------------------------ lineage
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        cur = node
        while not isinstance(cur, ast.stmt):
            cur = self.parents[cur]
        return cur

    def qualname(self, node: ast.AST) -> str:
        """Dotted def/class chain enclosing ``node`` ("" at module level)."""
        parts = []
        scopes = [node] if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ) else []
        scopes += [a for a in self.ancestors(node) if isinstance(
            a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))]
        for scope in scopes:
            parts.append(scope.name)
        return ".".join(reversed(parts))

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        span = (line, line)
        try:
            stmt = self.enclosing_statement(node)
            span = (stmt.lineno, stmt.end_lineno or stmt.lineno)
        except KeyError:
            pass  # synthetic/module-level anchor
        return Finding(rule=rule, path=self.path, line=line,
                       message=message, symbol=self.qualname(node),
                       stmt_span=span)


class Rule:
    """Base rule: subclasses declare ``node_types`` and yield Findings
    from ``visit`` (per matching node, one engine walk) and/or
    ``finish`` (after the walk — whole-file aggregates).  A fresh
    instance runs per file, so instance state is per-file state;
    ``run_state`` (a dict the engine threads through one analysis run —
    all files of an ``analyze_paths`` call share it, a standalone
    ``analyze_source`` gets a fresh one unless the caller passes its
    own) is where cross-FILE state lives, so one run never leaks into
    the next (rule OL8's lock-order graph rides it)."""

    id: str = ""
    name: str = ""
    node_types: tuple = ()
    run_state: Optional[dict] = None  # set by the engine per run

    def applies(self, ctx: FileContext) -> bool:
        return True

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize_run(self) -> Iterable[Finding]:
        """Called ONCE per analysis run, after every file's walk, on a
        fresh instance whose ``run_state`` carries the whole run: the
        ``files`` registry (path -> FileContext) and anything per-file
        passes stashed.  This is where package-wide rules live — the
        cross-module taint (OL10) and recompile-hazard (OL11) families
        need the full symbol table and call graph
        (:class:`ProgramGraph`) before they can judge any one file.
        Suppressions are applied afterwards by the engine, per the
        finding's own path."""
        return ()


# --------------------------------------------------------------- suppression
class SuppressionIndex:
    """Per-file ``# omnilint: disable`` comments with USE tracking.

    Each comment declares (declaration line, rule) pairs; applying the
    file's findings marks the pairs that actually suppressed one.  The
    pairs that never fire are *stale* — dead suppressions that would
    silently bless a future regression — and the
    ``--report-stale-suppressions`` audit (``stale_suppressions``)
    collects them across a run."""

    def __init__(self, ctx: FileContext):
        self.path = ctx.path
        # (decl_line, rule) -> covered line set, or None for file-wide
        self.declared: dict[tuple, Optional[set]] = {}
        self.used: set[tuple] = set()
        n = len(ctx.lines)
        comment_lines = self._comment_lines(ctx)
        for i, line in enumerate(ctx.lines, start=1):
            if comment_lines is not None and i not in comment_lines:
                continue  # e.g. a suppression EXAMPLE inside a docstring
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group("rules").split(",")}
            if m.group("file"):
                for r in rules:
                    self.declared[(i, r)] = None
                continue
            covered = {i}
            # a comment-only line suppresses the next CODE line (the
            # disable may sit atop a multi-line explanation block)
            if line.strip().startswith("#"):
                j = i + 1
                while j <= n and ctx.lines[j - 1].strip().startswith("#"):
                    j += 1
                covered.add(j)
            for r in rules:
                cur = self.declared.setdefault((i, r), set())
                if cur is not None:
                    cur.update(covered)

    @staticmethod
    def _comment_lines(ctx: FileContext) -> Optional[set]:
        """Lines carrying a REAL comment token — a ``disable=`` inside
        a docstring is documentation, not a suppression (and would
        read as permanently stale to the audit).  None when the file
        doesn't tokenize (fall back to treating every line as
        eligible, the pre-audit behavior)."""
        import io
        import tokenize

        try:
            return {tok.start[0] for tok in tokenize.generate_tokens(
                        io.StringIO(ctx.source).readline)
                    if tok.type == tokenize.COMMENT}
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return None

    def apply(self, findings: list[Finding]) -> list[Finding]:
        if not self.declared:
            return findings
        out = []
        for f in findings:
            lo, hi = f.stmt_span if f.stmt_span else (f.line, f.line)
            lines = set(range(lo, hi + 1)) | {f.line}
            hit = False
            for (decl, rule), covered in self.declared.items():
                if rule != f.rule and rule != "ALL":
                    continue
                if covered is None or covered & lines:
                    hit = True
                    self.used.add((decl, rule))
            out.append(replace(f, suppressed=True) if hit else f)
        return out

    def stale(self) -> list[tuple]:
        """(decl_line, rule) pairs that suppressed nothing this run."""
        return sorted(k for k in self.declared if k not in self.used)


def stale_suppressions(run_state: dict) -> list[tuple]:
    """All (path, decl_line, rule) suppression declarations in the run
    that matched no finding.  Only meaningful after a FULL run (every
    rule family over the whole tree): a subset run trivially leaves the
    other families' suppressions unmatched."""
    out = []
    for path in sorted(run_state.get("suppressions", {})):
        idx = run_state["suppressions"][path]
        out.extend((path, line, rule) for line, rule in idx.stale())
    return out


def stale_baseline_entries(findings: Iterable[Finding],
                           baseline: dict[str, int],
                           analyzed_paths: Optional[set] = None
                           ) -> list[str]:
    """Baseline fingerprints whose current unsuppressed finding count
    fell below the committed count — debt nothing produces anymore.
    ``analyzed_paths`` (the run's file set) scopes the verdict: an
    entry for an EXISTING file this run never analyzed is unjudgeable,
    not stale — a path-subset invocation must not cry wolf on the
    gate's full baseline.  An entry whose file is gone from disk stays
    judgeable everywhere (a deleted/renamed file is the classic stale
    debt)."""
    produced: dict[str, int] = {}
    for f in findings:
        if not f.suppressed:
            produced[f.fingerprint] = produced.get(f.fingerprint, 0) + 1
    out = []
    for fp, count in baseline.items():
        if analyzed_paths is not None:
            parts = fp.split("|")
            if (len(parts) > 1 and parts[1] not in analyzed_paths
                    and os.path.exists(os.path.join(REPO_ROOT,
                                                    parts[1]))):
                continue
        if produced.get(fp, 0) < count:
            out.append(fp)
    return sorted(out)


# ------------------------------------------------------------------ analysis
def canonical_path(path: str) -> str:
    """Repo-relative posix path when under the repo, else as given."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        ap = os.path.relpath(ap, REPO_ROOT)
    return ap.replace(os.sep, "/")


def default_rules() -> list[type]:
    from vllm_omni_tpu.analysis.rules import ALL_RULES

    return list(ALL_RULES)


def analyze_source(source: str, path: str,
                   rules: Optional[list[type]] = None,
                   run_state: Optional[dict] = None) -> list[Finding]:
    """Run the rule set over one in-memory source blob.  ``path`` is the
    repo-relative path the file *claims* to be at — rules scope by it
    (HOT_PATHS, protocol modules), which is what lets tests feed tiny
    fixture snippets through the real engine.  ``run_state`` is the
    cross-file dict rules with whole-run aggregates use; None (the
    default) isolates this call completely AND treats it as a complete
    one-file run (the package-wide finalize stage fires too).  Pass
    one dict across calls to emulate a multi-file run, finishing with
    ``finalize_findings`` — or use :func:`analyze_sources`."""
    path = path.replace(os.sep, "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="OL0", path=path, line=e.lineno or 1,
                        message=f"file does not parse: {e.msg}")]
    ctx = FileContext(path, source, tree)
    state = run_state if run_state is not None else {}
    # the run-wide registries package-level rules (finalize_run) and
    # the stale-suppression audit consume
    state.setdefault("files", {})[path] = ctx
    supp = state.setdefault("suppressions", {})[path] = \
        SuppressionIndex(ctx)
    active = []
    for rule_cls in (rules if rules is not None else default_rules()):
        rule = rule_cls()
        rule.run_state = state
        if rule.applies(ctx):
            active.append(rule)
    findings: list[Finding] = []
    if active:
        # THE walk: one traversal, every rule sees its node types
        for node in ast.walk(tree):
            for rule in active:
                if isinstance(node, rule.node_types):
                    findings.extend(rule.visit(node, ctx))
        for rule in active:
            findings.extend(rule.finish(ctx))
    findings.sort(key=lambda f: (f.line, f.rule, f.message))
    findings = supp.apply(findings)
    if run_state is None:
        # an isolated call IS a complete one-file run: package-wide
        # rules still fire (fixture tests feed single files through
        # the full pipeline)
        findings.extend(finalize_findings(rules, state))
    return findings


def finalize_findings(rules: Optional[list[type]],
                      run_state: dict) -> list[Finding]:
    """Run every package-wide rule's ``finalize_run`` over the
    accumulated run state and apply each finding's own file's
    suppressions.  ``analyze_paths``/``analyze_sources`` call this once
    at the end of a run; callers emulating a multi-file run through
    repeated ``analyze_source(..., run_state=state)`` calls finish with
    it explicitly."""
    out: list[Finding] = []
    for rule_cls in (rules if rules is not None else default_rules()):
        if rule_cls.finalize_run is Rule.finalize_run:
            continue
        rule = rule_cls()
        rule.run_state = run_state
        out.extend(rule.finalize_run())
    by_path = run_state.get("suppressions", {})
    applied = []
    for f in out:
        idx = by_path.get(f.path)
        applied.append(idx.apply([f])[0] if idx is not None else f)
    applied.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return applied


def iter_python_files(paths: Iterable[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def analyze_paths(paths: Iterable[str],
                  rules: Optional[list[type]] = None,
                  run_state: Optional[dict] = None) -> list[Finding]:
    findings: list[Finding] = []
    # one run = one cross-file aggregate scope; callers pass their own
    # dict to inspect run-wide registries afterwards (the CLI's
    # stale-suppression audit)
    state: dict = run_state if run_state is not None else {}
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(analyze_source(source, canonical_path(fp),
                                       rules, state))
    findings.extend(finalize_findings(rules, state))
    return findings


def analyze_sources(sources: dict[str, str],
                    rules: Optional[list[type]] = None) -> list[Finding]:
    """One complete run over in-memory {claimed path: source} blobs —
    the multi-file counterpart of ``analyze_source`` for fixture tests
    exercising cross-module flows (an OL10 source in one file reaching
    a sink in another)."""
    findings: list[Finding] = []
    state: dict = {}
    for path, source in sources.items():
        findings.extend(analyze_source(source, path, rules, state))
    findings.extend(finalize_findings(rules, state))
    return findings


# ------------------------------------------------------------------ baseline
def load_baseline(path: str = DEFAULT_BASELINE) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(findings: Iterable[Finding],
                  path: str = DEFAULT_BASELINE) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        if not f.suppressed:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    payload = {
        "comment": ("omnilint baseline: pre-existing findings the gate "
                    "tolerates. Regenerate with `python -m "
                    "vllm_omni_tpu.analysis --update-baseline <paths>` "
                    "after deliberate changes; new code must come in "
                    "clean or carry an explicit suppression."),
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return counts


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, int]) -> list[Finding]:
    """Mark the first ``baseline[fingerprint]`` unsuppressed occurrences
    of each fingerprint as baselined; anything beyond the count is NEW
    and stays unmarked (the gate fails on it)."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if not f.suppressed and remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            f = replace(f, baselined=True)
        out.append(f)
    return out


def new_findings(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed and not f.baselined]


# ------------------------------------------------------------ program graph
def own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes of one function's OWN body: descends into everything
    except nested def/class subtrees (a closure is its own analysis
    unit — it runs on its own schedule, often after the enclosing
    frame is gone) while lambdas stay in (they are inline
    expressions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def dotted_names(expr: ast.AST) -> set[str]:
    """Every dotted name readable off ``expr``: ``asm.deepstack.shape``
    contributes {"asm", "asm.deepstack", "asm.deepstack.shape"} — the
    vocabulary two expressions are compared in when asking "does the
    cache key OBSERVE this variant?" (rule OL11)."""
    out: set[str] = set()
    for node in ast.walk(expr):
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            parts.reverse()
            for i in range(1, len(parts) + 1):
                out.add(".".join(parts[:i]))
    return out


# ------------------------------------------------------- control-flow graph
#
# The path-sensitive substrate under the lifecycle families (OL12/OL13):
# a statement-level intraprocedural CFG with EXCEPTION edges.  What the
# reaching-defs pass (ProgramGraph) deliberately flattened — "which
# paths can actually execute between these two statements" — is exactly
# what resource-lifecycle checking needs: an acquire leaks precisely
# when SOME path escapes the function without its release, and the
# paths that matter most are the ones a stock linter cannot see at all,
# the implicit gotos every call inside a ``try`` carries.
#
# Modeling decisions (each one a noise/recall trade documented in
# docs/static_analysis.md):
#
# - every statement that contains a call (or ``raise``/``assert``) gets
#   an exception edge to a per-``try`` DISPATCH node fanning out to the
#   handlers, plus — unless some handler is a catch-all — onward to the
#   enclosing dispatch and ultimately the synthetic RAISE exit;
# - ``finally`` bodies are built TWICE: a normal-completion copy whose
#   continuation is the code after the try, and an exception-unwind
#   copy (marked ``cleanup``) whose continuation is the enclosing
#   exception target.  Without the split, a normal-flow path could
#   spuriously reach RAISE through the shared finally block;
# - ``with`` is try/finally with a synthetic ``withexit`` cleanup node
#   on both continuations (context managers are must-execute cleanup);
# - logging calls (``logger.*``) are modeled as non-raising: handlers
#   swallow, and counting them would put an exception edge under
#   virtually every statement in the tree;
# - loops get back edges and the visit-once search below is the
#   bounded widening: each (node, crossed-exception) state is explored
#   once, so cycles terminate and path count stays linear.


_LOG_RECEIVERS = frozenset({"logger", "logging", "log"})
_CATCH_ALL = frozenset({"Exception", "BaseException"})


def scan_calls(trees) -> Iterable[ast.Call]:
    """Calls in the given trees, skipping nested def/class/lambda
    subtrees (they run on their own schedule, like ``own_nodes``)."""
    stack = [t for t in trees if t is not None]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_log_call(call: ast.Call) -> bool:
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    base = f.value
    term = (base.attr if isinstance(base, ast.Attribute)
            else base.id if isinstance(base, ast.Name) else None)
    return term in _LOG_RECEIVERS


def _can_raise(owned) -> bool:
    """Whether the expressions a CFG node owns can raise: any
    non-logging call or ``await``.  Attribute/subscript/arithmetic
    errors are deliberately out of model (noise)."""
    stack = [t for t in owned if t is not None]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Await):
            return True
        if isinstance(node, ast.Call) and not _is_log_call(node):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _catches_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        term = (n.attr if isinstance(n, ast.Attribute)
                else n.id if isinstance(n, ast.Name) else None)
        if term in _CATCH_ALL:
            return True
    return False


class CFGNode:
    """One CFG node.  ``owned`` is the expression set the node
    evaluates (an ``if`` node owns its test, not its body — body
    statements have their own nodes); ``cleanup`` marks nodes inside
    an exception-unwind ``finally``/``with``-exit copy (must-execute
    cleanup — a release there discharges escaping obligations)."""

    __slots__ = ("kind", "stmt", "owned", "cleanup")

    def __init__(self, kind, stmt=None, owned=(), cleanup=False):
        self.kind = kind      # entry/exit/raise/stmt/dispatch/with/withexit
        self.stmt = stmt
        self.owned = tuple(owned)
        self.cleanup = cleanup

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


@dataclass(frozen=True)
class _Frame:
    """Builder context: where exceptions, returns and break/continue
    go from the current nesting."""

    exc: int            # exception continuation (dispatch/cleanup/RAISE)
    fins: tuple = ()    # enclosing finally bodies, innermost LAST
    loop: Optional[tuple] = None   # (break target, continue target,
    #                                 fin-stack depth at loop entry)


class FunctionCFG:
    """Intraprocedural CFG of one function with exception edges.
    ``succs[i]`` is ``[(dst, kind)]`` with kind "normal" or "exc"."""

    ENTRY, EXIT, RAISE = 0, 1, 2

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.nodes: list[CFGNode] = [CFGNode("entry", fn),
                                     CFGNode("exit", fn),
                                     CFGNode("raise", fn)]
        self.succs: list[list[tuple]] = [[], [], []]
        self._cleanup = 0
        self._reach: dict[int, frozenset] = {}
        entry = self._block(fn.body, self.EXIT, _Frame(exc=self.RAISE))
        self.succs[self.ENTRY].append((entry, "normal"))

    # ------------------------------------------------------------ building
    def _new(self, kind, stmt=None, owned=()) -> int:
        self.nodes.append(CFGNode(kind, stmt, owned,
                                  cleanup=self._cleanup > 0))
        self.succs.append([])
        return len(self.nodes) - 1

    def _block(self, stmts, nxt: int, fr: _Frame) -> int:
        cur = nxt
        for stmt in reversed(stmts):
            cur = self._stmt(stmt, cur, fr)
        return cur

    def _cleanup_block(self, stmts, nxt: int, fr: _Frame) -> int:
        self._cleanup += 1
        try:
            return self._block(stmts, nxt, fr)
        finally:
            self._cleanup -= 1

    def _unwind(self, fins, target: int, fr: _Frame) -> int:
        """Chain of finally copies a return/break/continue runs
        through before reaching ``target`` (innermost executes first:
        built backwards, outermost-first)."""
        cur = target
        for body in fins:               # fins holds innermost LAST
            cur = self._cleanup_block(body, cur, fr)
        return cur

    def _simple(self, stmt, nxt: int, fr: _Frame, owned=None) -> int:
        idx = self._new("stmt", stmt,
                        [stmt] if owned is None else owned)
        self.succs[idx].append((nxt, "normal"))
        if _can_raise(self.nodes[idx].owned):
            self.succs[idx].append((fr.exc, "exc"))
        return idx

    def _stmt(self, stmt, nxt: int, fr: _Frame) -> int:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return self._simple(stmt, nxt, fr,
                                owned=stmt.decorator_list)
        if isinstance(stmt, ast.Return):
            idx = self._new("stmt", stmt, [stmt.value])
            self.succs[idx].append(
                (self._unwind(fr.fins, self.EXIT, fr), "normal"))
            if _can_raise(self.nodes[idx].owned):
                self.succs[idx].append((fr.exc, "exc"))
            return idx
        if isinstance(stmt, ast.Raise):
            idx = self._new("stmt", stmt, [stmt.exc, stmt.cause])
            self.succs[idx].append((fr.exc, "exc"))
            return idx
        if isinstance(stmt, ast.Assert):
            idx = self._new("stmt", stmt, [stmt.test, stmt.msg])
            self.succs[idx].append((nxt, "normal"))
            self.succs[idx].append((fr.exc, "exc"))
            return idx
        if isinstance(stmt, (ast.Break, ast.Continue)) and fr.loop:
            brk, cont, depth = fr.loop
            target = brk if isinstance(stmt, ast.Break) else cont
            idx = self._new("stmt", stmt)
            self.succs[idx].append(
                (self._unwind(fr.fins[depth:], target, fr), "normal"))
            return idx
        if isinstance(stmt, ast.If):
            idx = self._new("stmt", stmt, [stmt.test])
            self.succs[idx].append(
                (self._block(stmt.body, nxt, fr), "normal"))
            self.succs[idx].append(
                (self._block(stmt.orelse, nxt, fr), "normal"))
            if _can_raise(self.nodes[idx].owned):
                self.succs[idx].append((fr.exc, "exc"))
            return idx
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            owned = ([stmt.test] if isinstance(stmt, ast.While)
                     else [stmt.iter])
            idx = self._new("stmt", stmt, owned)
            body_fr = replace_frame(fr, loop=(nxt, idx, len(fr.fins)))
            body = self._block(stmt.body, idx, body_fr)
            after = (self._block(stmt.orelse, nxt, fr)
                     if stmt.orelse else nxt)
            self.succs[idx].append((body, "normal"))
            self.succs[idx].append((after, "normal"))
            if _can_raise(owned):
                self.succs[idx].append((fr.exc, "exc"))
            return idx
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, nxt, fr)
        if isinstance(stmt, ast.Try) or isinstance(
                stmt, getattr(ast, "TryStar", ())):
            return self._try(stmt, nxt, fr)
        if isinstance(stmt, ast.Match):
            idx = self._new("stmt", stmt, [stmt.subject])
            matched = False
            for case in stmt.cases:
                self.succs[idx].append(
                    (self._block(case.body, nxt, fr), "normal"))
                if (isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None):
                    matched = True
            if not matched:
                self.succs[idx].append((nxt, "normal"))
            if _can_raise(self.nodes[idx].owned):
                self.succs[idx].append((fr.exc, "exc"))
            return idx
        return self._simple(stmt, nxt, fr)

    def _with(self, stmt, nxt: int, fr: _Frame) -> int:
        wexit_r = self._new("withexit", stmt)
        self.nodes[wexit_r].cleanup = True
        self.succs[wexit_r].append((fr.exc, "normal"))
        wexit_n = self._new("withexit", stmt)
        self.succs[wexit_n].append((nxt, "normal"))
        body = self._block(stmt.body, wexit_n,
                           replace_frame(fr, exc=wexit_r))
        owned = [i.context_expr for i in stmt.items]
        idx = self._new("with", stmt, owned)
        self.succs[idx].append((body, "normal"))
        if _can_raise(owned):
            self.succs[idx].append((fr.exc, "exc"))
        return idx

    def _try(self, stmt, nxt: int, fr: _Frame) -> int:
        fins = stmt.finalbody
        # normal-completion finally copy -> code after the try;
        # exception-unwind copy (cleanup) -> enclosing exception target
        after_normal = self._block(fins, nxt, fr) if fins else nxt
        f_raise = (self._cleanup_block(fins, fr.exc, fr)
                   if fins else fr.exc)
        inner_fins = fr.fins + ((fins,) if fins else ())
        fr_handler = replace_frame(fr, exc=f_raise, fins=inner_fins)
        dispatch = self._new("dispatch", stmt)
        caught_all = False
        for h in stmt.handlers:
            h_idx = self._new("stmt", h, [h.type])
            self.succs[h_idx].append(
                (self._block(h.body, after_normal, fr_handler),
                 "normal"))
            self.succs[dispatch].append((h_idx, "normal"))
            caught_all = caught_all or _catches_all(h)
        if not caught_all:
            self.succs[dispatch].append((f_raise, "normal"))
        orelse = (self._block(stmt.orelse, after_normal, fr_handler)
                  if stmt.orelse else after_normal)
        return self._block(stmt.body, orelse,
                           replace_frame(fr, exc=dispatch,
                                         fins=inner_fins))

    # ----------------------------------------------------------- querying
    def reachable(self, start: int) -> frozenset:
        """Node set reachable from ``start`` (memoized)."""
        cached = self._reach.get(start)
        if cached is not None:
            return cached
        seen = {start}
        stack = [start]
        while stack:
            for dst, _ in self.succs[stack.pop()]:
                if dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        out = frozenset(seen)
        self._reach[start] = out
        return out

    def call_sites(self) -> Iterable[tuple]:
        """(node index, call) for every call each node owns — the
        finally duplication means one source call can appear under
        several nodes, and all of them must be checked."""
        for idx, node in enumerate(self.nodes):
            for call in scan_calls(node.owned):
                yield idx, call


def replace_frame(fr: _Frame, **kw) -> _Frame:
    return replace(fr, **kw)


def cfg_leak_path(cfg: FunctionCFG, start: int, is_discharge,
                  kind: str) -> Optional[list]:
    """First witness path (node-index list, ``start`` first) of the
    given kind from ``start``'s NORMAL successors — exception edges out
    of the start node itself don't count (if the acquire raised,
    nothing was acquired).  Visit-once per (node, crossed-exception)
    state is the bounded widening: loops terminate, cost stays linear.

    - "escape": reaches the RAISE exit with no discharge node on the
      path and no discharge inside a must-execute cleanup reachable
      from any crossed exception edge (a release in a ``finally``
      discharges the unwind even when a condition guards it);
    - "swallow": crosses an exception edge whose handler side can
      reach NO discharge at all, then still reaches the normal EXIT —
      the swallowed-abort shape (the object/resource is stranded and
      the function reports success);
    - "normal": reaches EXIT along normal edges only, undischarged.
    """
    succs, nodes = cfg.succs, cfg.nodes

    def exc_side_discharged(dst: int, cleanup_only: bool) -> bool:
        return any((nodes[x].cleanup or not cleanup_only)
                   and is_discharge(x)
                   for x in cfg.reachable(dst))

    target = cfg.RAISE if kind == "escape" else cfg.EXIT
    init = [(dst, False) for dst, ek in succs[start] if ek == "normal"]
    visited = set(init)
    parent: dict[tuple, tuple] = {s: None for s in init}
    stack = list(init)
    while stack:
        state = stack.pop()
        n, crossed = state
        if n != target and is_discharge(n):
            continue
        if n == target and (kind != "swallow" or crossed):
            path = [n]
            cur = parent[state]
            while cur is not None:
                path.append(cur[0])
                cur = parent[cur]
            path.append(start)
            path.reverse()
            return path
        if n == target:
            continue
        for dst, ek in succs[n]:
            nxt_crossed = crossed
            if ek == "exc":
                if kind == "normal":
                    continue
                if exc_side_discharged(dst,
                                       cleanup_only=kind == "escape"):
                    continue
                nxt_crossed = True
            nxt = (dst, nxt_crossed)
            if nxt not in visited:
                visited.add(nxt)
                parent[nxt] = state
                stack.append(nxt)
    return None


def describe_path(cfg: FunctionCFG, path: list, kind: str) -> tuple:
    """Compress a witness path into (line, note) waypoints for the
    chain report: the acquire site, each exception crossing, and the
    escape point.  Rides ``Finding.trace`` (not the fingerprint)."""
    out = [(cfg.nodes[path[0]].line, "acquired/entered here")]
    for a, b in zip(path, path[1:]):
        if any(dst == b and ek == "exc" for dst, ek in cfg.succs[a]):
            line = cfg.nodes[a].line or out[-1][0]
            out.append((line, "exception edge leaves here"))
    last = path[-1]
    end_note = ("exception escapes the function" if kind == "escape"
                else "function exits normally — obligation dropped")
    line = 0
    for idx in reversed(path):
        if cfg.nodes[idx].line:
            line = cfg.nodes[idx].line
            break
    out.append((line, end_note))
    # dedupe consecutive same-line waypoints, bound the length
    compact: list = []
    for wp in out:
        if not compact or compact[-1] != wp:
            compact.append(wp)
    return tuple(compact[:8])


class FunctionInfo:
    """One function/method in the program graph."""

    __slots__ = ("key", "path", "qual", "node", "ctx", "name",
                 "cls_name", "is_method")

    def __init__(self, key, path, qual, node, ctx, cls_name,
                 is_method=False):
        self.key = key          # "path::Qual.Name"
        self.path = path
        self.qual = qual        # dotted def/class chain
        self.node = node
        self.ctx = ctx
        self.name = node.name   # terminal name
        self.cls_name = cls_name
        # direct class-body member (has a self/cls slot, callable only
        # through an attribute) vs a plain function or a closure — a
        # closure nested in a method keeps cls_name but IS bare-callable
        self.is_method = is_method

    def param_names(self) -> list[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])


class ProgramGraph:
    """Symbol table + cross-module call graph over every FileContext of
    one analysis run — the substrate the package-wide rule families
    (OL10 taint, OL11 recompile-hazard) resolve interprocedural flows
    on.  Generalizes the intra-module call-edge fixpoint OL7/OL8 run
    per class/file: imports are resolved to the analyzed file set, so a
    helper in another module is a graph edge, not a dead end.  Built
    lazily once per run by the first finalize-stage rule that asks
    (``ProgramGraph.ensure``)."""

    def __init__(self, files: dict[str, FileContext]):
        self.files = files
        # run_state["files"] is mutated IN PLACE by every
        # analyze_source call, so `ensure` cannot detect growth by
        # dict identity — snapshot what this graph was built over
        self._built_over = {p: id(c) for p, c in files.items()}
        self.functions: dict[str, FunctionInfo] = {}
        # (path, terminal name) -> [FunctionInfo] for same-file calls
        self._file_by_name: dict[tuple, list[FunctionInfo]] = {}
        # path -> {local binding -> dotted import target}
        self.imports: dict[str, dict[str, str]] = {}
        # dotted module -> path, for the files of THIS run
        self.module_paths: dict[str, str] = {}
        self._callers: Optional[dict] = None
        for path, ctx in files.items():
            self._index_file(path, ctx)

    @classmethod
    def ensure(cls, run_state: dict) -> "ProgramGraph":
        files = run_state.get("files", {})
        graph = run_state.get("program_graph")
        if (graph is None
                or graph._built_over != {p: id(c)
                                         for p, c in files.items()}):
            graph = cls(files)
            run_state["program_graph"] = graph
        return graph

    # ------------------------------------------------------------ indexing
    @staticmethod
    def module_name(path: str) -> str:
        mod = path[:-3] if path.endswith(".py") else path
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        return mod.replace("/", ".")

    def _index_file(self, path: str, ctx: FileContext) -> None:
        self.module_paths[self.module_name(path)] = path
        imp = self.imports.setdefault(path, {})
        pkg_parts = path.split("/")[:-1]
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imp[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    prefix = ".".join(base)
                else:
                    prefix = ""
                mod = ".".join(p for p in (prefix, node.module or "")
                               if p)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    target = f"{mod}.{alias.name}" if mod else alias.name
                    imp[alias.asname or alias.name] = target
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ctx.qualname(node)
                cls_name = None
                in_closure = False
                for anc in ctx.ancestors(node):
                    if isinstance(anc, ast.ClassDef):
                        cls_name = anc.name
                        break
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        # an enclosing def before any class: closure
                        in_closure = True
                is_method = cls_name is not None and not in_closure
                fi = FunctionInfo(f"{path}::{qual}", path, qual, node,
                                  ctx, cls_name, is_method)
                self.functions[fi.key] = fi
                self._file_by_name.setdefault(
                    (path, node.name), []).append(fi)

    # ----------------------------------------------------------- resolution
    def _key_for_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            path = self.module_paths.get(mod)
            if path is None:
                continue
            return self.functions.get(f"{path}::{'.'.join(parts[i:])}")
        return None

    def resolve_call(self, call: ast.Call,
                     ctx: FileContext) -> Optional[FunctionInfo]:
        """The FunctionInfo a call lands on, or None when the target is
        outside the analyzed file set (stdlib, jax, an instance whose
        class the graph can't see)."""
        f = call.func
        path = ctx.path
        if isinstance(f, ast.Name):
            # a bare name can never invoke a method — an unrelated
            # same-named method must not shadow an imported function
            # (closures nested in methods ARE bare-callable and stay)
            cands = [c for c in self._file_by_name.get((path, f.id), [])
                     if not c.is_method]
            if len(cands) == 1:
                return cands[0]
            dotted = self.imports.get(path, {}).get(f.id)
            if dotted:
                return self._key_for_dotted(dotted)
            return None
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                for anc in ctx.ancestors(call):
                    if isinstance(anc, ast.ClassDef):
                        cands = [
                            fi for fi in self._file_by_name.get(
                                (path, f.attr), [])
                            if fi.cls_name == anc.name]
                        if len(cands) == 1:
                            return cands[0]
                        return None
                return None
            if isinstance(base, ast.Name):
                # same-file ClassName.method (unbound call)
                cands = [fi for fi in self._file_by_name.get(
                             (path, f.attr), [])
                         if fi.cls_name == base.id]
                if len(cands) == 1:
                    return cands[0]
                dotted = self.imports.get(path, {}).get(base.id)
                if dotted:
                    return self._key_for_dotted(f"{dotted}.{f.attr}")
        return None

    def callers_of(self, key: str) -> list[tuple]:
        """(caller FunctionInfo, call node) pairs for every resolvable
        call site of ``key`` across the run.  Built once, lazily."""
        if self._callers is None:
            callers: dict[str, list] = {}
            for fi in self.functions.values():
                for node in own_nodes(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target = self.resolve_call(node, fi.ctx)
                    if target is not None:
                        callers.setdefault(target.key, []).append(
                            (fi, node))
            self._callers = callers
        return self._callers.get(key, [])

    @staticmethod
    def call_arg_for_param(call: ast.Call, fi: "FunctionInfo",
                           param: str) -> Optional[ast.AST]:
        """The argument expression a call passes for ``fi``'s named
        parameter, accounting for the implicit self/cls slot on
        ``obj.method(...)`` calls."""
        params = fi.param_names()
        decorators = {d.id for d in fi.node.decorator_list
                      if isinstance(d, ast.Name)}
        if fi.is_method and "classmethod" in decorators:
            # cls is implicit on EVERY call shape (instance, self, or
            # Cls.method(...) — the class binds it)
            params = params[1:] if params else params
        elif (fi.is_method
                and isinstance(call.func, ast.Attribute)
                and not (isinstance(call.func.value, ast.Name)
                         and call.func.value.id == fi.cls_name)
                and "staticmethod" not in decorators):
            # self is implicit on obj.method(...) — but a staticmethod
            # has no such slot, and an unbound Cls.method(obj, x) call
            # passes self EXPLICITLY, so neither may have its first
            # parameter swallowed
            params = params[1:] if params else params
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        try:
            idx = params.index(param)
        except ValueError:
            return None
        if idx < len(call.args):
            arg = call.args[idx]
            return None if isinstance(arg, ast.Starred) else arg
        return None
