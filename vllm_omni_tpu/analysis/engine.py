"""omnilint engine: one AST walk per file, dispatching to rule visitors.

The analysis layer is the JAX/TPU-aware counterpart of a stock linter:
stock tools see valid Python where this codebase sees staged-out traces,
donated buffers, host↔device sync points, and cross-process frame
protocols.  Each rule family (``rules/``) encodes one of those invisible
contracts; the engine owns everything rule-agnostic:

- parsing each file ONCE and walking its AST once, dispatching nodes to
  every applicable rule's ``visit`` (rules declare ``node_types``);
  rules that need whole-file aggregation emit from ``finish``
- suppression comments (same line or the line above a finding)::

      x = foo()  # omnilint: disable=OL2
      # omnilint: disable=OL1,OL3   (suppresses the next line)
      # omnilint: disable-file=OL4  (anywhere: suppresses the whole file)

- the committed baseline (``analysis/baseline.json``): pre-existing
  findings fingerprinted by (rule, path, symbol, message) — NOT line
  numbers, so unrelated edits don't invalidate it — with per-fingerprint
  counts.  The gate fails only on findings *beyond* the baselined count.

No jax import anywhere in this package: the CLI must run in any lane
(the same stance as scripts/check_metrics_names.py, which rule OL6
absorbed).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field, replace
from typing import Iterable, Optional

# repo root == parent of the vllm_omni_tpu package dir; fingerprints use
# paths relative to it so the baseline is stable across checkouts/cwd
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*omnilint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``fingerprint`` deliberately omits the line
    number: the baseline must survive unrelated edits above a finding."""

    rule: str      # "OL1".."OL6" ("OL0" = file failed to parse)
    path: str      # repo-relative posix path
    line: int
    message: str
    symbol: str = ""          # enclosing def/class qualname, "" = module
    suppressed: bool = False  # matched a disable comment
    baselined: bool = False   # absorbed by the committed baseline
    # line span of the enclosing statement: a suppression anywhere in it
    # applies (multi-line calls anchor findings on continuation lines)
    stmt_span: tuple = ()

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.symbol}|{self.message}"

    def render(self) -> str:
        tag = (" [suppressed]" if self.suppressed
               else " [baselined]" if self.baselined else "")
        sym = f" ({self.symbol})" if self.symbol else ""
        return (f"{self.path}:{self.line}: {self.rule}{tag} "
                f"{self.message}{sym}")


class FileContext:
    """Everything rules need about one file: source, tree, parent links,
    and qualname resolution — built once, shared by every rule."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # ------------------------------------------------------------ lineage
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        cur = node
        while not isinstance(cur, ast.stmt):
            cur = self.parents[cur]
        return cur

    def qualname(self, node: ast.AST) -> str:
        """Dotted def/class chain enclosing ``node`` ("" at module level)."""
        parts = []
        scopes = [node] if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ) else []
        scopes += [a for a in self.ancestors(node) if isinstance(
            a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))]
        for scope in scopes:
            parts.append(scope.name)
        return ".".join(reversed(parts))

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        span = (line, line)
        try:
            stmt = self.enclosing_statement(node)
            span = (stmt.lineno, stmt.end_lineno or stmt.lineno)
        except KeyError:
            pass  # synthetic/module-level anchor
        return Finding(rule=rule, path=self.path, line=line,
                       message=message, symbol=self.qualname(node),
                       stmt_span=span)


class Rule:
    """Base rule: subclasses declare ``node_types`` and yield Findings
    from ``visit`` (per matching node, one engine walk) and/or
    ``finish`` (after the walk — whole-file aggregates).  A fresh
    instance runs per file, so instance state is per-file state;
    ``run_state`` (a dict the engine threads through one analysis run —
    all files of an ``analyze_paths`` call share it, a standalone
    ``analyze_source`` gets a fresh one unless the caller passes its
    own) is where cross-FILE state lives, so one run never leaks into
    the next (rule OL8's lock-order graph rides it)."""

    id: str = ""
    name: str = ""
    node_types: tuple = ()
    run_state: Optional[dict] = None  # set by the engine per run

    def applies(self, ctx: FileContext) -> bool:
        return True

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        return ()


# --------------------------------------------------------------- suppression
def _suppressions(ctx: FileContext):
    """(file-wide rule set, {line -> rule set}).  Rule ids are
    upper-cased; ``all`` suppresses every rule."""
    file_wide: set[str] = set()
    by_line: dict[int, set[str]] = {}
    for i, line in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group("rules").split(",")}
        if m.group("file"):
            file_wide |= rules
        else:
            by_line.setdefault(i, set()).update(rules)
            # a comment-only line suppresses the next CODE line (the
            # disable may sit atop a multi-line explanation block)
            if line.strip().startswith("#"):
                j = i + 1
                while j <= len(ctx.lines) \
                        and ctx.lines[j - 1].strip().startswith("#"):
                    j += 1
                by_line.setdefault(j, set()).update(rules)
    return file_wide, by_line


def _apply_suppressions(findings: list[Finding],
                        ctx: FileContext) -> list[Finding]:
    file_wide, by_line = _suppressions(ctx)
    if not file_wide and not by_line:
        return findings
    out = []
    for f in findings:
        active = file_wide | by_line.get(f.line, set())
        lo, hi = f.stmt_span if f.stmt_span else (f.line, f.line)
        for ln in range(lo, hi + 1):
            active |= by_line.get(ln, set())
        if f.rule in active or "ALL" in active:
            f = replace(f, suppressed=True)
        out.append(f)
    return out


# ------------------------------------------------------------------ analysis
def canonical_path(path: str) -> str:
    """Repo-relative posix path when under the repo, else as given."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        ap = os.path.relpath(ap, REPO_ROOT)
    return ap.replace(os.sep, "/")


def default_rules() -> list[type]:
    from vllm_omni_tpu.analysis.rules import ALL_RULES

    return list(ALL_RULES)


def analyze_source(source: str, path: str,
                   rules: Optional[list[type]] = None,
                   run_state: Optional[dict] = None) -> list[Finding]:
    """Run the rule set over one in-memory source blob.  ``path`` is the
    repo-relative path the file *claims* to be at — rules scope by it
    (HOT_PATHS, protocol modules), which is what lets tests feed tiny
    fixture snippets through the real engine.  ``run_state`` is the
    cross-file dict rules with whole-run aggregates use; None (the
    default) isolates this call completely — pass one dict across
    calls to emulate a multi-file run."""
    path = path.replace(os.sep, "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="OL0", path=path, line=e.lineno or 1,
                        message=f"file does not parse: {e.msg}")]
    ctx = FileContext(path, source, tree)
    state = run_state if run_state is not None else {}
    active = []
    for rule_cls in (rules if rules is not None else default_rules()):
        rule = rule_cls()
        rule.run_state = state
        if rule.applies(ctx):
            active.append(rule)
    findings: list[Finding] = []
    if active:
        # THE walk: one traversal, every rule sees its node types
        for node in ast.walk(tree):
            for rule in active:
                if isinstance(node, rule.node_types):
                    findings.extend(rule.visit(node, ctx))
        for rule in active:
            findings.extend(rule.finish(ctx))
    findings.sort(key=lambda f: (f.line, f.rule, f.message))
    return _apply_suppressions(findings, ctx)


def iter_python_files(paths: Iterable[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def analyze_paths(paths: Iterable[str],
                  rules: Optional[list[type]] = None) -> list[Finding]:
    findings: list[Finding] = []
    run_state: dict = {}  # one run = one cross-file aggregate scope
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(analyze_source(source, canonical_path(fp),
                                       rules, run_state))
    return findings


# ------------------------------------------------------------------ baseline
def load_baseline(path: str = DEFAULT_BASELINE) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(findings: Iterable[Finding],
                  path: str = DEFAULT_BASELINE) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        if not f.suppressed:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    payload = {
        "comment": ("omnilint baseline: pre-existing findings the gate "
                    "tolerates. Regenerate with `python -m "
                    "vllm_omni_tpu.analysis --update-baseline <paths>` "
                    "after deliberate changes; new code must come in "
                    "clean or carry an explicit suppression."),
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return counts


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, int]) -> list[Finding]:
    """Mark the first ``baseline[fingerprint]`` unsuppressed occurrences
    of each fingerprint as baselined; anything beyond the count is NEW
    and stays unmarked (the gate fails on it)."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if not f.suppressed and remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            f = replace(f, baselined=True)
        out.append(f)
    return out


def new_findings(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed and not f.baselined]
