"""omnirace runtime validator: traced locks + live deadlock detection.

The static rules (OL7-OL9) see lock discipline the AST can prove; this
module sees the discipline the PROCESS actually exercises.  The two
validate each other: a static lock-order cycle that never manifests is
noise to triage, and a runtime inversion the AST cannot see (callbacks,
dynamic dispatch, locks passed across modules) is exactly the
once-a-week wedge the PR 8 stall watchdog exists to catch after the
fact — this module catches it before the hang, in the test suite.

Opt-in and zero-cost when off: ``traced(lock, name)`` returns ``lock``
UNCHANGED unless ``OMNI_TPU_LOCK_CHECK=1`` at wrap time, so production
paths pay nothing — no wrapper object, no per-acquire bookkeeping, not
even an attribute indirection.  The heavy threaded suites (disagg
router + chaos loadgen, resilience supervisor, introspection watchdog,
async engine) enable it via an autouse fixture and call
``assert_clean()`` at teardown.

What the wrapper records, per acquisition, into ONE process-global
graph keyed by lock *name* (``Class._attr`` — all instances of a class
share a node, the same granularity rule OL8 reasons at):

- **order edges** ``A -> B``: some thread acquired B while holding A,
  with the first-seen code site.  An acquisition that would create a
  path-reversing edge (B is already an ancestor of A) records an
  **inversion violation** naming both code paths — the two sides of a
  potential deadlock, even if this run interleaved them safely.
- **wait cycles**, live: before blocking on a contended lock the
  wrapper walks the waits-for graph (per-INSTANCE owners, so two
  instances of one class never alias); a cycle means the block would
  never return — it raises :class:`LockOrderViolation` in the acquiring
  thread instead of deadlocking the suite.  Re-entrant RLock
  acquisition is recognized and never an edge or a cycle; re-entering a
  plain ``Lock`` is reported as a self-deadlock.

``Condition`` wrappers forward ``wait``/``notify``/``notify_all`` and
mark the lock released for the duration of ``wait`` (Condition drops it
internally — holding it in the books would fabricate inversions).

See docs/debugging.md ("Lock-order checking") for how to read a
reported cycle.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Optional

__all__ = [
    "LockOrderViolation",
    "TracedLock",
    "assert_clean",
    "enabled",
    "lock_graph",
    "reset",
    "traced",
    "violations",
]


class LockOrderViolation(RuntimeError):
    """A wait-for cycle was detected at acquire time: blocking would
    deadlock.  Raised in the acquiring thread so the suite fails with
    the two code paths instead of hanging until a CI timeout."""


def enabled() -> bool:
    return os.environ.get("OMNI_TPU_LOCK_CHECK") == "1"


# ------------------------------------------------------------ global state
# The meta-lock guards every structure below.  It is, deliberately, a
# raw lock: tracing the tracer would recurse.  It is leaf-only — held
# for dict work, never while acquiring a traced lock — so it can't
# participate in any cycle it would report.
_state_lock = threading.Lock()
# (holder_name, acquired_name) -> first-seen site description
_edges: dict[tuple[str, str], str] = {}
# recorded inversion/self-deadlock reports (deduped by lock-name pair)
_violations: list[str] = []
_seen_pairs: set[frozenset] = set()
# instance-level ownership for wait-cycle detection: two instances of
# one class must never alias (hist_a held by T1 must not make T2's
# block on hist_b look like a cycle)
_owners: dict[int, int] = {}      # id(wrapper) -> owning thread ident
_wants: dict[int, "TracedLock"] = {}  # thread ident -> wrapper it blocks on

_tls = threading.local()


def _held() -> list:
    """This thread's stack of (wrapper, count) acquisitions."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _site(name: str) -> str:
    """One human line for where an acquisition happened: the innermost
    caller frame outside this module."""
    for frame in reversed(traceback.extract_stack()):
        if os.path.basename(frame.filename) == "runtime.py":
            continue
        return (f"{name} at {frame.filename}:{frame.lineno} "
                f"in {frame.name} [thread {threading.current_thread().name}]")
    return name


def _path_between(src: str, dst: str) -> Optional[list[str]]:
    """Lock-name path src -> ... -> dst through the order-edge graph
    (caller holds _state_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for (a, b) in _edges:
            if a == node and b not in seen:
                seen.add(b)
                stack.append((b, path + [b]))
    return None


class TracedLock:
    """Order-checking wrapper over Lock/RLock/Condition.

    Context-manager and ``acquire``/``release`` faces match the wrapped
    primitive; everything else (``wait``, ``notify``, ``locked``, ...)
    is delegated, with ``wait`` additionally releasing the bookkeeping
    for its duration.
    """

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    def __repr__(self) -> str:
        return f"TracedLock({self.name!r}, {self._inner!r})"

    # ------------------------------------------------------- bookkeeping
    def _note_acquired(self, reentrant: bool) -> None:
        me = threading.get_ident()
        stack = _held()
        if reentrant:
            for entry in stack:
                if entry[0] is self:
                    entry[1] += 1
                    return
        with _state_lock:
            _owners[id(self)] = me
            for wrapper, _count in stack:
                held_name = wrapper.name
                if held_name == self.name:
                    continue
                pair = (held_name, self.name)
                if pair not in _edges:
                    # inversion: acquiring B under A when the graph
                    # already shows a path B -> ... -> A
                    rev = _path_between(self.name, held_name)
                    if rev is not None:
                        key = frozenset((held_name, self.name))
                        if key not in _seen_pairs:
                            _seen_pairs.add(key)
                            first = _edges.get((rev[0], rev[1]), "?")
                            _violations.append(
                                "lock-order inversion: "
                                f"{held_name} -> {self.name} "
                                f"({_site(self.name)}) vs existing "
                                f"{' -> '.join(rev)} (first seen: "
                                f"{first})")
                    _edges[pair] = _site(self.name)
        stack.append([self, 1])

    def _note_released(self) -> bool:
        """True when this thread's bookkeeping actually dropped a
        recorded acquisition (False: release of a lock never acquired
        through the wrapper — ignored)."""
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                stack[i][1] -= 1
                if stack[i][1] == 0:
                    del stack[i]
                    with _state_lock:
                        _owners.pop(id(self), None)
                return True
        return False

    def _held_by_me(self) -> bool:
        return any(entry[0] is self for entry in _held())

    def _check_wait_cycle(self) -> None:
        """Caller is about to block on self: walk waits-for (me wants
        self; self's owner wants X; X's owner wants ...).  Raises
        instead of letting the suite hang."""
        me = threading.get_ident()
        with _state_lock:
            chain = [self]
            seen_threads = {me}
            cur = self
            while True:
                owner = _owners.get(id(cur))
                if owner is None:
                    return
                if owner in seen_threads:
                    names = " -> ".join(w.name for w in chain)
                    report = ("deadlock (wait cycle): thread "
                              f"{threading.current_thread().name} "
                              f"blocking on {self.name} closes the "
                              f"cycle [{names}]; {_site(self.name)}")
                    _violations.append(report)
                    raise LockOrderViolation(report)
                seen_threads.add(owner)
                nxt = _wants.get(owner)
                if nxt is None:
                    return
                chain.append(nxt)
                cur = nxt

    # ---------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._held_by_me():
            # re-entrant path: RLock grants immediately; a plain Lock
            # would block on itself forever — report it instead.  A
            # NON-blocking probe on an already-held plain Lock is legal
            # (it cannot deadlock) and must return False like the raw
            # primitive, not raise.
            got = self._inner.acquire(blocking=False)
            if not got:
                if not blocking:
                    return False
                report = ("self-deadlock: re-acquiring non-reentrant "
                          f"lock {self.name}; {_site(self.name)}")
                with _state_lock:
                    _violations.append(report)
                raise LockOrderViolation(report)
            self._note_acquired(reentrant=True)
            return True
        got = self._inner.acquire(blocking=False)
        if not got:
            if not blocking:
                return False
            me = threading.get_ident()
            with _state_lock:
                _wants[me] = self
            try:
                self._check_wait_cycle()
                if timeout is not None and timeout >= 0:
                    got = self._inner.acquire(True, timeout)
                else:
                    got = self._inner.acquire()
            finally:
                with _state_lock:
                    _wants.pop(me, None)
            if not got:
                return False
        self._note_acquired(reentrant=False)
        return True

    def release(self) -> None:
        # bookkeeping BEFORE the inner release: releasing first would
        # let a woken contender record its new ownership, which our
        # late _note_released() would then erase — blinding the
        # wait-cycle walk for the contender's whole hold.  The reverse
        # window (books cleared while we still hold for an instant) can
        # only make a cycle check miss a lock whose release is already
        # in progress — a cycle that is resolving itself.
        noted = self._note_released()
        try:
            self._inner.release()
        except BaseException:
            if noted:
                # inner refused (e.g. not owned): restore the books
                self._note_acquired(reentrant=False)
            raise

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # ------------------------------------------- Condition delegation
    def wait(self, timeout: Optional[float] = None):
        # Condition.wait releases the underlying lock for the duration;
        # mirror that in the books or every lock acquired by OTHER
        # threads while we sleep would look like it nests under ours.
        # Restore ONLY what was dropped: wait() on an un-held condition
        # raises from inner.wait, and re-acquiring books we never held
        # would corrupt this thread's stack for the whole session.
        noted = self._note_released()
        try:
            return self._inner.wait(timeout)
        finally:
            if noted:
                self._note_acquired(reentrant=False)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        noted = self._note_released()
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            if noted:
                self._note_acquired(reentrant=False)

    def __getattr__(self, attr):
        # notify/notify_all/locked/... pass straight through
        return getattr(self._inner, attr)


def traced(lock, name: str):
    """Wrap ``lock`` for order checking — or return it untouched when
    ``OMNI_TPU_LOCK_CHECK`` is off (the zero-overhead contract: the
    decision is made once, at creation, not per acquire).

    ``name`` should be ``Class._attr`` (or ``module._attr``): it is the
    graph-node identity, deliberately shared by all instances of a
    class so the order relation is about code paths, not objects.
    """
    if not enabled():
        return lock
    return TracedLock(lock, name)


# -------------------------------------------------------------- inspection
def violations() -> list[str]:
    with _state_lock:
        return list(_violations)


def lock_graph() -> dict[str, list[str]]:
    """Adjacency view of the observed acquisition order (debug aid)."""
    out: dict[str, list[str]] = {}
    with _state_lock:
        for (a, b) in sorted(_edges):
            out.setdefault(a, []).append(b)
    return out


def reset() -> None:
    """Clear all recorded state (test isolation; per-thread held stacks
    clear themselves as locks release)."""
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _seen_pairs.clear()
        _owners.clear()
        _wants.clear()


def assert_clean(do_reset: bool = True) -> None:
    """Raise AssertionError listing every recorded violation (suite
    teardown contract).  Resets afterwards by default so one poisoned
    test doesn't fail the rest of the session."""
    found = violations()
    if do_reset:
        reset()
    if found:
        raise AssertionError(
            "lock-order violations recorded "
            f"({len(found)}):\n" + "\n".join(f"  - {v}" for v in found))
