"""omnilint CLI: ``python -m vllm_omni_tpu.analysis [opts] paths...``

Exit codes: 0 = clean against the committed baseline, 1 = NEW findings
(or OL0 parse failures), 2 = usage error.  ``--update-baseline`` is the
escape hatch for deliberate changes: it rewrites
``analysis/baseline.json`` from the current findings and exits 0 —
review the diff it produces like any other code change.
"""

from __future__ import annotations

import argparse
import json
import sys

from vllm_omni_tpu.analysis.engine import (
    DEFAULT_BASELINE,
    analyze_paths,
    apply_baseline,
    load_baseline,
    new_findings,
    save_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vllm_omni_tpu.analysis",
        description="omnilint: JAX/TPU-aware static analysis "
                    "(rules OL1-OL9; see docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=["vllm_omni_tpu"],
                        help="files/directories to analyze "
                             "(default: vllm_omni_tpu)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file (default: the committed "
                             "analysis/baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding as new (audit mode)")
    parser.add_argument("--show-all", action="store_true",
                        help="also print suppressed/baselined findings")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (e.g. "
                             "OL7,OL8,OL9 — scripts/racecheck.sh's "
                             "concurrency-only gate); default: all")
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        if args.update_baseline:
            # a baseline regenerated from a rule subset would silently
            # drop every other family's entries
            parser.error("--rules cannot be combined with "
                         "--update-baseline (the baseline covers every "
                         "family)")
        from vllm_omni_tpu.analysis.rules import ALL_RULES

        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in ALL_RULES if r.id in wanted]
        unknown = wanted - {r.id for r in rules}
        if unknown:
            parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))}")

    findings = analyze_paths(args.paths, rules)
    if args.update_baseline:
        counts = save_baseline(findings, args.baseline)
        print(f"baseline updated: {sum(counts.values())} finding(s) "
              f"across {len(counts)} fingerprint(s) -> {args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    findings = apply_baseline(findings, baseline)
    new = new_findings(findings)

    if args.format == "json":
        payload = [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "symbol": f.symbol, "message": f.message,
             "suppressed": f.suppressed, "baselined": f.baselined,
             "new": not (f.suppressed or f.baselined)}
            for f in findings
            if args.show_all or not (f.suppressed or f.baselined)
        ]
        json.dump({"findings": payload, "new": len(new)},
                  sys.stdout, indent=1)
        print()
    else:
        shown = findings if args.show_all else new
        for f in shown:
            print(f.render())
        n_supp = sum(f.suppressed for f in findings)
        n_base = sum(f.baselined for f in findings)
        print(f"omnilint: {len(new)} new finding(s) "
              f"({n_base} baselined, {n_supp} suppressed)",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
