"""omnilint CLI: ``python -m vllm_omni_tpu.analysis [opts] paths...``

Exit codes: 0 = clean against the committed baseline, 1 = NEW findings
(or OL0 parse failures, or stale suppressions under
``--report-stale-suppressions`` / ``--stale-audit``), 2 = usage error /
broken manifest.
``--update-baseline`` is the escape hatch for deliberate changes: it
rewrites ``analysis/baseline.json`` from the current findings and
exits 0 — review the diff it produces like any other code change.

The path manifests (``analysis/manifest.py``) are validated before any
analysis: a renamed module/class must fail the run loudly instead of
silently un-linting whatever its entry used to cover.
"""

from __future__ import annotations

import argparse
import json
import sys

from vllm_omni_tpu.analysis.engine import (
    DEFAULT_BASELINE,
    analyze_paths,
    apply_baseline,
    load_baseline,
    new_findings,
    save_baseline,
    stale_baseline_entries,
    stale_suppressions,
)


def _print_stale(stale, stale_base, dest) -> None:
    """One report shape for both audit modes — detail lines to
    ``dest``, the summary always to stderr."""
    for path, line, rule in stale:
        print(f"{path}:{line}: stale suppression: disable={rule} "
              "matches no finding — remove it (or the contract it "
              "documented no longer holds)", file=dest)
    for fp in stale_base:
        print(f"stale baseline entry: {fp}", file=dest)
    print(f"omnilint: {len(stale)} stale suppression(s), "
          f"{len(stale_base)} stale baseline entr(ies)",
          file=sys.stderr)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vllm_omni_tpu.analysis",
        description="omnilint: JAX/TPU-aware static analysis "
                    "(rules OL1-OL13; see docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=["vllm_omni_tpu"],
                        help="files/directories to analyze "
                             "(default: vllm_omni_tpu)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--sarif-out", default=None, metavar="PATH",
                        help="also write a SARIF 2.1.0 document of the "
                             "NEW findings to PATH (scripts/omnilint.sh "
                             "wires OMNI_LINT_SARIF=path to this)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file (default: the committed "
                             "analysis/baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding as new (audit mode)")
    parser.add_argument("--show-all", action="store_true",
                        help="also print suppressed/baselined findings")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (e.g. "
                             "OL7,OL8,OL9 — scripts/racecheck.sh's "
                             "concurrency-only gate; OL10,OL11 — the "
                             "omniflow families; OL12,OL13 — the "
                             "omnileak lifecycle families); "
                             "default: all")
    parser.add_argument("--report-stale-suppressions", action="store_true",
                        help="audit mode: list `# omnilint: disable` "
                             "comments that no longer suppress any "
                             "finding and baseline entries nothing "
                             "produces; exit 1 if any exist")
    parser.add_argument("--stale-audit", action="store_true",
                        help="run the normal gate AND the stale-"
                             "suppression audit over the same analysis "
                             "pass (scripts/omnilint.sh uses this so "
                             "the gate analyzes once, not twice); exit "
                             "1 on new findings OR stale entries")
    args = parser.parse_args(argv)

    # a broken manifest must fail LOUDLY before any analysis claims
    # cleanliness with half its scope silently gone
    from vllm_omni_tpu.analysis.manifest import (
        ManifestError,
        validate_manifest,
    )

    try:
        validate_manifest()
    except ManifestError as e:
        parser.exit(2, f"{e}\n")

    rules = None
    if args.rules:
        if args.update_baseline:
            # a baseline regenerated from a rule subset would silently
            # drop every other family's entries
            parser.error("--rules cannot be combined with "
                         "--update-baseline (the baseline covers every "
                         "family)")
        if args.report_stale_suppressions or args.stale_audit:
            # a subset run trivially leaves every other family's
            # suppressions unmatched — the audit would cry wolf
            parser.error("--rules cannot be combined with "
                         "--report-stale-suppressions/--stale-audit "
                         "(staleness is only meaningful for a "
                         "full-family run)")
        from vllm_omni_tpu.analysis.rules import ALL_RULES

        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in ALL_RULES if r.id in wanted]
        unknown = wanted - {r.id for r in rules}
        if unknown:
            parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))}")

    run_state: dict = {}
    findings = analyze_paths(args.paths, rules, run_state)
    analyzed = set(run_state.get("files", ()))
    if args.update_baseline:
        counts = save_baseline(findings, args.baseline)
        print(f"baseline updated: {sum(counts.values())} finding(s) "
              f"across {len(counts)} fingerprint(s) -> {args.baseline}")
        if args.sarif_out:
            # a requested artifact must not silently vanish; against
            # the just-written baseline every finding is accepted debt
            from vllm_omni_tpu.analysis.sarif import write_sarif

            write_sarif(apply_baseline(findings,
                                       load_baseline(args.baseline)),
                        args.sarif_out)
        return 0

    if args.report_stale_suppressions:
        stale = stale_suppressions(run_state)
        stale_base = stale_baseline_entries(
            findings, load_baseline(args.baseline), analyzed)
        if args.sarif_out:
            # a requested artifact must not silently vanish because
            # the run happened to be an audit-mode invocation
            from vllm_omni_tpu.analysis.sarif import write_sarif

            write_sarif(apply_baseline(
                findings,
                {} if args.no_baseline else load_baseline(args.baseline)),
                args.sarif_out)
        _print_stale(stale, stale_base, sys.stdout)
        return 1 if (stale or stale_base) else 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    # the combined gate audits the SAME analysis pass the gate judges
    # (same paths, same baseline) instead of re-running everything
    stale: list = []
    stale_base: list = []
    if args.stale_audit:
        stale = stale_suppressions(run_state)
        stale_base = stale_baseline_entries(findings, baseline, analyzed)
    findings = apply_baseline(findings, baseline)
    new = new_findings(findings)

    if args.sarif_out or args.format == "sarif":
        from vllm_omni_tpu.analysis.sarif import to_sarif, write_sarif

        doc = (write_sarif(findings, args.sarif_out) if args.sarif_out
               else to_sarif(findings))
        if args.format == "sarif":
            json.dump(doc, sys.stdout, indent=1)
            print()
    if args.format == "json":
        payload = [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "symbol": f.symbol, "message": f.message,
             "suppressed": f.suppressed, "baselined": f.baselined,
             "new": not (f.suppressed or f.baselined)}
            for f in findings
            if args.show_all or not (f.suppressed or f.baselined)
        ]
        doc = {"findings": payload, "new": len(new)}
        if args.stale_audit:
            # the machine-readable document must record WHY a failing
            # exit code fired, not just the finding count
            doc["stale_suppressions"] = [
                {"path": p, "line": ln, "rule": r}
                for p, ln, r in stale]
            doc["stale_baseline_entries"] = list(stale_base)
        json.dump(doc, sys.stdout, indent=1)
        print()
    elif args.format == "text":
        shown = findings if args.show_all else new
        for f in shown:
            print(f.render())
        n_supp = sum(f.suppressed for f in findings)
        n_base = sum(f.baselined for f in findings)
        print(f"omnilint: {len(new)} new finding(s) "
              f"({n_base} baselined, {n_supp} suppressed)",
              file=sys.stderr)
    if args.stale_audit:
        # stdout carries the machine-readable document under
        # --format json/sarif — audit detail must not corrupt it
        _print_stale(stale, stale_base,
                     sys.stdout if args.format == "text" else sys.stderr)
    return 1 if (new or stale or stale_base) else 0


if __name__ == "__main__":
    sys.exit(main())
