"""Path manifests scoping the TPU-specific rules.

HOT_PATHS: modules on the serving hot path, where an accidental
host↔device sync (rule OL2) stalls every in-flight request — the
scheduler/runner/engine step loop and the kernels under it.  Cold
surfaces (entrypoints, config, model loaders) legitimately sync and are
not listed.

PROTOCOL_MODULES: files implementing a cross-process frame protocol
(rule OL5 checks every sent frame type has a receiver handler and that
span payloads are re-stamped on the other side).

BENCH_PATHS: measurement code where wall-clock timing without
``block_until_ready`` measures dispatch (enqueue) instead of execution
(rule OL4).

METRIC_MODULES: the Prometheus registry files rule OL6 (the absorbed
scripts/check_metrics_names.py drift guard) validates.
"""

from __future__ import annotations

HOT_PATHS: tuple[str, ...] = (
    "vllm_omni_tpu/core/",
    # kvcache tier moves run between schedule() and execute() on the
    # engine thread — a stray per-page host sync in the offload path
    # multiplies by every payload parked that step (the batched
    # extract/inject discipline of docs/kv_cache.md)
    "vllm_omni_tpu/kvcache/",
    "vllm_omni_tpu/ops/",
    # the ragged unified kernel is covered by the ops/ prefix above;
    # listed explicitly because a stray host sync inside the ONE
    # dispatch serving a whole mixed step stalls every request at once
    "vllm_omni_tpu/ops/ragged_paged_attention.py",
    # the shared KV quantizer is covered by the kvcache/ prefix above;
    # listed explicitly because its helpers run inside the KV-write
    # path of EVERY forward and inside the tier drain — a host sync in
    # quantize/dequantize would serialize each step on each payload
    "vllm_omni_tpu/kvcache/quant.py",
    "vllm_omni_tpu/sample/",
    "vllm_omni_tpu/worker/",
    "vllm_omni_tpu/engine/",
    # the open-loop load harness: no jax today, but a stray host sync
    # creeping into a driver would serialize the very concurrency the
    # harness exists to measure — linted from day one
    "vllm_omni_tpu/loadgen/",
    # introspection: the flight recorder appends INSIDE the engine
    # step loop and the watchdog/debugz probes read live engine state
    # from other threads — a stray device sync in either would stall
    # serving exactly while an operator is debugging it
    "vllm_omni_tpu/introspection/",
    # disaggregated serving: the router steps every replica engine on
    # ONE thread — a stray device sync in the routing/handoff logic
    # would stall all tiers at once (payloads are host numpy by the
    # time they reach this layer; keep it that way)
    "vllm_omni_tpu/disagg/",
    # control plane: actuation runs BETWEEN router steps on the engine
    # thread, and the sensor tick reads live engine state from the
    # controller thread — a stray device sync in either stalls all
    # replicas at once (or serializes serving behind a poll)
    "vllm_omni_tpu/controlplane/",
    # journey tracing + live roofline: both record INSIDE the router/
    # engine step loops (spans per dispatch/handoff/retire, MFU/MBU per
    # step) — the whole design is host-ints-only, and a stray device
    # sync here would stall serving exactly in proportion to how
    # observable it is
    "vllm_omni_tpu/tracing/",
    "vllm_omni_tpu/metrics/roofline.py",
    # omnipulse: the attribution sketch is fed from the engine step
    # loop (token/page·second/shed meters per request event) and the
    # alert probes read live engine state from the evaluation thread —
    # host dict/heap arithmetic only; a device sync in either stalls
    # serving in proportion to how observable it is
    "vllm_omni_tpu/metrics/attribution.py",
    "vllm_omni_tpu/metrics/alerts.py",
    # omniscope: dispatch-regret scoring runs inside the router's
    # dispatch path and digest folding inside its step loop — pure
    # dict/set arithmetic over already-exported digests; a device sync
    # here would stall every tier at once
    "vllm_omni_tpu/metrics/cache_economics.py",
)

PROTOCOL_MODULES: tuple[str, ...] = (
    # speaks submit/abort/outputs/fatal/profile_*/shutdown/bye plus the
    # resilience PR's ping/pong heartbeat frames (sender AND handler
    # both live here, so OL5 can check the pairing statically)
    "vllm_omni_tpu/entrypoints/stage_proc.py",
    # drives restarts/redelivery over those frames; constructs no frame
    # literals itself today — listed so any future frame it grows is
    # linted from day one
    "vllm_omni_tpu/resilience/supervisor.py",
    # the disagg handoff protocol (meta + per-shard layer streams) and
    # the router consuming replica health answers — no frame literals
    # today (payloads ride connector keys), listed so any future wire
    # frames are linted from day one
    "vllm_omni_tpu/disagg/roles.py",
    "vllm_omni_tpu/disagg/router.py",
)

BENCH_PATHS: tuple[str, ...] = (
    "bench.py",
    "vllm_omni_tpu/benchmarks/",
    "vllm_omni_tpu/metrics/",
    "tests/benchmarks/",
    # async pipelined step: the engine's dispatch/retire halves and the
    # runner's dispatch_decode/retire_decode time host vs. device phases
    # for the overlap metrics — OL4 watches that any wall-clock pair
    # around a jax dispatch in them syncs (or says why it must not).
    # model_runner.py also carries the unified ragged dispatch
    # (_run_unified/dispatch_unified) and the compile-telemetry timing
    # in _run_jit, whose fresh-compile branch must block_until_ready
    # before stopping the clock
    "vllm_omni_tpu/engine/llm_engine.py",
    "vllm_omni_tpu/worker/model_runner.py",
    # the open-loop runner times around async dispatch (arrival ->
    # first output -> completion across asyncio tasks / HTTP threads);
    # OL4 watches that any wall-clock pair it grows around a jax
    # dispatch syncs first — today its durations are client-observed
    # network/queue round trips, which is the product being measured
    "vllm_omni_tpu/loadgen/",
)

METRIC_MODULES: tuple[str, ...] = (
    "vllm_omni_tpu/metrics/prometheus.py",
    # alert gauges/transition counters and attribution series render
    # through METRIC_SPECS like everything else; listed so any future
    # spec table grown in these modules rides the OL6 drift guard
    "vllm_omni_tpu/metrics/alerts.py",
    "vllm_omni_tpu/metrics/attribution.py",
    # omniscope fleet cache series (fleet_prefix_hit_tokens_total &
    # co.) render from the router's exposition block through the same
    # spec table
    "vllm_omni_tpu/metrics/cache_economics.py",
)

# --------------------------------------------------------------- omnirace
# THREADED_PATHS: modules with real cross-thread locking that are NOT on
# the serving hot path — rule OL9 (blocking-under-lock) covers
# HOT_PATHS ∪ THREADED_PATHS.  A blocking call under a lock here won't
# stall a device step directly, but it convoys every thread that needs
# the lock (heartbeats, /metrics, intake) behind one slow operation.
THREADED_PATHS: tuple[str, ...] = (
    # supervisor heartbeat/restart threads + fault injector
    "vllm_omni_tpu/resilience/",
    # connector cv-protected stores, the TCP KV server's per-connection
    # threads, and the client's one-socket mutex
    "vllm_omni_tpu/distributed/",
    # histograms observed by the engine thread, snapshotted by /metrics
    "vllm_omni_tpu/metrics/",
    # span ring shared by every stage thread + the drain/export path
    "vllm_omni_tpu/tracing/",
    # native shm ring op lock
    "vllm_omni_tpu/native/",
    # the async orchestrator's pause gate + engine loop
    "vllm_omni_tpu/entrypoints/async_omni.py",
    # the stage channel's send mutex (submit thread vs profile RPC)
    "vllm_omni_tpu/entrypoints/stage_proc.py",
    "vllm_omni_tpu/entrypoints/openai/api_server.py",
    # closed-loop bench workers share a result lock
    "vllm_omni_tpu/benchmarks/",
    # the lock tracer itself: its meta-lock must stay leaf-only
    "vllm_omni_tpu/analysis/runtime.py",
    # controller thread emits intents; the router thread actuates —
    # the intent/ring lock convoys both if anything blocks under it
    "vllm_omni_tpu/controlplane/",
)

# LOCK_GUARDS: the concurrency manifest rule OL7 (lock-discipline)
# enforces.  Per class (keyed "path::ClassName"), which attributes are
# guarded by which lock attribute: every read/write of a guarded
# attribute must happen under `with self.<lock>` — directly, or in a
# private helper whose every same-class call site holds the lock
# (__init__/__del__ are exempt: construction and teardown are
# single-threaded by contract).  Lock attribute names must follow the
# *lock/*cv/*cond naming convention (rules/_lockinfo.py) so the `with`
# scopes are recognizable.
#
# Declare the invariant that is TRUE and must stay true — the manifest
# is documentation the linter enforces, not aspiration.  Deliberately
# unguarded attributes (GIL-atomic monitoring reads) are simply not
# listed, or the access carries a reasoned OL7 suppression.
LOCK_GUARDS: dict[str, dict[str, tuple[str, ...]]] = {
    # engine thread observes while the /metrics HTTP thread snapshots
    "vllm_omni_tpu/metrics/stats.py::Histogram": {
        "_lock": ("_counts", "_sum", "_count", "_window"),
    },
    # every subsystem counts events here from its own thread
    "vllm_omni_tpu/resilience/metrics.py::ResilienceMetrics": {
        "_lock": ("_counters", "_gauges"),
    },
    # orchestrator thread (submit/poll) vs heartbeat + restart threads
    "vllm_omni_tpu/resilience/supervisor.py::StageSupervisor": {
        "_lock": ("_tracked", "_redelivered", "_failed_outs",
                  "_restarts", "_restarting", "_dead", "_closed"),
    },
    # chaos sites fire from every replica/stage thread
    "vllm_omni_tpu/resilience/faults.py::FaultInjector": {
        "_lock": ("_steps", "_rngs"),
    },
    # engine step appends; /debug + crash hooks snapshot from anywhere
    "vllm_omni_tpu/introspection/flight_recorder.py::FlightRecorder": {
        "_lock": ("_ring", "_seq", "_dropped", "_last_mono",
                  "_last_wall"),
    },
    "vllm_omni_tpu/introspection/memory_ledger.py::DeviceMemoryLedger": {
        "_lock": ("_peaks", "_peak_total", "_last"),
    },
    # monitor thread mutates source states; /debug reads them
    "vllm_omni_tpu/introspection/watchdog.py::StallWatchdog": {
        "_lock": ("_sources",),
    },
    # per-connection server threads share the one object table
    "vllm_omni_tpu/distributed/tcp.py::KVStoreServer": {
        "_cv": ("_store",),
    },
    # one persistent socket, many caller threads
    "vllm_omni_tpu/distributed/tcp.py::TCPConnector": {
        "_lock": ("_sock",),
    },
    # per-namespace store shared by every same-namespace instance
    "vllm_omni_tpu/distributed/connectors.py::InProcConnector": {
        "_cv": ("_store",),
    },
    # every stage thread records; the writer drains
    "vllm_omni_tpu/tracing/trace.py::TraceRecorder": {
        "_lock": ("_spans", "_dropped"),
    },
    "vllm_omni_tpu/tracing/trace.py::TraceWriter": {
        "_lock": ("_spans", "_chrome_dropped", "_last_export_ts"),
    },
    # engine thread accounts steps; /metrics + /debug threads snapshot
    "vllm_omni_tpu/metrics/roofline.py::RooflineTracker": {
        "_lock": ("_window", "_flops_total", "_bytes_total"),
    },
    # controller thread emits intents + reads the ring; the router
    # thread drains intents, records outcomes, and bumps the applied-
    # action counters.  The state-machine fields (_op, _warming,
    # hysteresis) are deliberately NOT listed: they are controller-
    # thread-private by contract (actuate() only touches the guarded
    # attributes below)
    "vllm_omni_tpu/controlplane/controller.py::ControlPlane": {
        "_lock": ("_pending", "_done", "_ring", "_seq", "actions"),
    },
    # evaluation thread and force_firing (the watchdog thread) both
    # step the per-rule lifecycle — every state WRITE happens under
    # the lock (serialized check+set, so the two can't double-land a
    # firing edge); /debug/alerts, /health, and the control plane's
    # advisory READ the per-rule scalars lock-free in the watchdog's
    # GIL-atomic monitoring-read stance, so they're not listed
    "vllm_omni_tpu/metrics/alerts.py::AlertEngine": {
        "_lock": ("_rules", "_transitions"),
    },
    # (TenantAttribution's _meters dict is immutable post-__init__ —
    # the lock guards the SKETCH CONTENTS, which OL7's attribute
    # granularity can't express; its mutation sites all hold _lock)
    # any thread may dump (crash hooks, alert evidence, SIGUSR2)
    "vllm_omni_tpu/introspection/flight_recorder.py::DumpCooldown": {
        "_lock": ("_last", "_suppressed"),
    },
    # the router thread folds digests + scores dispatches while
    # /metrics and /debug/cache snapshot from HTTP threads and the
    # alert probe reads from the evaluation thread
    "vllm_omni_tpu/metrics/cache_economics.py::CacheEconomics": {
        "_lock": ("_digests", "_cover", "_last", "_fleet_hit_tokens",
                  "_fleet_prefill_tokens", "_dup_by_reason",
                  "_pending", "_ledger", "_dispatches"),
    },
}


# --------------------------------------------------------------- omniflow
# The OL10 hostile-input-taint manifest: which expressions produce
# attacker-controlled values (TAINT_SOURCES), which calls launder them
# into safe values (SANITIZERS), and which calls/operations must never
# see them raw (TAINT_SINKS).  The rule (rules/taint_flow.py) flags
# every source->sink dataflow that crosses no sanitizer — the bug class
# of the PR 7 unsanitized tenant label (unbounded /metrics cardinality
# + label injection) and the PR 12 float("inf") priority crash.

TAINT_SOURCES: dict[str, tuple[str, ...]] = {
    # hostile HTTP headers read off the OpenAI server's request object:
    # `headers.get("x-omni-tenant")` / `headers["x-omni-tenant"]`
    "headers": ("x-omni-tenant", "x-omni-priority", "traceparent",
                "x-omni-trace-id"),
    # raw (pre-sanitizer) client metadata: EVERY read of these
    # attributes is hostile until a sanitizer touches it — the
    # Request.tenant/priority properties exist precisely to be the one
    # blessed crossing
    "attrs": ("additional_information",),
    # cross-host payload metadata off a connector edge: a torn or
    # hostile remote store controls every field of the `{key}/meta`
    # header (num_layers/shape/dtype/crc32)
    "meta_suffixes": ("/meta",),
    # key-prefix carve-out: `additional_information` doubles as the
    # engine's internal scratch namespace, and internal keys are
    # underscore-prefixed by convention ("_parked_len",
    # "_hidden_chunks") — reads of those are engine-written state, not
    # client input
    "internal_key_prefixes": ("_",),
}

# terminal function name -> defining file (the drift guard checks the
# def still exists there; matching in the rule is by terminal name so
# fixture files exercise the same manifest)
SANITIZERS: dict[str, str] = {
    "sanitize_tenant": "vllm_omni_tpu/metrics/stats.py",
    "sanitize_priority": "vllm_omni_tpu/metrics/stats.py",
    "inbound_trace_id": "vllm_omni_tpu/tracing/journey.py",
    "parse_traceparent": "vllm_omni_tpu/tracing/journey.py",
    "_escape_label_value": "vllm_omni_tpu/metrics/prometheus.py",
}

TAINT_SINKS: dict[str, tuple[str, ...]] = {
    # metric label dicts: a raw tenant here is unbounded series
    # cardinality + Prometheus exposition injection
    "metric_labels": ("_fmt_labels", "cap_tenant"),
    # log calls: raw client bytes in a log line are log injection (and
    # an f-string renders them before any later escaping could help)
    "log_receivers": ("logger", "logging", "log"),
    # filesystem paths: a client-controlled path component is traversal
    "fs_calls": ("open", "os.replace", "os.rename", "os.remove",
                 "os.unlink", "os.makedirs", "os.path.join"),
    # scheduler arithmetic (WFQ quantum weights): an unclamped client
    # number in admission math is the float("inf") crash class — scoped
    # to the scheduler so ordinary string plumbing stays quiet
    "sched_arith_paths": ("vllm_omni_tpu/core/scheduler.py",),
}

# --------------------------------------------------------------- recompile
# The OL11 recompile-hazard manifest: every `_run_jit(kind, shape_key,
# thunk)` dispatch must build its shape key from BUCKETED values or
# static config — a per-request int in the key (or in a jitted dummy
# array's shape) compiles one executable per distinct value, which is
# the mid-traffic 20-40 s XLA stall warmup exists to prevent.  The
# rule (rules/recompile_hazard.py) also checks every conditional
# argument variant at the dispatch site is observable in the key (the
# PR 11 `n_deep` bug class) and every dispatched `kind` is reachable
# from the warmup walker.
RECOMPILE: dict[str, tuple[str, ...]] = {
    # the jit telemetry choke points — every dispatch goes through one
    "dispatch_fns": ("_run_jit",),
    # calls that BUCKET a raw count (their result is shape-safe even
    # when fed per-request ints)
    "bucket_fns": ("_bucket", "_make_buckets", "_decode_bucket",
                   "_bucketed_prefill_shapes", "auto_blocks",
                   "auto_ragged_blocks"),
    # attributes holding precomputed bucket tables / static tile picks
    # (and the resident-KV layout flag: one of exactly two executable
    # families per kind — int8-quantized caches are a different pytree,
    # so the flag MUST ride every dispatch key, threaded through the
    # warmup walker so both layouts compile before traffic)
    "bucket_attrs": ("_token_buckets", "_batch_buckets", "_seq_buckets",
                     "_token_block", "_dma_slots", "_kv_quant"),
    # attribute reads that ARE per-request counts
    "per_request_attrs": ("num_new_tokens", "num_tokens",
                          "num_computed_tokens", "num_inflight_tokens",
                          "num_prompt_tokens"),
    # the warmup bucket walkers: kinds dispatched outside these must be
    # warmed inside them
    "warmup_funcs": ("precompile",),
    # jax array constructors whose literal shape tuples the rule scans
    "array_ctors": ("zeros", "ones", "full", "empty"),
}


# ---------------------------------------------------------------- omnileak
# The OL12 resource-lifecycle manifest: acquire->release pairs whose
# obligation the exception-edge CFG checks path-by-path.  Every entry is
# a protocol this repo's review passes have already paid for once: the
# PR 15 harvest found a failed dump write consuming the DumpCooldown
# window and an un-closed host-tier park interval; PR 12's found an
# aborted re-role stranding a drained donor; PR 9's found failover
# ledger entries surviving revive.
#
# Spec shape (schema in docs/static_analysis.md):
#   carrier  "path::Class" owning the protocol — the carrier's own
#            methods ARE the implementation and are never judged;
#   acquire/release/transfer
#            call specs, "recv.method" or bare "method".  The receiver
#            part substring-matches the call receiver's terminal name
#            ("kv.allocate" matches self.kv.allocate and
#            self.scheduler.kv.allocate, NOT recorder.allocate) —
#            transfer marks ownership moving into a tracked container;
#   on       which witness-path kinds to report:
#            "escape"  — an exception leaves the function with the
#                        obligation live (caller-owned resources: only
#                        the acquiring frame can release);
#            "swallow" — an exception is caught and the function exits
#                        normally with no release reachable from the
#                        handler (the stranded-state shape — valid for
#                        registry-owned resources too, where a later
#                        keyed cleanup covers ordinary escapes);
#            "normal"  — a normal path drops the obligation (strictest;
#                        no in-tree protocol needs it, tests use it).
RESOURCE_PROTOCOLS: tuple[dict, ...] = (
    {
        # paged KV page-table entries: registry-owned (the manager
        # tracks pages per request; abort/finish free by request id),
        # so only a swallowed failure that reports success leaks
        "name": "kv-page-table",
        "carrier": "vllm_omni_tpu/core/kv_cache_manager.py"
                   "::KVCacheManager",
        "acquire": ("kv.allocate", "kv.adopt_streamed"),
        "release": ("kv.free", "kv.restore_truncated"),
        "on": ("swallow",),
    },
    {
        # cross-tier transfer pins: pinned pages survive free() until
        # acked, so a swallowed transfer failure pins HBM forever
        "name": "kv-transfer-pin",
        "carrier": "vllm_omni_tpu/core/kv_cache_manager.py"
                   "::KVCacheManager",
        "acquire": ("kv.pin_for_transfer",),
        "release": ("kv.ack_transfer",),
        "on": ("swallow",),
    },
    {
        # host-tier park intervals (the PR 15 un-closed interval bug):
        # every parked request must be restored or dropped
        "name": "kv-park-interval",
        "carrier": "vllm_omni_tpu/core/kv_cache_manager.py"
                   "::KVCacheManager",
        "acquire": ("kv.park_request",),
        "release": ("kv.restore_parked", "kv.drop_park"),
        "on": ("swallow",),
    },
    {
        # the flight-recorder dump window: caller-owned — ready()
        # atomically reserves the cooldown window and ONLY the
        # acquiring frame can roll it back, so an escaping exception
        # after a successful ready() suppresses evidence capture for
        # the whole cooldown period (the PR 15 consumed-window bug)
        "name": "dump-cooldown-window",
        "carrier": "vllm_omni_tpu/introspection/flight_recorder.py"
                   "::DumpCooldown",
        "acquire": ("cooldown.ready",),
        "release": ("cooldown.release",),
        "on": ("swallow", "escape"),
    },
    {
        # router drain: a drained replica serves nothing until
        # undrained or removed — the PR 12 stranded-donor resource
        "name": "router-drain",
        "carrier": "vllm_omni_tpu/disagg/router.py::DisaggRouter",
        "acquire": ("router.drain",),
        "release": ("router.undrain", "router.remove_replica"),
        "on": ("swallow", "escape"),
    },
    {
        # exactly-once failover submission ledger: an entry nothing
        # clears replays or suppresses a request forever (PR 9)
        "name": "failover-submission-ledger",
        "carrier": "vllm_omni_tpu/disagg/router.py::EngineReplica",
        "acquire": ("_submitted.add",),
        "release": ("_submitted.discard", "_submitted.clear"),
        "on": ("swallow",),
    },
)

# The OL13 typestate manifest: declared state machines whose mutation
# sites the CFG checks against the transition graph, plus the
# generalized PR 12 abort check — a non-terminal state write followed
# by a swallowed exception path from which no recovery transition is
# reachable strands the object.
#
# Spec shape:
#   class       "path::Class" carrying the state field (the class's own
#               methods are exempt — they ARE the machine);
#   field       the attribute holding the state;
#   states/transitions/terminal
#               the graph; ``aliases`` maps writer-vocabulary names to
#               canonical states ("resolved" -> "inactive");
#   values      for boolean flag machines: {True: name, False: name};
#   transition_fn
#               mutations also happen through calls to this method
#               (target = positional arg ``target_arg``), and ITS body
#               is exempt (it is the one blessed mutation site);
#   recover     call vocabulary that re-admits/rolls back — reaching
#               one from a swallowed handler discharges the abort
#               check;
#   match       "class" (default: the file must define/import the
#               class or its module) or "field" (any assignment of the
#               field counts — for distinctive field names whose
#               carrier instances travel between modules).
STATE_MACHINES: tuple[dict, ...] = (
    {
        # the control-plane operation ladder (rerole/scale_down), with
        # the bounded actuation-refused retry edges back to draining
        "name": "controlplane-op",
        "class": "vllm_omni_tpu/controlplane/controller.py::_Op",
        "field": "stage",
        "states": ("draining", "flipping", "readmitting", "removing"),
        "transitions": {
            "draining": ("flipping", "removing"),
            "flipping": ("readmitting", "draining"),
            "removing": ("draining",),
            "readmitting": (),
        },
        "terminal": (),
        "recover": ("_abort_op", "_finish_op"),
    },
    {
        # the alert lifecycle ring; "resolved" is writer vocabulary
        # for the inactive state (the transition doc keeps the word)
        "name": "alert-lifecycle",
        "class": "vllm_omni_tpu/metrics/alerts.py::_RuleState",
        "field": "state",
        "states": ("inactive", "pending", "firing"),
        "aliases": {"resolved": "inactive"},
        "transitions": {
            "inactive": ("pending", "firing"),
            "pending": ("firing", "inactive"),
            "firing": ("inactive",),
        },
        "terminal": ("inactive",),
        "transition_fn": "_transition",
        "target_arg": 1,
        "recover": (),
    },
    {
        # replica rotation membership as a two-state machine: drained
        # is the non-terminal "someone must re-admit or remove me"
        # state (the PR 12 stranded-donor bug, generalized)
        "name": "replica-rotation",
        "class": "vllm_omni_tpu/disagg/router.py::EngineReplica",
        "field": "drained",
        "values": {True: "drained", False: "in-rotation"},
        "states": ("drained", "in-rotation"),
        "transitions": {
            "drained": ("in-rotation",),
            "in-rotation": ("drained",),
        },
        "terminal": ("in-rotation",),
        "recover": ("undrain", "remove_replica", "revive",
                    "_abort_op"),
        "match": "field",
    },
)


class ManifestError(RuntimeError):
    """A manifest entry no longer resolves to real code — a renamed
    module/class must fail the lint run loudly, not silently un-lint
    whatever the entry used to cover."""


def validate_manifest(root: "str | None" = None) -> None:
    """Check every path-shaped manifest entry resolves to an existing
    file/dir and every ``path::Class`` / sanitizer entry to a real
    class/def.  Called once per CLI run (``__main__``) and by
    ``tests/analysis``; raises :class:`ManifestError` listing every
    broken entry."""
    import os

    if root is None:
        from vllm_omni_tpu.analysis.engine import REPO_ROOT
        root = REPO_ROOT
    problems: list[str] = []

    def check_path(entry: str, table: str) -> "str | None":
        """Absolute path for an existing entry, else records a problem."""
        p = os.path.join(root, entry.rstrip("/"))
        if entry.endswith("/"):
            if not os.path.isdir(p):
                problems.append(f"{table}: no such directory: {entry}")
                return None
        elif not os.path.isfile(p):
            problems.append(f"{table}: no such file: {entry}")
            return None
        return p

    for table, entries in (("HOT_PATHS", HOT_PATHS),
                           ("THREADED_PATHS", THREADED_PATHS),
                           ("BENCH_PATHS", BENCH_PATHS),
                           ("PROTOCOL_MODULES", PROTOCOL_MODULES),
                           ("METRIC_MODULES", METRIC_MODULES),
                           ("sched_arith_paths",
                            TAINT_SINKS["sched_arith_paths"])):
        for entry in entries:
            check_path(entry, table)
    for key, guards in LOCK_GUARDS.items():
        path, _, cls = key.partition("::")
        p = check_path(path, "LOCK_GUARDS")
        if p is None:
            continue
        with open(p, encoding="utf-8") as fh:
            src = fh.read()
        import re as _re
        if not _re.search(rf"^\s*class\s+{_re.escape(cls)}\b", src,
                          _re.MULTILINE):
            problems.append(f"LOCK_GUARDS: no class '{cls}' in {path}")
        del guards
    for fn, path in SANITIZERS.items():
        p = check_path(path, "SANITIZERS")
        if p is None:
            continue
        with open(p, encoding="utf-8") as fh:
            src = fh.read()
        if f"def {fn}(" not in src:
            problems.append(f"SANITIZERS: no def '{fn}' in {path}")

    # ---- omnileak (OL12/OL13): every acquire/release/transfer spec,
    # state, transition endpoint and recover name must resolve to real
    # code — a renamed method must fail the run, not silently un-lint
    # the protocol it used to guard
    import re as _re

    def read_class_src(key: str, table: str) -> "str | None":
        path, _, cls = key.partition("::")
        p = check_path(path, table)
        if p is None:
            return None
        with open(p, encoding="utf-8") as fh:
            src = fh.read()
        if not _re.search(rf"^\s*class\s+{_re.escape(cls)}\b", src,
                          _re.MULTILINE):
            problems.append(f"{table}: no class '{cls}' in {path}")
            return None
        return src

    def def_somewhere(name: str) -> bool:
        """``def name(`` anywhere under the package tree — recover
        vocabularies cross modules (the controller re-admits what the
        router drained)."""
        pkg = os.path.join(root, "vllm_omni_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in filenames:
                if not f.endswith(".py"):
                    continue
                with open(os.path.join(dirpath, f),
                          encoding="utf-8") as fh:
                    if f"def {name}(" in fh.read():
                        return True
        return False

    for proto in RESOURCE_PROTOCOLS:
        tag = f"RESOURCE_PROTOCOLS[{proto.get('name', '?')}]"
        src = read_class_src(proto["carrier"], tag)
        for kind in proto.get("on", ()):
            if kind not in ("escape", "swallow", "normal"):
                problems.append(f"{tag}: unknown path kind {kind!r}")
        if src is None:
            continue
        for spec in (proto.get("acquire", ()) + proto.get("release", ())
                     + proto.get("transfer", ())):
            recv, _, meth = spec.rpartition(".")
            if f"def {meth}(" in src:
                continue
            # container protocols (``_submitted.add``): the method is
            # a builtin, the receiver must be a carrier attribute
            if recv and f"self.{recv}" in src:
                continue
            problems.append(
                f"{tag}: spec '{spec}' resolves to neither a def nor "
                f"a carrier attribute in {proto['carrier']}")
    for mach in STATE_MACHINES:
        tag = f"STATE_MACHINES[{mach.get('name', '?')}]"
        src = read_class_src(mach["class"], tag)
        if src is None:
            continue
        field = mach["field"]
        if not _re.search(rf"\b{_re.escape(field)}\b\s*[:=]", src):
            problems.append(
                f"{tag}: field '{field}' never assigned/declared in "
                f"{mach['class'].partition('::')[0]}")
        states = tuple(mach.get("states", ()))
        if not mach.get("values"):
            for st in states:
                if f'"{st}"' not in src and f"'{st}'" not in src:
                    problems.append(
                        f"{tag}: state {st!r} never appears in "
                        f"{mach['class'].partition('::')[0]}")
        for src_st, dsts in mach.get("transitions", {}).items():
            for st in (src_st,) + tuple(dsts):
                if st not in states:
                    problems.append(
                        f"{tag}: transition endpoint {st!r} not in "
                        f"states")
        for st in tuple(mach.get("terminal", ())) + tuple(
                mach.get("aliases", {}).values()):
            if st not in states:
                problems.append(f"{tag}: state {st!r} not in states")
        fn = mach.get("transition_fn")
        if fn and f"def {fn}(" not in src:
            problems.append(
                f"{tag}: no def '{fn}' in "
                f"{mach['class'].partition('::')[0]}")
        for name in mach.get("recover", ()):
            if not def_somewhere(name):
                problems.append(
                    f"{tag}: recover '{name}' is not a def anywhere "
                    f"under vllm_omni_tpu/")
    if problems:
        raise ManifestError(
            "manifest entries no longer resolve (a rename must update "
            "analysis/manifest.py, not silently un-lint):\n  "
            + "\n  ".join(problems))


def in_scope(path: str, prefixes: tuple[str, ...]) -> bool:
    """True when repo-relative ``path`` matches a manifest entry (a
    directory prefix ending in "/", an exact file, or a bare filename)."""
    for p in prefixes:
        if p.endswith("/"):
            if path.startswith(p):
                return True
        elif path == p or path.endswith("/" + p):
            return True
    return False
