"""OL6 — metric-drift: the Prometheus metric surface can't silently move.

The omnilint absorption of ``scripts/check_metrics_names.py`` (that
script is now a thin shim over this module so existing CI invocations
keep working).  Two layers:

- static (pure AST, runs anywhere): every key literal in the
  ``METRIC_SPECS`` dict must match ``vllm_omni_tpu_[a-z_]+`` after the
  prefix — lowercase/underscore only, no digits (which is why the E2E
  latency series is ``request_latency_ms``)
- dynamic (imports ``metrics/prometheus.py`` — dependency-free by
  design, so safe in any lane): render a synthetic exposition covering
  every stage/edge/engine series and parse it back against the specs
  (``validate_specs`` + ``validate_exposition``)
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import METRIC_MODULES, in_scope

_NAME_RE = re.compile(r"vllm_omni_tpu_[a-z_]+")
_PREFIX = "vllm_omni_tpu_"


def synthetic_summary() -> dict:
    """An aggregator summary exercising every stage/edge series."""
    return {
        "stages": {
            0: {"num_requests": 3, "tokens_in": 30, "tokens_out": 12,
                "tps": 41.5},
            1: {"num_requests": 3, "tokens_in": 12, "tokens_out": 12,
                "tps": 9.0},
        },
        "edges": {"0->1": {"transfers": 3, "bytes": 4096, "ms": 1.25}},
        "e2e": {"num_finished": 3, "window": 3, "p50_ms": 101.0,
                "p90_ms": 250.0, "p99_ms": 251.0},
    }


def synthetic_engine_snapshot() -> dict:
    """An engine snapshot exercising every engine series (LLM histograms
    + scheduler/KV gauges + diffusion counters)."""
    hist = {"buckets": [[10.0, 1], [100.0, 2], [float("inf"), 3]],
            "sum": 123.0, "count": 3, "p50": 40.0, "p90": 100.0,
            "p99": 110.0}
    return {
        "gauges": {"num_waiting": 1, "num_running": 2},
        "counters": {"num_steps": 7, "tokens_generated": 12,
                     "prefill_tokens": 30},
        "ttft_ms": hist, "tpot_ms": hist, "itl_ms": hist,
        "step_ms": hist, "host_ms": hist, "device_ms": hist,
        "overlap": {"ratio": 0.75, "host_ms_total": 40.0,
                    "overlapped_host_ms_total": 30.0},
        "batched_tokens": hist,
        "padding": {"useful_tokens_total": 42, "padded_tokens_total": 64,
                    "efficiency": 0.6563},
        "compile": {"compiles": 9, "cache_hits": 120,
                    "compile_s": 33.5},
        # live roofline attribution (metrics/roofline.py):
        # engine_step_mfu + the phase-labeled engine_step_mbu
        "roofline": {"mfu": 0.31, "mbu": {"prefill": 0.12,
                                          "decode": 0.55,
                                          "mixed": 0.4},
                     "window_steps": 128},
        "async_fallback": {"prefill": 4, "kv_transfer": 1},
        "scheduler": {"waiting": 1, "running": 2, "preemptions": 1,
                      "rejections": 0},
        "kv": {"pages_total": 64, "pages_used": 8, "utilization": 0.125},
        "prefix_cache": {"enabled": True, "hits": 2, "hit_tokens": 16},
        "kv_tiers": {
            "hbm_pages": 8, "host_pages": 3, "remote_pages": 1,
            "host_bytes": 12288,
            "bytes_moved": {"host/out": 16384, "host/in": 8192,
                            "remote/out": 4096, "remote/in": 4096},
            "prefix_hit_tokens": 16, "restored_tokens": 24,
            "parked_tokens": 32, "offload_evictions": 2,
        },
        "kv_restore_seconds": hist,
        # serving-curve observability (docs/load_testing.md): tenant-
        # labeled SLO/goodput ledger, queue depth + wait, shed ledger,
        # per-phase saturation — the drift guard must cover every
        # series the loadgen harness reads mid-flight
        "queue_wait_ms": hist,
        "queue": {"depth_by_tenant": {"default": 1, "acme": 2}},
        "shed": {"queue_depth/acme": 3, "deadline_headroom/default": 1},
        # weighted-fair overload scheduling (docs/control_plane.md)
        "wfq": {"deferred_by_tenant": {"default": 2, "acme": 1}},
        "slo": {
            "targets": {"ttft_ms": 500.0, "tpot_ms": 50.0},
            "tenants": {
                "default": {"finished": 4, "met": 3, "tokens": 128,
                            "goodput_tokens": 96, "attainment": 0.75},
                "acme": {"finished": 2, "met": 2, "tokens": 64,
                         "goodput_tokens": 64, "attainment": 1.0},
            },
        },
        "saturation": {"prefill": 0.5, "decode": 0.25, "seats": 0.75},
        # per-tenant heavy-hitter attribution (metrics/attribution.py):
        # every meter that maps to a /metrics series must render —
        # tenant_tokens_total{kind}, tenant_kv_page_seconds_total{tier},
        # handoff/queue-wait/shed meters, and the tracked-tenants gauge
        "attribution": {
            "capacity": 256,
            "meters": {
                "prefill_tokens": {
                    "total": 1200.0, "tenants_tracked": 2,
                    "max_overestimate": 4.7,
                    "top": [{"tenant": "acme", "est": 900.0,
                             "err": 0.0},
                            {"tenant": "default", "est": 300.0,
                             "err": 4.0}]},
                "decode_tokens": {
                    "total": 640.0, "tenants_tracked": 2,
                    "max_overestimate": 2.5,
                    "top": [{"tenant": "acme", "est": 512.0,
                             "err": 0.0}]},
                "kv_page_seconds_hbm": {
                    "total": 42.5, "tenants_tracked": 1,
                    "max_overestimate": 0.2,
                    "top": [{"tenant": "acme", "est": 42.5,
                             "err": 0.0}]},
                "kv_page_seconds_host": {
                    "total": 7.25, "tenants_tracked": 1,
                    "max_overestimate": 0.1,
                    "top": [{"tenant": "default", "est": 7.25,
                             "err": 0.0}]},
                "handoff_bytes": {
                    "total": 16384.0, "tenants_tracked": 1,
                    "max_overestimate": 64.0,
                    "top": [{"tenant": "acme", "est": 16384.0,
                             "err": 0.0}]},
                "queue_wait_ms": {
                    "total": 850.0, "tenants_tracked": 2,
                    "max_overestimate": 3.4,
                    "top": [{"tenant": "default", "est": 600.0,
                             "err": 1.0}]},
                "sheds": {
                    "total": 4.0, "tenants_tracked": 1,
                    "max_overestimate": 0.1,
                    "top": [{"tenant": "acme", "est": 4.0,
                             "err": 0.0}]},
                # omniscope per-tenant redundancy (metrics/
                # cache_economics.py): wasted re-prefill tokens the
                # router meters at dispatch time
                "duplicate_prefill_tokens": {
                    "total": 96.0, "tenants_tracked": 1,
                    "max_overestimate": 0.4,
                    "top": [{"tenant": "acme", "est": 96.0,
                             "err": 0.0}]},
            },
        },
        # device-memory ledger (introspection/memory_ledger.py):
        # components sum to total; every new component label value
        # renders through the same two series
        "device_memory": {
            "source": "fallback",
            "total_bytes": 3145728,
            "peak_total_bytes": 3145728,
            "components": {
                "weights": {"bytes": 2097152, "peak_bytes": 2097152},
                "kv_pages": {"bytes": 1048576, "peak_bytes": 1048576},
                "workspace": {"bytes": 0, "peak_bytes": 0},
            },
        },
        "diffusion": {"requests_total": 3, "batches_total": 2,
                      "gen_seconds": hist},
    }


def run_check() -> list[str]:
    """Spec + rendered-exposition round-trip; returns violation strings
    (the contract scripts/check_metrics_names.py and
    tests/metrics/test_prometheus.py have always consumed)."""
    from vllm_omni_tpu.metrics.prometheus import (
        render_exposition,
        validate_exposition,
        validate_specs,
    )

    hist = {"buckets": [[0.005, 1], [0.1, 2], [float("inf"), 3]],
            "sum": 0.2, "count": 3}
    errors = validate_specs()
    text = render_exposition(
        synthetic_summary(),
        {0: synthetic_engine_snapshot(), 1: synthetic_engine_snapshot()},
        device={"hbm_bytes": 16 * 2**30},
        # process-level introspection counters (span loss + watchdog)
        process_stats={"spans_dropped": 5, "watchdog_trips": 1,
                       "watchdog_tripped": True},
        # disaggregated serving (docs/disaggregation.md): the handoff
        # histogram plus the router's registry-riding counters/gauges —
        # every series the failover e2e asserts on must render here —
        # and the omniscope fleet cache board (metrics/
        # cache_economics.py exposition shape)
        disagg={"handoff_seconds": hist,
                "prefix_pull_seconds": hist,
                "cache": {
                    "fleet_hit_tokens": 320,
                    "fleet_prefill_tokens": 480,
                    "hit_rate": 0.4,
                    "duplicate_by_reason": {"peer_replica": 96,
                                            "peer_cold_tier": 32},
                    "duplicate_prefix_tokens": 64,
                    "digest_nodes": {"prefill0": 12, "decode0": 3},
                }},
        resilience={
            "kv_handoff_bytes_total": [({"dir": "out"}, 8192),
                                       ({"dir": "in"}, 8192)],
            "failover_total": [({"reason": "prefill_replica_died"}, 1),
                               ({"reason": "handoff_failed"}, 2)],
            "router_healthy_replicas": [({"role": "prefill"}, 2),
                                        ({"role": "decode"}, 1)],
            "degraded_mode": [({}, 0)],
            # omniaffinity (disagg/router.py): affinity dispatch
            # outcomes + cluster-KV-fabric pull bytes
            "router_affinity_dispatch_total": [
                ({"outcome": "hit"}, 5), ({"outcome": "miss"}, 3),
                ({"outcome": "load_override"}, 1)],
            "kv_prefix_pull_bytes_total": [({"src": "peer"}, 8192),
                                           ({"src": "cold"}, 4096)],
            # control plane (docs/control_plane.md): the controller's
            # registry-riding fleet gauges and actuation counters —
            # every series the closed-loop bench asserts on
            "controlplane_reroles_total": [
                ({"from_role": "decode", "to_role": "prefill"}, 1),
                ({"from_role": "prefill", "to_role": "decode"}, 1)],
            "controlplane_replicas": [({"role": "prefill"}, 2),
                                      ({"role": "decode"}, 2)],
            "controlplane_actions_total": [
                ({"action": "drain"}, 2), ({"action": "rerole"}, 1),
                ({"action": "scale_up"}, 1)],
            # omnipulse alert lifecycle (metrics/alerts.py): the
            # firing gauge + per-destination transition counters the
            # loadgen overload e2e asserts on mid-flight
            "alerts_firing": [({"alert": "slo_fast_burn"}, 1),
                              ({"alert": "engine_stalled"}, 0)],
            "alert_transitions_total": [
                ({"alert": "slo_fast_burn", "to": "pending"}, 2),
                ({"alert": "slo_fast_burn", "to": "firing"}, 1),
                ({"alert": "slo_fast_burn", "to": "resolved"}, 1)],
        },
    )
    errors += validate_exposition(text)
    return errors


class MetricDriftRule(Rule):
    id = "OL6"
    name = "metric-drift"
    node_types = (ast.Assign, ast.AnnAssign)

    def __init__(self):
        self._specs_node = None

    def applies(self, ctx: FileContext) -> bool:
        return in_scope(ctx.path, METRIC_MODULES)

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        is_specs = any(
            isinstance(t, ast.Name) and t.id == "METRIC_SPECS"
            for t in targets)
        if not is_specs or node.value is None:
            return
        self._specs_node = node
        if isinstance(node.value, ast.Dict):
            yield from self._check_keys(node.value, ctx)

    def _check_keys(self, d: ast.Dict, ctx) -> Iterable[Finding]:
        for k in d.keys:
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            full = _PREFIX + k.value
            if not _NAME_RE.fullmatch(full) or re.search(r"\d", k.value):
                yield ctx.finding(
                    self.id, k,
                    f"metric name '{k.value}' breaks the naming rule "
                    f"({_NAME_RE.pattern}, no digits)")

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        anchor = self._specs_node or ctx.tree
        try:
            errors = run_check()
        except Exception as e:  # import/render blew up: that IS drift
            yield ctx.finding(
                self.id, anchor,
                f"metric surface check failed to run: "
                f"{type(e).__name__}: {e}")
            return
        for err in errors:
            yield ctx.finding(self.id, anchor, f"metric drift: {err}")
