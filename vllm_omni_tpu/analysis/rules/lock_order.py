"""OL8 — lock-order: cycles in the process-wide acquisition graph.

Two threads acquiring the same two locks in opposite orders is the
classic deadlock — each waits for the other's lock forever, and the
PR 8 stall watchdog can only report the wedge after the fact.  This
rule builds the acquisition-order graph statically:

- **nested ``with``**: ``with A: ... with B:`` adds edge A -> B;
- **intra-module call edges**: a call made while holding A, to a
  function/method defined in the same module that (transitively)
  acquires B, also adds A -> B — the indirection idiom
  (``with self._lock: self._helper()``) must not hide an ordering.

Lock identity is ``Class._attr`` / ``<module-stem>._attr`` — the same
node granularity as OL7's manifest and the runtime validator
(analysis/runtime.py), so a static cycle and a runtime inversion name
the same nodes.  Edges accumulate **across every file analyzed in one
run** (the engine's per-run state, keyed by path), so the two halves
of a cycle may live in different modules.  The file whose analysis
COMPLETES the cycle reports it — once, anchored at that file's
acquisition site and naming the reverse path's location (files
analyzed earlier saw no cycle yet; re-running the gate is stable
because the walk order is deterministic).  One run never leaks into
the next: a standalone ``analyze_source`` sees only its own file
unless the caller threads a shared ``run_state`` dict across calls.

Re-entry (``with self._lock`` nested under itself — the RLock idiom)
is never an edge and never a cycle: self-deadlock on a plain ``Lock``
is the runtime validator's call, which knows the lock's actual type.

A deliberate, documented ordering that the graph misreads (e.g. two
locks that provably never cross threads) carries a suppression::

    with self._b:  # omnilint: disable=OL8 - B outlives A, single owner
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.rules._lockinfo import (
    held_locks,
    iter_local_functions,
    resolve_local_call,
    with_lock_ids,
)


class LockOrderRule(Rule):
    id = "OL8"
    name = "lock-order"
    node_types = (ast.With,)

    def __init__(self):
        self._withs: list[ast.With] = []

    def visit(self, node: ast.With,
              ctx: FileContext) -> Iterable[Finding]:
        self._withs.append(node)
        return ()

    # --------------------------------------------------------------- finish
    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        # run-scoped edge registry: path -> {(holder, acquired):
        # (line, qualname)} — all files of one analyze_paths run share
        # it through the engine's run_state
        registry = self.run_state.setdefault("ol8_edges", {})
        edges = self._file_edges(ctx)
        registry[ctx.path] = edges
        if not edges:
            return
        merged: dict[tuple, tuple] = {}
        for path, fe in registry.items():
            for edge, (line, qual) in fe.items():
                merged.setdefault(edge, (path, line, qual))
        reported: set[frozenset] = set()
        for (a, b) in sorted(edges):
            rev = self._find_path(merged, b, a)
            if rev is None:
                continue
            # one cycle = one finding: dedup by the cycle's full node
            # set (edge-pair keying would report a k-lock cycle k times)
            key = frozenset(set(rev) | {a, b})
            if key in reported:
                continue
            reported.add(key)
            line, qual = edges[(a, b)]
            # where the first reverse leg lives (path + qualname, no
            # line number: the fingerprint must survive unrelated edits)
            rpath, _rline, rqual = merged[(rev[0], rev[1])]
            yield Finding(
                rule=self.id, path=ctx.path, line=line,
                symbol=qual,
                message=(
                    f"potential deadlock: {a} -> {b} acquired here, "
                    f"but the reverse order {' -> '.join(rev)} exists "
                    f"at {rpath} ({rqual or 'module'}) — pick one "
                    "global order or collapse to a single lock"),
                stmt_span=(line, line))

    # ---------------------------------------------------------- edge build
    def _file_edges(self, ctx: FileContext) -> dict:
        edges: dict[tuple, tuple] = {}
        if not any(with_lock_ids(w, ctx) for w in self._withs):
            return edges  # no lock acquisitions at all in this file

        def add(a: str, b: str, node: ast.AST) -> None:
            if a == b:
                return  # re-entry (RLock idiom) is not an ordering
            edges.setdefault(
                (a, b),
                (getattr(node, "lineno", 1), ctx.qualname(node)))

        # 1. direct lexical nesting — including WITHIN one multi-item
        # statement: `with A, B:` acquires left-to-right, so it is the
        # same ordering fact as `with A: with B:`
        for w in self._withs:
            held = held_locks(w, ctx)
            ids = with_lock_ids(w, ctx)
            for i, lid in enumerate(ids):
                for h in held:
                    add(h, lid, w)
                for prior in ids[:i]:
                    add(prior, lid, w)

        # 2. intra-module call edges: calls under a lock into local
        # functions whose closure acquires more locks
        acquires = self._closure_acquires(ctx)
        if acquires:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                held = held_locks(node, ctx)
                if not held:
                    continue
                target = resolve_local_call(node, ctx)
                if target is None:
                    continue
                for lid in sorted(acquires.get(target, ())):
                    for h in held:
                        add(h, lid, node)
        return edges

    def _closure_acquires(self, ctx: FileContext) -> dict:
        """function key -> lock ids its transitive local closure can
        acquire.  Keys are "funcname" (module level) / "Class.method".
        ``ast.walk`` includes nested function bodies, so a method whose
        inner closure acquires a lock (the ``rpc``-under-retry idiom)
        counts as acquiring it — a deliberate over-approximation: the
        closure usually runs while the method is on the stack."""
        direct: dict[str, set] = {}
        calls: dict[str, set] = {}
        for key, fn in iter_local_functions(ctx):
            acq: set = set()
            callees: set = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.With):
                    acq.update(with_lock_ids(sub, ctx))
                elif isinstance(sub, ast.Call):
                    t = resolve_local_call(sub, ctx)
                    if t is not None and t != key:
                        callees.add(t)
            direct[key] = acq
            calls[key] = callees
        closure = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for k, callees in calls.items():
                for c in callees:
                    extra = closure.get(c, set()) - closure[k]
                    if extra:
                        closure[k] |= extra
                        changed = True
        return {k: v for k, v in closure.items() if v}

    @staticmethod
    def _find_path(merged: dict, src: str, dst: str) -> Optional[list]:
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for (a, b) in sorted(merged):
                if a == node and b not in seen:
                    seen.add(b)
                    stack.append((b, path + [b]))
        return None
