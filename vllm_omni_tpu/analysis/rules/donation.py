"""OL3 — donation-safety: reading a buffer after donating it.

``donate_argnums``/``donate_argnames`` hands the argument's buffer to
XLA for in-place reuse — the caller's reference is INVALIDATED the
moment the call dispatches.  Reading it afterwards raises
``RuntimeError: Array has been deleted`` on TPU, but silently *works*
on the CPU backend the tests run on, which is exactly why a linter has
to catch it.  The safe idiom this repo uses everywhere is
re-binding the donated expression from the call's result::

    logits, hidden, self.kv_caches = self._decode_fn(
        ..., self.kv_caches, ...)       # donated slot 2, rebound: OK

The rule resolves the module's jit wrappers through the shared index
(including ``functools.partial(jax.jit, donate_argnums=...)`` factories
and wrapper-returning helper defs), then checks every call site of a
donating callable:

- the donated argument must be re-bound by the same statement, OR
- never read again in the enclosing function after the call
  (first later reference being a store also counts as safe)
- inside a loop, a donated name that the statement does not re-bind is
  flagged even when the only other read is textually *before* the call
  (it re-executes on the next iteration against a dead buffer)
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.rules._jitinfo import (
    ModuleJitIndex,
    build_index,
    donate_positions,
    dotted,
    param_names,
)


def _is_store(node: ast.AST) -> bool:
    return isinstance(getattr(node, "ctx", None), (ast.Store, ast.Del))


def _refs_in(root: ast.AST, key: str):
    """(position, is_store, node) for every reference to dotted ``key``
    inside ``root`` — outermost match only (a.b.c doesn't also count as
    a.b)."""
    claimed: set[int] = set()
    refs = []
    for node in ast.walk(root):
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        if id(node) in claimed:
            continue
        if dotted(node) == key:
            for sub in ast.walk(node):
                claimed.add(id(sub))
            refs.append(((node.lineno, node.col_offset),
                         _is_store(node), node))
    refs.sort(key=lambda r: r[0])
    return refs


def _stmt_rebinds(stmt: ast.stmt, key: str) -> bool:
    """Does this statement bind ``key`` as (part of) an assignment
    target?"""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, (ast.Name, ast.Attribute)) \
                    and dotted(sub) == key:
                return True
    return False


class DonationRule(Rule):
    id = "OL3"
    name = "donation-safety"
    node_types = (ast.Call,)

    def __init__(self):
        self._index: Optional[ModuleJitIndex] = None
        self._calls: list[ast.Call] = []

    def visit(self, node: ast.Call, ctx: FileContext) -> Iterable[Finding]:
        self._calls.append(node)
        return ()

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        idx = self._index = build_index(ctx.tree)
        for call in self._calls:
            name = dotted(call.func)
            entry = idx.jitted.get(name or "")
            if entry is None:
                continue
            wrap, fn = entry
            positions = donate_positions(wrap, fn)
            if not positions:
                continue
            donated: list[tuple[str, ast.AST]] = []
            for pos in positions:
                if pos < len(call.args):
                    key = dotted(call.args[pos])
                    if key:
                        donated.append((key, call.args[pos]))
            if fn is not None:
                names = param_names(fn)
                for kw in call.keywords:
                    if kw.arg in wrap.donate_argnames or (
                            kw.arg in names
                            and names.index(kw.arg) in positions):
                        key = dotted(kw.value)
                        if key:
                            donated.append((key, kw.value))
            for key, anchor in donated:
                yield from self._check_use_after(call, key, anchor,
                                                 name, ctx)

    def _check_use_after(self, call: ast.Call, key: str, anchor,
                         callee: str, ctx: FileContext
                         ) -> Iterable[Finding]:
        stmt = ctx.enclosing_statement(call)
        if _stmt_rebinds(stmt, key):
            return  # canonical rebind-from-result idiom
        scope = ctx.enclosing_function(call) or ctx.tree
        call_pos = (call.end_lineno or call.lineno,
                    call.end_col_offset or call.col_offset)
        later = [(pos, is_store) for pos, is_store, node
                 in _refs_in(scope, key) if pos > call_pos]
        if later and not later[0][1]:
            yield ctx.finding(
                self.id, anchor,
                f"'{key}' is read after being donated to '{callee}' — "
                "the buffer is invalidated at dispatch (works on CPU, "
                "RuntimeError on TPU); re-bind it from the call result")
            return
        if "." in key and not any(is_store for _, is_store in later):
            # an attribute (self.X / obj.attr) OUTLIVES this function:
            # with no re-bind anywhere after the call, the stale handle
            # escapes and the next method that touches it reads a dead
            # buffer — "never read again locally" only clears LOCALS
            yield ctx.finding(
                self.id, anchor,
                f"attribute '{key}' is donated to '{callee}' and never "
                "re-bound — the stale handle outlives this function "
                "(dead-buffer read on the next access); assign the "
                "call's returned buffer back")
            return
        # loop-carried: an un-rebound donation re-executes on the next
        # iteration — the donated argument itself is then a read of a
        # dead buffer, unless something in the loop body stores a fresh
        # value into the name first
        for anc in ctx.ancestors(call):
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                stores = [n for _, is_store, n in _refs_in(anc, key)
                          if is_store]
                if not stores:
                    yield ctx.finding(
                        self.id, anchor,
                        f"'{key}' is donated to '{callee}' inside a "
                        "loop without re-binding — the next iteration "
                        "donates an already-dead buffer")
                break
