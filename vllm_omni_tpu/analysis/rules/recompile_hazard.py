"""OL11 — recompile-hazard: per-request values in jit cache keys,
cache keys blind to dispatch variants, and unwarmed executables.

XLA compiles one executable per input signature and a mid-traffic
cache miss stalls every in-flight request for the full compile
(20-40 s per shape on a remote-attached chip — docs/performance.md).
The whole per-shape discipline therefore hangs on three invariants at
every ``_run_jit(kind, shape_key, thunk)`` dispatch site
(``RECOMPILE`` manifest, analysis/manifest.py):

1. **bucketed keys** — every term of ``shape_key`` (and every literal
   shape handed to a jax array constructor near the dispatch) derives
   from bucketed values (``_bucket``/``_token_buckets``/
   ``auto_blocks``…) or static config.  A per-request int (``len(...)``
   of runtime data, a ``num_*_tokens`` read) flowing in unbucketed
   compiles a NEW executable per distinct value.  Resolution follows
   local reaching definitions and, for helper indirection (a ``warm``
   wrapper taking the key as a parameter), the cross-module call graph
   to a bounded depth.
2. **variants in the key** — the PR 11 ``n_deep`` bug class: an
   argument whose *presence/width* is conditional at the dispatch site
   (a ``kwargs["deepstack"] = ...`` under ``if``, a keyword bound only
   inside a branch) changes the traced program, so some term of the
   cache key must observe the same discriminator; otherwise a real
   compile is misread as a cache hit and the compile-stall
   introspection goes blind.
3. **warmed kinds** (``finish`` pass) — every ``kind`` string
   registered at a serving dispatch site must be reachable from the
   warmup bucket walker (``precompile``): an unwarmed executable is a
   guaranteed first-hit compile stall under traffic.

A deliberate exception carries a reasoned suppression::

    self._run_jit("oneshot", key, thunk)  # omnilint: disable=OL11 - offline tool
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import (
    FileContext,
    Finding,
    ProgramGraph,
    Rule,
    dotted_names,
    own_nodes,
)
from vllm_omni_tpu.analysis.manifest import RECOMPILE
from vllm_omni_tpu.analysis.rules._lockinfo import callee_terminal


class RecompileHazardRule(Rule):
    id = "OL11"
    name = "recompile-hazard"
    node_types = ()
    # overridable in tests
    manifest = RECOMPILE
    MAX_DEPTH = 3

    def applies(self, ctx: FileContext) -> bool:
        return False  # package-wide: everything happens in finalize_run

    # ------------------------------------------------------------ finalize
    def finalize_run(self) -> Iterable[Finding]:
        graph = ProgramGraph.ensure(self.run_state)
        self._graph = graph
        self._defs_cache: dict = {}
        dispatch_fns = self.manifest["dispatch_fns"]
        sites = []  # (fi, call, exclusively_warmup, warm_reachable)
        has_dispatch: dict = {}  # path -> file mentions a dispatch fn
        for key in sorted(graph.functions):
            fi = graph.functions[key]
            if fi.path not in has_dispatch:
                has_dispatch[fi.path] = any(
                    fn in fi.ctx.source for fn in dispatch_fns)
            if not has_dispatch[fi.path]:
                continue
            in_warm = self._in_warmup(fi)
            warm_reach = in_warm or self._warm_reachable(fi)
            for node in own_nodes(fi.node):
                if (isinstance(node, ast.Call)
                        and callee_terminal(node.func) in dispatch_fns
                        and len(node.args) >= 2):
                    sites.append((fi, node, in_warm, warm_reach))
        findings: list = []
        served: dict = {}   # (group, kind) -> first serving site
        warmed: set = set()  # (group, kind)
        groups_with_sites: set = set()
        for fi, call, in_warm, warm_reach in sites:
            group = (fi.path, fi.cls_name or "")
            groups_with_sites.add(group)
            findings.extend(self._check_shape_key(fi, call))
            if not in_warm:
                findings.extend(self._check_variants(fi, call))
                findings.extend(self._check_array_ctors(fi, call))
            kinds = self._kind_strings(call.args[0], fi, self.MAX_DEPTH,
                                       set())
            for k in kinds or ():
                # a helper shared by precompile AND serving is both: its
                # kinds ARE warmed (warmup provably reaches the site)
                # and its dispatch still rides the serving invariants
                if warm_reach:
                    warmed.add((group, k))
                if not in_warm:
                    served.setdefault((group, k), (fi, call))
        warm_groups = {g for (g, _k) in warmed}
        for (group, k) in sorted(served):
            if (group, k) in warmed:
                continue
            if (group not in warm_groups
                    and any(kk == k for (_g, kk) in warmed)):
                # the warmup walker lives in ANOTHER module/class (a
                # hoisted free-function precompile(runner)): the
                # serving group has no warmup sites of its own, so a
                # globally-warmed kind counts — per-group precision
                # only applies where the group warms itself
                continue
            fi, call = served[(group, k)]
            wnames = "/".join(self.manifest["warmup_funcs"])
            findings.append(fi.ctx.finding(
                self.id, call,
                f"kind '{k}' is dispatched here but never reached from "
                f"the warmup bucket walker ({wnames}) — an unwarmed "
                "executable compiles on its first traffic hit, a "
                "guaranteed mid-stream stall; add it to the warmup "
                "walk or suppress with the reason it cannot be warmed"))
        return findings

    def _in_warmup(self, fi) -> bool:
        """Lexically inside a warmup walker (``precompile`` or a
        closure nested in one), or called exclusively from warmup
        functions (one hop of helper indirection)."""
        warm = self.manifest["warmup_funcs"]
        if any(part in warm for part in fi.qual.split(".")):
            return True
        callers = self._graph.callers_of(fi.key)
        return bool(callers) and all(
            any(part in warm for part in cfi.qual.split("."))
            for cfi, _ in callers)

    def _warm_reachable(self, fi) -> bool:
        """ANY caller is a warmup function: the warmup walk provably
        reaches this site, so its kinds are warmed — even when other
        (serving) callers reach it too."""
        warm = self.manifest["warmup_funcs"]
        return any(
            any(part in warm for part in cfi.qual.split("."))
            for cfi, _ in self._graph.callers_of(fi.key))

    # ------------------------------------------------------ reaching defs
    def _defs(self, fi) -> dict:
        """name -> [(value expr, how, conditional)] from the function's
        own assignments.  ``how`` records HOW the name reads off the
        value: None = the whole expression, an int i = element i of a
        literal tuple unpack, ("iter", None) = an element of the
        iterable (plain for-target), ("iter", i) = element i of each
        item (``for kind, fn in (("a", f), ...)``) — kept resolvable so
        the precompile kind loop stays provable."""
        if fi.key in self._defs_cache:
            return self._defs_cache[fi.key]
        defs: dict = {}

        def conditional(node) -> bool:
            cur = fi.ctx.parent(node)
            while cur is not None and cur is not fi.node:
                if isinstance(cur, ast.If):
                    return True
                cur = fi.ctx.parent(cur)
            return False

        def record(tgt, value, how=None, iterated=False, cond=False):
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for i, elt in enumerate(tgt.elts):
                    record(elt, value, ("iter", i) if iterated else i,
                           cond=cond)
                return
            if isinstance(tgt, ast.Name):
                if iterated and how is None:
                    how = ("iter", None)
                defs.setdefault(tgt.id, []).append((value, how, cond))

        for node in own_nodes(fi.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    record(tgt, node.value, cond=conditional(node))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                record(node.target, node.value, cond=conditional(node))
            elif isinstance(node, ast.AugAssign):
                record(node.target, node.value, cond=conditional(node))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                record(node.target, node.iter, iterated=True,
                       cond=conditional(node))
            elif isinstance(node, ast.NamedExpr):
                record(node.target, node.value, cond=conditional(node))
        self._defs_cache[fi.key] = defs
        return defs

    # ------------------------------------------------- shape-key bucketing
    def _check_shape_key(self, fi, call: ast.Call) -> list:
        evidence = self._per_request_evidence(call.args[1], fi,
                                              self.MAX_DEPTH, set())
        if evidence is None:
            return []
        desc, chain = evidence
        via = f" (via {' -> '.join(chain)})" if chain else ""
        return [fi.ctx.finding(
            self.id, call,
            f"per-request value in jit cache key: {desc} flows into "
            f"the `_run_jit` shape_key unbucketed{via} — every "
            "distinct value compiles a NEW executable mid-traffic; "
            "bucket it (_bucket/_token_buckets/auto_blocks) or build "
            "the key from static config")]

    def _per_request_evidence(self, expr, fi, depth: int,
                              visited: set) -> Optional[tuple]:
        """(description, call-chain) of the first per-request int
        reachable from ``expr`` without crossing a bucketing call, or
        None.  Chases local reaching definitions, and parameters
        through the call graph (helper indirection)."""
        if depth < 0:
            return None
        bucket_fns = self.manifest["bucket_fns"]
        bucket_attrs = self.manifest["bucket_attrs"]
        pr_attrs = self.manifest["per_request_attrs"]
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Compare) or (
                    isinstance(node, ast.UnaryOp)
                    and isinstance(node.op, ast.Not)):
                # a comparison/negation collapses per-request data into
                # a 2-valued discriminator — bounded by construction,
                # and exactly what a cache key SHOULD observe
                continue
            if isinstance(node, ast.Call):
                term = callee_terminal(node.func)
                if term in bucket_fns:
                    continue  # bucketed: the whole subtree is safe
                if term in ("len", "sum") and node.args:
                    if self._derives_from_runtime(node.args[0], fi,
                                                  depth, visited):
                        return (f"`{term}(...)` of runtime data", ())
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Attribute):
                if node.attr in pr_attrs:
                    return (f"a `.{node.attr}` read", ())
                if node.attr == "shape":
                    # an operand's .shape in a key is the CORRECT
                    # discriminator — it observes what is actually
                    # traced (the n_deep fix is exactly this read)
                    continue
                if isinstance(node.value, ast.Name):
                    if node.value.id in ("self", "cls"):
                        # self-attrs are config/bucket tables (per-
                        # request state rides locals in this codebase)
                        continue
                    # field-sensitive projection: `asm.t_pad` follows
                    # the t_pad FIELD through the constructor the
                    # base name was built by, not every constructor
                    # argument
                    hit = self._field_evidence(node.value.id, node.attr,
                                               fi, depth, visited)
                    if hit is not None:
                        return hit
                    continue
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Name):
                hit = self._name_evidence(node.id, fi, depth, visited)
                if hit is not None:
                    return hit
                continue
            stack.extend(ast.iter_child_nodes(node))
        return None

    def _name_evidence(self, name: str, fi, depth: int,
                       visited: set) -> Optional[tuple]:
        key = (fi.key, name)
        if key in visited:
            return None
        visited.add(key)
        defs = self._defs(fi)
        for value, _idx, _cond in defs.get(name, ()):
            hit = self._per_request_evidence(value, fi, depth - 1,
                                             visited)
            if hit is not None:
                return hit
        if name not in defs and name in fi.param_names():
            # helper indirection: classify what every caller passes
            for cfi, call in self._graph.callers_of(fi.key):
                arg = ProgramGraph.call_arg_for_param(call, fi, name)
                if arg is None:
                    continue
                hit = self._per_request_evidence(arg, cfi, depth - 1,
                                                 visited)
                if hit is not None:
                    desc, chain = hit
                    return (desc, (f"{cfi.qual} -> {fi.qual}",) + chain)
        return None

    def _field_evidence(self, base: str, attr: str, fi, depth: int,
                        visited: set) -> Optional[tuple]:
        """Per-request evidence for ONE field of a constructed object:
        resolve the base name's defining call through the graph, find
        the ``return Ctor(...)`` feeding that field (keyword, or
        positional against the ctor class's annotated field order), and
        classify the feeding expression in the callee's context."""
        key = (fi.key, f"{base}.{attr}")
        if key in visited or depth < 0:
            return None
        visited.add(key)
        for value, how, _c in self._defs(fi).get(base, ()):
            if how is not None or not isinstance(value, ast.Call):
                continue
            target = self._graph.resolve_call(value, fi.ctx)
            if target is None:
                continue
            for node in own_nodes(target.node):
                if not (isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Call)):
                    continue
                field = self._ctor_field(node.value, attr, target.ctx)
                if field is None:
                    continue
                hit = self._per_request_evidence(field, target,
                                                 depth - 1, visited)
                if hit is not None:
                    desc, chain = hit
                    return (desc,
                            (f"{target.qual} builds .{attr}",) + chain)
        return None

    @staticmethod
    def _ctor_field(ctor: ast.Call, attr: str,
                    ctx: FileContext) -> Optional[ast.AST]:
        for kw in ctor.keywords:
            if kw.arg == attr:
                return kw.value
        term = callee_terminal(ctor.func)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == term:
                fields = [st.target.id for st in node.body
                          if isinstance(st, ast.AnnAssign)
                          and isinstance(st.target, ast.Name)]
                if attr in fields:
                    idx = fields.index(attr)
                    if idx < len(ctor.args):
                        return ctor.args[idx]
                return None
        return None

    def _derives_from_runtime(self, expr, fi, depth: int,
                              visited: set) -> bool:
        """True when ``len(expr)``/``sum(expr)`` measures per-request
        data: anything reaching a function parameter without crossing
        a bucket call or a self-attribute (static config/tables)."""
        if depth < 0:
            return False
        bucket_fns = self.manifest["bucket_fns"]
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                if callee_terminal(node.func) in bucket_fns:
                    continue
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) \
                        and node.value.id in ("self", "cls"):
                    continue  # static config/table read
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Name):
                if node.id in fi.param_names():
                    return True
                key = (fi.key, "runtime:" + node.id)
                if key in visited:
                    continue
                visited.add(key)
                for value, _i, _c in self._defs(fi).get(node.id, ()):
                    stack.append(value)
                continue
            stack.extend(ast.iter_child_nodes(node))
        return False

    # ------------------------------------------------------- variant check
    def _check_variants(self, fi, call: ast.Call) -> list:
        """Every conditionally-present argument variant observable at
        the dispatch site must appear in the cache key (the ``n_deep``
        class: presence/width of an arg changes the traced program)."""
        if len(call.args) < 3 or not isinstance(call.args[2], ast.Lambda):
            return []
        key_names = self._key_names(call.args[1], fi)
        out: list = []
        for inner in ast.walk(call.args[2].body):
            if not isinstance(inner, ast.Call):
                continue
            for kw in inner.keywords:
                if kw.arg is None and isinstance(kw.value, ast.Name):
                    out.extend(self._check_kwargs_dict(
                        fi, call, kw.value.id, key_names))
                elif kw.arg is not None and isinstance(kw.value,
                                                       ast.Name):
                    out.extend(self._check_conditional_name(
                        fi, call, kw.arg, kw.value.id, key_names))
        return out

    @staticmethod
    def _maximal(names: set) -> set:
        """Drop every chain another chain extends: {"asm",
        "asm.deepstack"} -> {"asm.deepstack"} — a bare base name must
        not count as observing every field hung off it."""
        return {c for c in names
                if not any(o != c and o.startswith(c + ".")
                           for o in names)}

    @staticmethod
    def _observes(key_names: set, discriminators: set) -> bool:
        """Does any key chain observe any discriminator chain?  Exact
        match, or a dotted prefix relation in either direction — but
        never through a bare (dot-free) base name, which would make
        `asm.t_pad` in the key bless every other `asm.*` variant."""
        for k in key_names:
            for g in discriminators:
                if k == g:
                    return True
                if k.startswith(g + ".") and "." in g:
                    return True
                if g.startswith(k + ".") and "." in k:
                    return True
        return False

    def _key_names(self, key_expr, fi) -> set:
        names = dotted_names(key_expr)
        # a key passed as a local name: read its definitions too
        if isinstance(key_expr, ast.Name):
            for value, _i, _c in self._defs(fi).get(key_expr.id, ()):
                names |= dotted_names(value)
        return self._maximal(names)

    def _if_guards(self, node, fi) -> list:
        guards = []
        cur = fi.ctx.parent(node)
        while cur is not None and cur is not fi.node:
            if isinstance(cur, ast.If):
                guards.append(cur.test)
            cur = fi.ctx.parent(cur)
        return guards

    def _check_kwargs_dict(self, fi, call, dname: str,
                           key_names: set) -> list:
        out = []
        for node in own_nodes(fi.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt.value, ast.Name)
                    and tgt.value.id == dname):
                continue
            variant = None
            if isinstance(tgt.slice, ast.Constant):
                variant = tgt.slice.value
            guards = self._if_guards(node, fi)
            if not guards:
                continue  # unconditional: always part of the signature
            observed = set()
            for g in guards:
                observed |= dotted_names(g)
            observed |= dotted_names(node.value)
            observed.discard(dname)
            observed = self._maximal(observed)
            if not self._observes(key_names, observed):
                out.append(fi.ctx.finding(
                    self.id, call,
                    f"dispatch variant '{variant}' feeds the jitted "
                    "call only under a condition, but no term of the "
                    "shape_key observes that condition — a changed "
                    "variant re-traces the program while the cache "
                    "key claims a hit (the n_deep bug class); add "
                    "the discriminator to the key"))
        return out

    def _check_conditional_name(self, fi, call, kwarg: str, name: str,
                                key_names: set) -> list:
        defs = self._defs(fi).get(name, ())
        if not defs or not all(cond for _v, _i, cond in defs):
            return []  # unconditionally bound at least once
        observed = set()
        for value, _i, _c in defs:
            observed |= dotted_names(value)
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        for g in self._if_guards(node, fi):
                            observed |= dotted_names(g)
        observed.discard(name)
        if self._observes(key_names, self._maximal(observed)):
            return []
        return [fi.ctx.finding(
            self.id, call,
            f"keyword '{kwarg}' is bound only inside a branch, but no "
            "term of the shape_key observes its discriminator — a "
            "changed variant re-traces the program while the cache "
            "key claims a hit (the n_deep bug class); add the "
            "discriminator to the key")]

    # -------------------------------------------------- array constructors
    def _check_array_ctors(self, fi, call: ast.Call) -> list:
        """Literal shape tuples handed to jax array constructors in the
        thunk: a per-request dim compiles per distinct value exactly
        like an unbucketed key term."""
        if len(call.args) < 3:
            return []
        ctors = self.manifest["array_ctors"]
        out = []
        for node in ast.walk(call.args[2]):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ctors and node.args):
                continue
            shape = node.args[0]
            hit = self._per_request_evidence(shape, fi, self.MAX_DEPTH,
                                             set())
            if hit is not None:
                desc, _chain = hit
                out.append(fi.ctx.finding(
                    self.id, node,
                    f"per-request value in a jitted array shape: {desc} "
                    f"sizes `{node.func.attr}(...)` inside the dispatch "
                    "thunk — pad to a bucket instead (every distinct "
                    "dim is a fresh XLA compile)"))
        return out

    # ------------------------------------------------------- kind strings
    def _kind_strings(self, expr, fi, depth: int,
                      visited: set) -> Optional[set]:
        """Every string literal ``expr`` can evaluate to, or None when
        unresolvable (no finding on what cannot be proven)."""
        if depth < 0:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return {expr.value}
        if isinstance(expr, ast.IfExp):
            a = self._kind_strings(expr.body, fi, depth, visited)
            b = self._kind_strings(expr.orelse, fi, depth, visited)
            if a is None or b is None:
                return None
            return a | b
        if isinstance(expr, ast.Name):
            key = (fi.key, expr.id)
            if key in visited:
                return None
            visited.add(key)
            defs = self._defs(fi).get(expr.id, ())
            if defs:
                out: set = set()
                for value, how, _c in defs:
                    if how is None:
                        got = self._kind_strings(value, fi, depth,
                                                 visited)
                    elif isinstance(how, int):
                        got = self._unpacked_strings(value, how, fi,
                                                     depth, visited)
                    else:  # ("iter", unpack index | None)
                        got = self._iterated_strings(value, how[1], fi,
                                                     depth, visited)
                    if got is None:
                        return None
                    out |= got
                return out
            if expr.id in fi.param_names():
                out = set()
                resolved_any = False
                for cfi, call in self._graph.callers_of(fi.key):
                    arg = ProgramGraph.call_arg_for_param(call, fi,
                                                          expr.id)
                    if arg is None:
                        continue
                    got = self._kind_strings(arg, cfi, depth - 1,
                                             visited)
                    if got is None:
                        return None
                    out |= got
                    resolved_any = True
                return out if resolved_any else None
        return None

    def _iterated_strings(self, iterable, idx, fi, depth,
                          visited) -> Optional[set]:
        """Strings a for-loop target takes from a LITERAL iterable."""
        if isinstance(iterable, (ast.Tuple, ast.List)):
            out: set = set()
            for elt in iterable.elts:
                got = (self._unpacked_strings(elt, idx, fi, depth,
                                              visited)
                       if idx is not None
                       else self._kind_strings(elt, fi, depth, visited))
                if got is None:
                    return None
                out |= got
            return out
        return None

    def _unpacked_strings(self, value, idx, fi, depth,
                          visited) -> Optional[set]:
        """Element ``idx`` of a literal tuple/list (direct unpack:
        ``kind, fn = ("a", f1)``)."""
        if isinstance(value, (ast.Tuple, ast.List)) \
                and idx < len(value.elts):
            return self._kind_strings(value.elts[idx], fi, depth,
                                      visited)
        return None
