"""OL9 — blocking-under-lock: unbounded waits while holding a lock.

A lock held across a blocking call turns one slow operation into a
convoy: every thread that needs the lock — the engine step loop, the
/metrics HTTP thread, a heartbeat — stalls behind it.  This is the
hazard class the PR 8 stall watchdog exists to catch at runtime; OL9
catches it in review.  Scope is ``HOT_PATHS`` plus ``THREADED_PATHS``
(the manifest's census of modules with real cross-thread locking).

Flagged while a lock is lexically held (directly, or one intra-module
call away — the helper that hides the ``recv`` still runs under the
caller's lock):

- device syncs: ``jax.device_get`` / ``.block_until_ready()`` — the
  worst case: the lock is held until the device queue drains;
- jit dispatch (callee named ``*jit*``): a shape-cache miss compiles
  for seconds with the lock held;
- sleeps: ``time.sleep`` / injected ``self._sleep``;
- socket/channel I/O: ``.recv``/``.recv_into``/``.accept``/
  ``.connect``/``create_connection``/``.sendall`` (and ``.send``/
  ``.put``/``.get``/``.join``/``.result`` on receivers whose names say
  socket/channel/connector/store/queue/thread/future);
- ``.wait(...)`` on anything that is NOT the lock being held
  (``Condition.wait`` on the held condition releases it — that idiom
  is fine and recognized);
- file I/O (``open``) and ``subprocess.*``.

Some holds are the entire point of the lock (a mutex serializing one
socket's request/response pairing); those carry a suppression with the
reason::

    resp = _recv_frame(sock)  # omnilint: disable=OL9 - lock IS the
    # socket serializer: send..recv must pair
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import (
    HOT_PATHS,
    THREADED_PATHS,
    in_scope,
)
from vllm_omni_tpu.analysis.rules._jitinfo import dotted
from vllm_omni_tpu.analysis.rules._lockinfo import (
    callee_terminal,
    held_locks,
    iter_local_functions,
    lock_id,
    receiver_terminal,
    resolve_local_call,
)

_JIT_NAME = re.compile(r"(?:^|_)jit(?:_|$|ted)")
_SOCKETISH_RECV = re.compile(
    r"(?i)(sock|chan|conn|pipe|stream|client)")
_QUEUEISH_RECV = re.compile(
    r"(?i)(connector|store|queue|_q$|chan|inbox|intake)")
_THREADISH_RECV = re.compile(r"(?i)(thread|proc|worker)")
_FUTUREISH_RECV = re.compile(r"(?i)(fut|promise)")

# attr names that block regardless of receiver
_ALWAYS_BLOCKING_ATTRS = {
    "block_until_ready": "device sync",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "accept": "socket accept",
    "create_connection": "socket connect",
    "sleep": "sleep",
    "_sleep": "sleep (injected)",
}


def blocking_reason(call: ast.Call,
                    held: list[str],
                    ctx: FileContext) -> Optional[str]:
    """Why this call can block, or None.  ``held`` is the lexical lock
    stack at the call (needed to bless Condition.wait on the held cv)."""
    fn = dotted(call.func) or ""
    attr = callee_terminal(call.func) or ""
    recv = receiver_terminal(call.func) or ""

    if fn in ("jax.device_get", "jax.block_until_ready"):
        return "device sync"
    if fn == "time.sleep":
        return "sleep"
    if fn == "open":
        return "file I/O"
    if fn.startswith("subprocess."):
        return "subprocess"
    if attr in _ALWAYS_BLOCKING_ATTRS:
        return _ALWAYS_BLOCKING_ATTRS[attr]
    if _JIT_NAME.search(attr) or _JIT_NAME.search(fn.replace(".", "_")):
        return "jit dispatch (compiles on cache miss)"
    if attr in ("wait", "wait_for"):
        # waiting on the condition you hold RELEASES it — the one
        # blessed blocking-under-lock idiom
        wid = lock_id(call.func.value, ctx) \
            if isinstance(call.func, ast.Attribute) else None
        if wid is not None and wid in held:
            return None
        return f"wait on '{recv or '?'}'"
    if attr == "connect" and _SOCKETISH_RECV.search(recv):
        return "socket connect"
    if attr in ("send", "sendall") and _SOCKETISH_RECV.search(recv):
        return "socket send"
    if attr in ("put", "get") and _QUEUEISH_RECV.search(recv):
        return "connector/queue round trip"
    if attr == "join" and _THREADISH_RECV.search(recv):
        return "thread join"
    if attr == "result" and _FUTUREISH_RECV.search(recv):
        return "future wait"
    return None


class BlockingUnderLockRule(Rule):
    id = "OL9"
    name = "blocking-under-lock"
    node_types = (ast.Call,)

    def __init__(self):
        self._locked_calls: list[tuple[ast.Call, list[str]]] = []
        self._directly_flagged: set[int] = set()

    def applies(self, ctx: FileContext) -> bool:
        return in_scope(ctx.path, HOT_PATHS) \
            or in_scope(ctx.path, THREADED_PATHS)

    def visit(self, node: ast.Call,
              ctx: FileContext) -> Iterable[Finding]:
        held = held_locks(node, ctx)
        if not held:
            return
        self._locked_calls.append((node, held))
        reason = blocking_reason(node, held, ctx)
        if reason is not None:
            self._directly_flagged.add(id(node))
            name = dotted(node.func) or callee_terminal(node.func) or "?"
            yield ctx.finding(
                self.id, node,
                f"{reason} ({name}) while holding "
                f"{'/'.join(sorted(set(held)))} — every thread needing "
                "the lock convoys behind it; move the call outside the "
                "lock or suppress with the reason the hold is required")

    # --------------------------------------------------------------- finish
    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        """Second face: a call *into a same-module helper* made under a
        lock, where the helper's unlocked body blocks."""
        if not self._locked_calls:
            return
        blocking_fns = self._helper_blockers(ctx)
        if not blocking_fns:
            return
        for call, held in self._locked_calls:
            if id(call) in self._directly_flagged:
                continue
            target = resolve_local_call(call, ctx)
            reason = blocking_fns.get(target)
            if reason is None:
                continue
            name = dotted(call.func) or callee_terminal(call.func)
            yield ctx.finding(
                self.id, call,
                f"call to {name}(), which performs {reason}, while "
                f"holding {'/'.join(sorted(set(held)))} — the helper's "
                "blocking call runs under the caller's lock")

    def _helper_blockers(self, ctx: FileContext) -> dict:
        """function key -> blocking reason reachable through its (and
        its local callees') *unlocked* body.  Blocking calls already
        under a lock inside the helper were flagged at their own site —
        propagating them too would double-report."""
        direct: dict[str, Optional[str]] = {}
        calls: dict[str, set] = {}
        for key, fn in iter_local_functions(ctx):
            reason = None
            callees: set = set()
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                inner_held = held_locks(sub, ctx)
                if inner_held:
                    continue
                r = blocking_reason(sub, inner_held, ctx)
                if r is not None and reason is None:
                    reason = r
                t = resolve_local_call(sub, ctx)
                if t is not None and t != key:
                    callees.add(t)
            direct[key] = reason
            calls[key] = callees
        # propagate through unlocked local calls to fixpoint
        changed = True
        while changed:
            changed = False
            for k, callees in calls.items():
                if direct.get(k) is not None:
                    continue
                for c in callees:
                    r = direct.get(c)
                    if r is not None:
                        direct[k] = f"{r} (via {c})"
                        changed = True
                        break
        return {k: v for k, v in direct.items() if v is not None}
