"""OL4 — wall-clock-in-trace: timing jax dispatch without a sync.

jax dispatch is asynchronous: ``fn(x)`` returns a future-like array the
moment the computation is *enqueued*.  A ``perf_counter()`` pair around
it measures enqueue latency (microseconds) instead of execution
(milliseconds) — benchmark numbers that look 100× too good and drift
with queue depth.  The fix is ``jax.block_until_ready(out)`` (or
``out.block_until_ready()``) before reading the second timestamp.

Scope is the BENCH_PATHS manifest (bench.py, benchmarks/, metrics/).
The rule fires per function that (a) reads the clock at least twice —
i.e. measures a duration, (b) dispatches jax work (a ``jnp.``/``jax.``
call in the body), and (c) never syncs via ``block_until_ready``.
Functions that time host-side phases of an already-synchronous API
(e.g. an engine step that device_gets internally) suppress with a
reason or get baselined.
"""

from __future__ import annotations

import ast
from typing import Iterable

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import BENCH_PATHS, in_scope
from vllm_omni_tpu.analysis.rules._jitinfo import dotted

_CLOCKS = ("time.time", "time.perf_counter", "time.monotonic",
           "perf_counter", "monotonic")


class WallClockRule(Rule):
    id = "OL4"
    name = "wall-clock-in-trace"
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def applies(self, ctx: FileContext) -> bool:
        return in_scope(ctx.path, BENCH_PATHS)

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        # analyze this def's OWN body: timing in a nested def is that
        # def's responsibility (it gets its own visit)
        clock_calls, has_jax, has_sync = [], False, False
        for sub in ast.walk(node):
            if sub is not node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # ast.walk still descends; filter below
            if not isinstance(sub, ast.Call):
                continue
            if self._owner(sub, node, ctx) is not node:
                continue
            fn = dotted(sub.func) or ""
            if fn in _CLOCKS:
                clock_calls.append(sub)
            elif fn.startswith(("jnp.", "jax.")) \
                    or fn.endswith(".block_until_ready"):
                has_jax = True
            if fn == "jax.block_until_ready" \
                    or fn.endswith(".block_until_ready"):
                has_sync = True
        if len(clock_calls) >= 2 and has_jax and not has_sync:
            yield ctx.finding(
                self.id, clock_calls[0],
                "wall-clock duration around jax dispatch without "
                "block_until_ready — async dispatch means this measures "
                "enqueue, not execution; sync the result before the "
                "second timestamp")

    @staticmethod
    def _owner(sub: ast.AST, fn_node: ast.AST, ctx: FileContext):
        """Nearest enclosing def of ``sub`` (to scope calls to the def
        being visited, not its nested defs)."""
        for anc in ctx.ancestors(sub):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None
