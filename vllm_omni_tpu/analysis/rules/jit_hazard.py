"""OL1 — jit-hazard: Python-level control flow on traced values, bad
static declarations, and jit re-wrapping inside loops.

``jax.jit`` stages a function out ONCE per input signature; Python
constructs that inspect traced *values* either crash at trace time
(``TracerBoolConversionError``) or silently bake one branch into every
future call.  Shape/dtype inspection is static under tracing and is
deliberately NOT flagged (``x.shape[i]``, ``x.ndim``, ``len(x)``,
``is None`` arity checks are how bucketed dispatch is supposed to
work) — the rule fires on the value-dependent cases a stock linter
cannot tell apart from them:

- ``if x:`` / ``while x > 0:`` / ternaries / asserts reading a traced
  argument's value (fix: ``lax.cond`` / ``jnp.where``, or declare the
  argument static)
- ``for _ in x`` / ``range(x)`` / ``int(x)`` / ``bool(x)`` /
  ``float(x)`` on a traced argument (needs ``static_argnames``)
- ``static_argnames``/``static_argnums`` referencing a parameter the
  wrapped function does not have (silently ignored by jax at best)
- list/dict/set literals passed in a static position (unhashable →
  TypeError at dispatch)
- ``jax.jit(...)`` / ``functools.partial(jax.jit, ...)`` evaluated
  inside a loop: every iteration builds a fresh wrapper with an empty
  compile cache — the classic accidental recompile-per-step
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.rules._jitinfo import (
    ModuleJitIndex,
    build_index,
    dotted,
    jit_call_info,
    param_names,
    static_names,
)

# attributes that are static (Python values) on a tracer
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "sharding")
_VALUE_CASTS = ("int", "bool", "float", "range")


def _parents_within(root: ast.AST) -> dict[ast.AST, ast.AST]:
    p = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            p[child] = node
    return p


def _traced_value_uses(test: ast.AST, traced: set[str]) -> list[str]:
    """Traced argument names whose VALUE the expression reads (static
    shape/dtype/len/is-None inspection exempted)."""
    parents = _parents_within(test)
    hits = []
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced
                and isinstance(node.ctx, ast.Load)):
            continue
        parent = parents.get(node)
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _STATIC_ATTRS):
            continue
        if (isinstance(parent, ast.Call) and dotted(parent.func) == "len"
                and node in parent.args):
            continue
        if (isinstance(parent, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops)
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in parent.comparators)):
            continue
        if node.id not in hits:
            hits.append(node.id)
    return hits


class JitHazardRule(Rule):
    id = "OL1"
    name = "jit-hazard"
    node_types = (ast.Call,)

    def __init__(self):
        self._index: Optional[ModuleJitIndex] = None
        self._seen_calls: list[ast.Call] = []

    def _idx(self, ctx: FileContext) -> ModuleJitIndex:
        if self._index is None:
            self._index = build_index(ctx.tree)
        return self._index

    # ------------------------------------------------------------- visit
    def visit(self, node: ast.Call, ctx: FileContext) -> Iterable[Finding]:
        if jit_call_info(node) is not None:
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                    yield ctx.finding(
                        self.id, node,
                        "jax.jit wrapper built inside a loop — a fresh "
                        "compile cache per iteration; hoist the wrap out "
                        "of the loop")
                    break
        else:
            self._seen_calls.append(node)

    # ------------------------------------------------------------ finish
    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        idx = self._idx(ctx)
        seen_wraps: set[int] = set()
        seen_defs: dict[int, tuple[ast.FunctionDef, set[str]]] = {}
        for wrap, fn in idx.jitted.values():
            if id(wrap.node) not in seen_wraps:
                seen_wraps.add(id(wrap.node))
                yield from self._check_static_decl(wrap, fn, ctx)
            if fn is not None:
                prev = seen_defs.get(id(fn))
                statics = static_names(wrap, fn)
                if prev is None:
                    seen_defs[id(fn)] = (fn, statics)
                else:
                    prev[1].intersection_update(statics)
        for fn, statics in seen_defs.values():
            yield from self._check_traced_flow(fn, statics, ctx)
        yield from self._check_static_call_sites(idx, ctx)

    def _check_static_decl(self, wrap, fn, ctx) -> Iterable[Finding]:
        if fn is None:
            return
        params = param_names(fn)
        for name in wrap.static_argnames:
            if name not in params:
                yield ctx.finding(
                    self.id, wrap.node,
                    f"static_argnames names parameter '{name}' which "
                    f"'{fn.name}' does not have")
        if fn.args.vararg is None:
            for i in wrap.static_argnums:
                if i >= len(params) or i < -len(params):
                    yield ctx.finding(
                        self.id, wrap.node,
                        f"static_argnums index {i} out of range for "
                        f"'{fn.name}' ({len(params)} parameters)")

    def _check_static_call_sites(self, idx, ctx) -> Iterable[Finding]:
        for call in self._seen_calls:
            name = dotted(call.func)
            entry = idx.jitted.get(name or "")
            if entry is None:
                continue
            wrap, fn = entry
            static_pos = set(wrap.static_argnums)
            params = param_names(fn) if fn is not None else []
            for sn in wrap.static_argnames:
                if sn in params:
                    static_pos.add(params.index(sn))
            for pos in static_pos:
                if 0 <= pos < len(call.args) and isinstance(
                        call.args[pos], (ast.List, ast.Dict, ast.Set)):
                    kind = type(call.args[pos]).__name__.lower()
                    yield ctx.finding(
                        self.id, call.args[pos],
                        f"non-hashable {kind} literal passed for static "
                        f"argument {pos} of '{name}' — TypeError at "
                        "dispatch; pass a tuple")
            for kw in call.keywords:
                if kw.arg in wrap.static_argnames and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    kind = type(kw.value).__name__.lower()
                    yield ctx.finding(
                        self.id, kw.value,
                        f"non-hashable {kind} literal passed for static "
                        f"argument '{kw.arg}' of '{name}' — TypeError at "
                        "dispatch; pass a tuple")

    # ------------------------------------------- traced control-flow scan
    def _check_traced_flow(self, fn: ast.FunctionDef, statics: set[str],
                           ctx: FileContext) -> Iterable[Finding]:
        traced = {p for p in param_names(fn)
                  if p not in statics and p not in ("self", "cls")}
        if traced:
            yield from self._scan(fn.body, traced, fn.name, ctx)

    def _scan(self, body, traced: set[str], fn_name: str,
              ctx: FileContext) -> Iterable[Finding]:
        for node in body:
            yield from self._scan_node(node, traced, fn_name, ctx)

    def _scan_node(self, node, traced, fn_name, ctx) -> Iterable[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its params shadow, but closed-over jit args
            # are STILL traced inside it (scan/cond/vmap bodies)
            inner = traced - set(param_names(node))
            yield from self._scan(node.body, inner, fn_name, ctx)
            return
        if isinstance(node, ast.Lambda):
            inner = traced - set(param_names(node))
            yield from self._scan_node(node.body, inner, fn_name, ctx)
            return
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            kind = {"If": "if", "While": "while", "IfExp": "ternary",
                    "Assert": "assert"}[type(node).__name__]
            for name in _traced_value_uses(node.test, traced):
                yield ctx.finding(
                    self.id, node,
                    f"Python {kind} on the value of traced argument "
                    f"'{name}' in jitted '{fn_name}' — fails or "
                    "specializes at trace time; use lax.cond/jnp.where "
                    "or declare it static")
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.iter, ast.Name) and node.iter.id in traced:
                yield ctx.finding(
                    self.id, node,
                    f"Python for-loop iterates traced argument "
                    f"'{node.iter.id}' in jitted '{fn_name}' — unrolls "
                    "or fails at trace time; use lax.scan/fori_loop")
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in _VALUE_CASTS:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in traced:
                        yield ctx.finding(
                            self.id, node,
                            f"'{fname}()' on traced argument '{arg.id}' "
                            f"in jitted '{fn_name}' — concretizes a "
                            "tracer; declare it in static_argnames")
        for child in ast.iter_child_nodes(node):
            yield from self._scan_node(child, traced, fn_name, ctx)
