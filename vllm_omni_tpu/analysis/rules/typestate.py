"""OL13 — typestate: declared state machines checked at mutation sites.

The ``STATE_MACHINES`` manifest (analysis/manifest.py) declares the
multi-step protocols this repo's reviews keep re-deriving by hand: the
control-plane operation ladder (draining -> flipping -> readmitting,
with bounded retry edges), the alert lifecycle ring
(inactive -> pending -> firing -> resolved), and replica rotation
membership as a two-state flag machine.  The rule checks two things:

- **transition validity** — every mutation site of a declared state
  field (attribute assignment, or a call to the machine's blessed
  ``transition_fn``) whose source state is recoverable from an
  enclosing ``if obj.field == STATE`` comparison must follow a
  declared edge; any resolvable target must be a declared state.
  Module-level ``STATE_X = "literal"`` constants resolve; aliases map
  writer vocabulary ("resolved") to canonical states.
- **the generalized PR 12 abort check** — a mutation to a
  NON-terminal state followed by a CFG path that crosses an exception
  edge, gets swallowed, and exits the function normally with no
  recovery reachable from the handler side strands the object: the
  function reports success while the protocol can never finish
  (exactly how an aborted re-role once left a live donor drained
  forever).  Recovery is reaching any declared ``recover`` call, a
  terminal-state write to the same field, or a ``transition_fn`` call
  to a terminal state.  Escaping (un-swallowed) exceptions are NOT
  flagged: the obligation propagates, and the frame that swallows is
  the one judged.

Exempt by construction: ``__init__`` (the initial state write), the
carrier class's own methods, and the ``transition_fn`` body (it is
the one blessed mutation site).  The machine applies to a file that
defines or imports the carrier class (or its module) — or, with
``match: "field"``, to any file assigning the field, for distinctive
fields whose carrier instances travel between modules.
"""

from __future__ import annotations

import ast
from dataclasses import replace
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import (
    FileContext,
    Finding,
    FunctionCFG,
    ProgramGraph,
    Rule,
    cfg_leak_path,
    describe_path,
    scan_calls,
)
from vllm_omni_tpu.analysis.manifest import STATE_MACHINES
from vllm_omni_tpu.analysis.rules._lockinfo import callee_terminal


def _module_constants(tree: ast.Module) -> dict:
    """Module-level ``NAME = "literal"`` string constants."""
    out: dict = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.value
    return out


class TypestateRule(Rule):
    id = "OL13"
    name = "typestate"
    node_types = ()
    # overridable in tests
    machines = STATE_MACHINES

    # -------------------------------------------------------------- finish
    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        consts = _module_constants(ctx.tree)
        defined = {n.name for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)}
        imported_names: set = set()
        imported_mods: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                imported_names.update(a.asname or a.name
                                      for a in node.names)
                if node.module:
                    imported_mods.add(node.module)
            elif isinstance(node, ast.Import):
                imported_mods.update(a.name for a in node.names)
        self._cfgs: dict = {}
        out: list = []
        for mach in self.machines:
            if self._applicable(mach, ctx, defined, imported_names,
                                imported_mods):
                out.extend(self._check_machine(mach, ctx, consts))
        return out

    def _applicable(self, mach, ctx, defined, imported_names,
                    imported_mods) -> bool:
        path, _, qual = mach["class"].partition("::")
        cls = qual.split(".")[-1]
        if ctx.path == path or cls in defined or cls in imported_names:
            return True
        if ProgramGraph.module_name(path) in imported_mods:
            return True
        if mach.get("match") == "field":
            field = mach["field"]
            return any(isinstance(n, ast.Attribute) and n.attr == field
                       and isinstance(n.ctx, ast.Store)
                       for n in ast.walk(ctx.tree))
        return False

    # ----------------------------------------------------------- resolving
    def _resolve_state(self, mach, expr, consts) -> Optional[str]:
        """State name an expression resolves to, through module
        constants, flag-machine values, and aliases.  None when the
        value is not statically known."""
        val = None
        if isinstance(expr, ast.Constant):
            val = expr.value
        elif isinstance(expr, ast.Name):
            val = consts.get(expr.id)
            if val is None:
                return None
        else:
            return None
        values = mach.get("values")
        if values is not None and val in values:
            return values[val]
        if not isinstance(val, str):
            return None
        return mach.get("aliases", {}).get(val, val)

    def _governing_source(self, mach, node, ctx,
                          consts) -> Optional[str]:
        """Source state from the innermost enclosing
        ``if obj.field == STATE`` the mutation sits in the BODY of."""
        field = mach["field"]
        cur = node
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(anc, ast.If) and cur in anc.body:
                for cmp_ in ast.walk(anc.test):
                    if (isinstance(cmp_, ast.Compare)
                            and len(cmp_.ops) == 1
                            and isinstance(cmp_.ops[0], ast.Eq)
                            and isinstance(cmp_.left, ast.Attribute)
                            and cmp_.left.attr == field):
                        src = self._resolve_state(
                            mach, cmp_.comparators[0], consts)
                        if src is not None:
                            return src
            cur = anc
        return None

    # ------------------------------------------------------------ checking
    def _mutations(self, mach, ctx, consts) -> list:
        """(anchor node, target state or None, enclosing fn) for every
        judged mutation site of the machine's field."""
        field = mach["field"]
        fn_name = mach.get("transition_fn")
        target_arg = mach.get("target_arg", 1)
        cls = mach["class"].partition("::")[2].split(".")[-1]
        out = []
        for node in ast.walk(ctx.tree):
            anchor = value = None
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and t.attr == field:
                        anchor, value = t, node.value
                        break
            elif (fn_name and isinstance(node, ast.Call)
                    and callee_terminal(node.func) == fn_name
                    and len(node.args) > target_arg):
                anchor, value = node, node.args[target_arg]
            if anchor is None or value is None:
                continue
            fn = ctx.enclosing_function(anchor)
            if fn is not None:
                if fn.name in ("__init__", fn_name):
                    continue
                in_carrier = any(
                    isinstance(a, ast.ClassDef) and a.name == cls
                    for a in ctx.ancestors(fn))
                # methods of the carrier class ARE the machine — but a
                # closure or unrelated nested class stays judged
                if in_carrier and ctx.enclosing_function(fn) is None:
                    continue
            state = self._resolve_state(mach, value, consts)
            out.append((anchor, state, fn))
        return out

    def _recover_fn(self, mach, cfg, consts):
        """Per-node recovery predicate for the abort check."""
        field = mach["field"]
        recover = set(mach.get("recover", ()))
        terminal = set(mach.get("terminal", ()))
        fn_name = mach.get("transition_fn")
        target_arg = mach.get("target_arg", 1)

        def rec(idx: int) -> bool:
            node = cfg.nodes[idx]
            for call in scan_calls(node.owned):
                term = callee_terminal(call.func)
                if term in recover:
                    return True
                if (fn_name and term == fn_name
                        and len(call.args) > target_arg):
                    st = self._resolve_state(mach,
                                             call.args[target_arg],
                                             consts)
                    if st in terminal:
                        return True
            stmt = node.stmt
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Attribute) and t.attr == field:
                        st = self._resolve_state(mach, stmt.value,
                                                 consts)
                        if st in terminal:
                            return True
            return False

        return rec

    def _check_machine(self, mach, ctx,
                       consts) -> Iterable[Finding]:
        name = mach["name"]
        field = mach["field"]
        states = set(mach.get("states", ()))
        transitions = mach.get("transitions", {})
        terminal = set(mach.get("terminal", ()))
        for anchor, state, fn in self._mutations(mach, ctx, consts):
            if state is None:
                continue  # not statically resolvable: out of model
            if state not in states:
                yield ctx.finding(
                    "OL13", anchor,
                    f"typestate '{name}': {field} assigned unknown "
                    f"state {state!r} (declared: "
                    f"{', '.join(sorted(states))})")
                continue
            src = self._governing_source(mach, anchor, ctx, consts)
            if src is not None and src in transitions \
                    and state not in transitions[src] and state != src:
                allowed = ", ".join(transitions[src]) or "none"
                yield ctx.finding(
                    "OL13", anchor,
                    f"typestate '{name}': invalid transition {src!r} "
                    f"-> {state!r} for {field} (allowed from {src!r}: "
                    f"{allowed})")
                continue
            if fn is None or state in terminal:
                continue
            # the generalized PR 12 abort check
            cfg = self._cfgs.get(id(fn))
            if cfg is None:
                cfg = self._cfgs[id(fn)] = FunctionCFG(fn)
            stmt = ctx.enclosing_statement(anchor)
            rec = self._recover_fn(mach, cfg, consts)
            for idx, node in enumerate(cfg.nodes):
                if node.stmt is not stmt:
                    continue
                path = cfg_leak_path(cfg, idx, rec, "swallow")
                if path is None:
                    continue
                recs = ", ".join(mach.get("recover", ())) or \
                    "no recover vocabulary declared"
                f = ctx.finding(
                    "OL13", anchor,
                    f"typestate '{name}': {field} set to non-terminal "
                    f"{state!r} and an exception path is swallowed "
                    f"with no recovery ({recs}) reachable — the "
                    f"object exits the protocol stranded")
                yield replace(f,
                              trace=describe_path(cfg, path, "swallow"))
                break
