"""OL2 — host-sync: device→host transfers inside hot-path modules.

On TPU every ``.item()`` / ``jax.device_get`` / ``np.asarray(jax_expr)``
/ ``float(jax_expr)`` blocks the host until the device queue drains —
one stray sync in the decode loop serializes dispatch and stalls every
in-flight request (the async-dispatch win multi-step decode exists to
protect).  Scope is the ``HOT_PATHS`` manifest (core/, ops/, sample/,
worker/, engine/); cold modules sync freely.

Deliberate batch-boundary syncs (the engine DOES need the sampled
tokens) carry a same-line suppression with the reason::

    toks = jax.device_get(toks)  # omnilint: disable=OL2 - batch boundary

Detected forms:

- ``x.item()``
- ``jax.device_get(...)`` / ``jax.device_get(...)`` via any alias
  written as an attribute of ``jax``
- ``np.asarray(expr)`` / ``np.array(expr)`` where ``expr`` contains a
  ``jnp.`` / ``jax.`` call (implicit transfer of a live device array)
- ``float(expr)`` / ``int(expr)`` / ``bool(expr)`` over a ``jnp.``/
  ``jax.`` expression (implicit transfer + scalarization)
- ``if arr:`` / ``while arr:`` / ``not arr`` where ``arr`` was assigned
  from a ``jnp.``/``jax.`` call earlier in the same function (implicit
  ``__bool__`` → sync)
"""

from __future__ import annotations

import ast
from typing import Iterable

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import HOT_PATHS, in_scope
from vllm_omni_tpu.analysis.rules._jitinfo import dotted

_CASTS = ("float", "int", "bool")
_NP_COERCE = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")


def _jax_rooted(node: ast.AST) -> bool:
    """Does the expression subtree contain a jnp./jax. qualified use?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jnp", "jax"):
            return True
    return False


def _has_device_get(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and dotted(sub.func) == "jax.device_get":
            return True
    return False


class HostSyncRule(Rule):
    id = "OL2"
    name = "host-sync"
    node_types = (ast.Call, ast.Assign, ast.If, ast.While, ast.UnaryOp)

    def __init__(self):
        # (function node id or None) -> names assigned from jax exprs
        self._arrayish: dict = {}
        self._bool_tests: list = []  # (name, test node, scope id)

    def applies(self, ctx: FileContext) -> bool:
        return in_scope(ctx.path, HOT_PATHS)

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            yield from self._visit_call(node, ctx)
        elif isinstance(node, ast.Assign):
            self._track_assign(node, ctx)
        elif isinstance(node, (ast.If, ast.While)):
            self._track_bool(node.test, node, ctx)
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            self._track_bool(node.operand, node, ctx)

    def _visit_call(self, node: ast.Call, ctx) -> Iterable[Finding]:
        fn = dotted(node.func)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            yield ctx.finding(
                self.id, node,
                ".item() forces a device sync in a hot-path module — "
                "keep values on device or batch the transfer")
            return
        if fn == "jax.device_get":
            yield ctx.finding(
                self.id, node,
                "jax.device_get in a hot-path module blocks on the "
                "device queue — hoist to a batch boundary or overlap "
                "with the next dispatch")
            return
        if fn in _NP_COERCE and node.args \
                and _jax_rooted(node.args[0]) \
                and not _has_device_get(node.args[0]):
            yield ctx.finding(
                self.id, node,
                f"{fn} over a jax expression is an implicit device→host "
                "transfer — make the sync explicit (jax.device_get) at "
                "a batch boundary")
            return
        if fn in _CASTS and node.args and _jax_rooted(node.args[0]):
            yield ctx.finding(
                self.id, node,
                f"{fn}() over a jax expression scalarizes through an "
                "implicit device sync — keep the compare/accumulate on "
                "device (jnp) or sync once per batch")

    # ------------------------------------------------ implicit bool flow
    def _scope(self, node, ctx):
        fn = ctx.enclosing_function(node)
        return id(fn) if fn is not None else None

    def _track_assign(self, node: ast.Assign, ctx) -> None:
        if not (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            return
        callee = dotted(node.value.func) or ""
        if callee.startswith(("jnp.", "jax.")) \
                and not callee.startswith(("jax.device_get",)):
            self._arrayish.setdefault(self._scope(node, ctx), {})[
                node.targets[0].id] = node.lineno

    def _track_bool(self, test, anchor, ctx) -> None:
        if isinstance(test, ast.Name):
            self._bool_tests.append((test.id, anchor,
                                     self._scope(anchor, ctx)))

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        for name, anchor, scope in self._bool_tests:
            assigned = self._arrayish.get(scope, {}).get(name)
            if assigned is not None and assigned < anchor.lineno:
                yield ctx.finding(
                    self.id, anchor,
                    f"implicit bool of device array '{name}' forces a "
                    "sync (and raises under jit) — compare explicitly "
                    "and sync once, or keep the predicate on device")
