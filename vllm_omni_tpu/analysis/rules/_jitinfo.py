"""Shared jit-wrapper introspection for the OL1/OL3 rule families.

Recognizes the wrapping idioms this codebase actually uses (see
worker/model_runner.py) without importing jax:

- ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorators
- ``name = jax.jit(fn, ...)`` assignments
- ``jit2 = functools.partial(jax.jit, donate_argnums=(2,))`` factories,
  later applied as ``self._fn = jit2(fn)``
- factory *functions* whose return value is a jit wrap
  (``def wrap(f): ... return jax.jit(sm, donate_argnums=(2,))``),
  later applied as ``self._fn = wrap(fn, ...)``
- plain aliasing of an already-known jitted name
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

JIT_CALLABLES = ("jax.jit", "jit", "pjit", "jax.pjit")
PARTIAL_CALLABLES = ("functools.partial", "partial")


def dotted(node: ast.AST) -> Optional[str]:
    """"a.b.c" for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _literal_ints(node: ast.AST) -> Optional[tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None


def _literal_strs(node: ast.AST) -> Optional[tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None


@dataclass
class JitWrap:
    """Static/donate argument declarations extracted from one jit wrap."""

    node: ast.AST
    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    donate_argnums: tuple[int, ...] = ()
    donate_argnames: tuple[str, ...] = ()

    def merged(self, other: "JitWrap") -> "JitWrap":
        """Factory kwargs + application kwargs (partial semantics)."""
        return JitWrap(
            node=other.node,
            static_argnums=self.static_argnums + other.static_argnums,
            static_argnames=self.static_argnames + other.static_argnames,
            donate_argnums=self.donate_argnums + other.donate_argnums,
            donate_argnames=self.donate_argnames + other.donate_argnames,
        )


def _wrap_from_keywords(call: ast.Call) -> JitWrap:
    wrap = JitWrap(node=call)
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            wrap.static_argnums = _literal_ints(kw.value) or ()
        elif kw.arg == "static_argnames":
            wrap.static_argnames = _literal_strs(kw.value) or ()
        elif kw.arg == "donate_argnums":
            wrap.donate_argnums = _literal_ints(kw.value) or ()
        elif kw.arg == "donate_argnames":
            wrap.donate_argnames = _literal_strs(kw.value) or ()
    return wrap


def jit_call_info(call: ast.Call) -> Optional[JitWrap]:
    """JitWrap if ``call`` is ``jax.jit(...)`` or
    ``functools.partial(jax.jit, ...)``, else None."""
    fn = dotted(call.func)
    if fn in JIT_CALLABLES:
        return _wrap_from_keywords(call)
    if fn in PARTIAL_CALLABLES and call.args \
            and dotted(call.args[0]) in JIT_CALLABLES:
        return _wrap_from_keywords(call)
    return None


def decorator_jit_info(node: ast.AST) -> Optional[JitWrap]:
    """JitWrap if a def's decorator expression is a jit wrap."""
    if dotted(node) in JIT_CALLABLES:
        return JitWrap(node=node)
    if isinstance(node, ast.Call):
        return jit_call_info(node)
    return None


@dataclass
class ModuleJitIndex:
    """Module-wide map of jit wrappers, built in one prepass.

    - ``jitted``: callable dotted-name -> (JitWrap, wrapped FunctionDef
      or None) for every name known to be a jitted function
    - ``defs``: function name -> FunctionDef (last definition wins)
    """

    jitted: dict[str, tuple[JitWrap, Optional[ast.FunctionDef]]] = field(
        default_factory=dict)
    defs: dict[str, ast.FunctionDef] = field(default_factory=dict)


def _assign_target_names(stmt: ast.Assign) -> list[str]:
    names = []
    for t in stmt.targets:
        d = dotted(t)
        if d:
            names.append(d)
    return names


def build_index(tree: ast.Module) -> ModuleJitIndex:
    idx = ModuleJitIndex()
    factories: dict[str, JitWrap] = {}        # partial(jax.jit, ...) names
    factory_defs: dict[str, JitWrap] = {}     # defs returning a jit wrap

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.defs[node.name] = node
            wrap = None
            for dec in node.decorator_list:
                wrap = decorator_jit_info(dec)
                if wrap is not None:
                    break
            if wrap is not None:
                idx.jitted[node.name] = (wrap, node)
            else:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) \
                            and isinstance(sub.value, ast.Call):
                        w = jit_call_info(sub.value)
                        if w is not None and (w.donate_argnums
                                              or w.donate_argnames
                                              or w.static_argnums
                                              or w.static_argnames):
                            factory_defs[node.name] = w
                            break

    # assignment pass (separate loop: factories/defs must be complete —
    # ast.walk order does not follow execution order)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        targets = _assign_target_names(node)
        if not targets:
            continue
        callee = dotted(call.func)
        wrap = jit_call_info(call)
        if wrap is not None:
            if call.args and dotted(call.args[0]) in JIT_CALLABLES:
                # name = functools.partial(jax.jit, ...) -> a factory
                for t in targets:
                    factories[t] = wrap
            else:
                # name = jax.jit(fn, ...)
                inner = (idx.defs.get(dotted(call.args[0]) or "")
                         if call.args else None)
                for t in targets:
                    idx.jitted[t] = (wrap, inner)
        elif callee in factories:
            # name = jit2(fn) -> jitted with the factory's kwargs
            base = factories[callee]
            applied = base.merged(_wrap_from_keywords(call))
            inner = (idx.defs.get(dotted(call.args[0]) or "")
                     if call.args else None)
            for t in targets:
                idx.jitted[t] = (applied, inner)
        elif callee in factory_defs:
            # name = wrap(fn, ...) -> jitted with the factory def's kwargs
            inner = (idx.defs.get(dotted(call.args[0]) or "")
                     if call.args else None)
            for t in targets:
                idx.jitted[t] = (factory_defs[callee], inner)

    # plain aliasing: name = known_jitted_name
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and dotted(node.value) in idx.jitted:
            src = idx.jitted[dotted(node.value)]
            for t in _assign_target_names(node):
                idx.jitted.setdefault(t, src)
    return idx


def param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def donate_positions(wrap: JitWrap,
                     fn: Optional[ast.FunctionDef]) -> tuple[int, ...]:
    """Donated positional indices; argnames resolve through the wrapped
    def's signature when it is syntactically visible."""
    pos = list(wrap.donate_argnums)
    if wrap.donate_argnames and fn is not None:
        names = param_names(fn)
        pos += [names.index(n) for n in wrap.donate_argnames if n in names]
    return tuple(sorted(set(pos)))


def static_names(wrap: JitWrap,
                 fn: Optional[ast.FunctionDef]) -> set[str]:
    """Parameter names declared static (argnums resolved through the
    signature when visible)."""
    names = set(wrap.static_argnames)
    if fn is not None:
        params = param_names(fn)
        for i in wrap.static_argnums:
            if 0 <= i < len(params):
                names.add(params[i])
    return names
