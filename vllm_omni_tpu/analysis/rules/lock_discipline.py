"""OL7 — lock-discipline: guarded attributes touched outside their lock.

The concurrency manifest (``analysis/manifest.py`` ``LOCK_GUARDS``)
declares, per class, which attributes are guarded by which lock.  This
rule flags every read/write of a guarded attribute that is not covered
by a ``with self.<lock>`` scope — the missed-lock bug class that
produces torn snapshots and lost updates under the serving stack's
~10 thread-spawn sites (engine loops, heartbeats, watchdog, /metrics
HTTP threads).

Coverage is resolved through **same-class call edges**, because the
codebase's idiom is locked public methods delegating to unlocked
private helpers (``_fail_locked``, ``_connect``, ``_drop_sock``):

- an access is covered when a guarding lock is held *lexically* (an
  enclosing ``with``), or
- the enclosing method *inherits* the lock: it is private (``_``-named)
  and EVERY same-class call site holds the lock (directly or by its own
  inheritance, computed to fixpoint).  Public methods never inherit —
  external callers hold nothing.  Call sites inside ``__init__`` /
  ``__new__`` / ``__del__`` count as holding every lock: construction
  and teardown are single-threaded by contract, which also exempts the
  ubiquitous ``self._x = ...`` initialization writes.

Bare ``.acquire()``/``.release()`` on a manifest lock is flagged too:
lexical analysis (and every reader) can only trust ``with`` discipline.

Deliberate unlocked access (GIL-atomic reads on a monitoring path, a
benign racy gauge) carries a same-line suppression with the reason::

    depth = len(self._ctx)  # omnilint: disable=OL7 - racy read is a gauge
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import LOCK_GUARDS
from vllm_omni_tpu.analysis.rules._lockinfo import held_locks

# construction/teardown run before/after the object is shared; call
# sites inside them count as holding every lock
EXEMPT_METHODS = ("__init__", "__new__", "__del__", "__post_init__")


class LockDisciplineRule(Rule):
    id = "OL7"
    name = "lock-discipline"
    node_types = (ast.ClassDef,)
    # overridable in tests: {"path::Class": {lock_attr: (guarded, ...)}}
    manifest = LOCK_GUARDS

    def applies(self, ctx: FileContext) -> bool:
        prefix = f"{ctx.path}::"
        return any(k.startswith(prefix) for k in self.manifest)

    def visit(self, node: ast.ClassDef,
              ctx: FileContext) -> Iterable[Finding]:
        guards = self.manifest.get(f"{ctx.path}::{node.name}")
        if not guards:
            return
        yield from self._check_class(node, guards, ctx)

    # ------------------------------------------------------------ analysis
    def _check_class(self, cls: ast.ClassDef,
                     guards: dict, ctx: FileContext) -> Iterable[Finding]:
        # lock attr -> graph id ("Class._lock"); attr -> its lock ids
        lock_ids = {la: f"{cls.name}.{la}" for la in guards}
        attr_locks: dict[str, set[str]] = {}
        for la, attrs in guards.items():
            for a in attrs:
                attr_locks.setdefault(a, set()).add(lock_ids[la])

        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}

        # per-method: guarded-attr accesses + same-class call sites
        accesses: dict[str, list] = {m: [] for m in methods}
        call_sites: dict[str, list] = {m: [] for m in methods}
        all_locks = set(lock_ids.values())
        bare_ops: list = []
        for mname, mnode in methods.items():
            for sub in ast.walk(mnode):
                if isinstance(sub, ast.Attribute):
                    if (sub.attr in ("acquire", "release")
                            and isinstance(sub.value, ast.Attribute)
                            and self._attr_owner(sub.value, cls.name)
                            and sub.value.attr in guards):
                        bare_ops.append((sub.value.attr, sub))
                        continue
                    owner = self._attr_owner(sub, cls.name)
                    if owner is None:
                        continue
                    if sub.attr in attr_locks:
                        held = set(held_locks(sub, ctx))
                        accesses[mname].append((sub.attr, sub, held))
                elif isinstance(sub, ast.Call):
                    callee = self._self_call(sub)
                    if callee in methods:
                        held = set(held_locks(sub, ctx))
                        if mname in EXEMPT_METHODS:
                            held = set(all_locks)
                        call_sites[callee].append((mname, held))

        # fixpoint: which locks can a method assume its callers hold?
        inherited: dict[str, set[str]] = {}
        for mname in methods:
            if mname.startswith("_") and not mname.startswith("__") \
                    and call_sites[mname]:
                inherited[mname] = set(all_locks)
            else:
                inherited[mname] = set()
        changed = True
        while changed:
            changed = False
            for mname in methods:
                if not inherited[mname]:
                    continue
                assume: Optional[set] = None
                for caller, held in call_sites[mname]:
                    ctx_locks = held | inherited.get(caller, set())
                    assume = (set(ctx_locks) if assume is None
                              else assume & ctx_locks)
                assume = assume or set()
                if assume != inherited[mname]:
                    inherited[mname] = assume
                    changed = True

        for attr, node in bare_ops:
            yield ctx.finding(
                self.id, node,
                f"bare .{node.attr} on manifest lock '{attr}' — use "
                f"`with self.{attr}:` so lock scope is statically "
                "checkable")

        for mname, mnode in methods.items():
            if mname in EXEMPT_METHODS:
                continue
            for attr, node, held in accesses[mname]:
                effective = held | inherited[mname]
                if attr_locks[attr] & effective:
                    continue
                kind = ("write" if isinstance(node.ctx,
                                              (ast.Store, ast.Del))
                        else "read")
                locks = "/".join(sorted(
                    lid.split(".", 1)[1] for lid in attr_locks[attr]))
                yield ctx.finding(
                    self.id, node,
                    f"{kind} of '{attr}' (guarded by '{locks}' per "
                    "LOCK_GUARDS) outside the lock — wrap in "
                    f"`with self.{locks}:` or make every same-class "
                    "call path hold it")

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _attr_owner(node: ast.Attribute, cls_name: str) -> Optional[str]:
        """'self' / 'cls' / the class's own name when ``node`` is an
        instance-or-class attribute access, else None."""
        if isinstance(node.value, ast.Name) \
                and node.value.id in ("self", "cls", cls_name):
            return node.value.id
        return None

    @staticmethod
    def _self_call(node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in ("self", "cls"):
            return f.attr
        return None
