"""Shared lock-AST vocabulary for the omnirace rules (OL7-OL9).

One place answers three questions every concurrency rule asks:

- *is this expression a lock?*  Heuristic by terminal name (``_lock``,
  ``_cv``, ``_cond``, ``_mutex``, ...), because the codebase's naming
  convention is the only static signal — type inference on
  ``threading.Lock()`` through attributes would be a whole-program
  analysis for the same answer.
- *what is a lock's graph identity?*  ``Class._attr`` for
  ``self._attr``/``cls._attr``/``Class._attr`` (all instances of a
  class share a node — the granularity the runtime validator
  (analysis/runtime.py) uses too, so static and dynamic graphs line
  up), ``<module-stem>._attr`` for module globals.
- *which locks are held HERE?*  The lexical ``with`` stack: every
  ancestor ``with`` whose context expression is a lock.  Lexical scope
  is exact for ``with``-disciplined code (this repo's only acquisition
  idiom; bare ``.acquire()`` is itself a finding under OL7's manifest
  classes) and function-local, so a nested closure executed later
  still reports the locks its own body wraps.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from vllm_omni_tpu.analysis.engine import FileContext

# terminal-name heuristic for "this attribute/variable is a lock"
LOCK_NAME_RE = re.compile(r"(?i)(?:^|_)(?:lock|rlock|cv|cond|condition|"
                          r"mutex|sem|semaphore)$")


def is_lockish_name(name: str) -> bool:
    return bool(LOCK_NAME_RE.search(name))


def enclosing_class(node: ast.AST, ctx: FileContext) -> Optional[ast.ClassDef]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def module_stem(ctx: FileContext) -> str:
    base = os.path.basename(ctx.path)
    stem = base[:-3] if base.endswith(".py") else base
    if stem == "__init__":
        # a package's __init__ is named by the package, not "__init__"
        parent = os.path.basename(os.path.dirname(ctx.path))
        return parent or stem
    return stem


def lock_id(expr: ast.AST, ctx: FileContext) -> Optional[str]:
    """Canonical graph identity of a lock expression, or None when the
    expression is not lock-shaped.  ``traced(...)`` wrappers
    (analysis/runtime.py) are transparent: the identity comes from the
    attribute the wrapped lock is bound to, not the call."""
    if isinstance(expr, ast.Attribute):
        if not is_lockish_name(expr.attr):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls"):
                cls = enclosing_class(expr, ctx)
                owner = cls.name if cls is not None else module_stem(ctx)
                return f"{owner}.{expr.attr}"
            return f"{base.id}.{expr.attr}"
        return None
    if isinstance(expr, ast.Name) and is_lockish_name(expr.id):
        return f"{module_stem(ctx)}.{expr.id}"
    return None


def with_lock_ids(node: ast.With, ctx: FileContext) -> list[str]:
    """Lock identities acquired by one ``with`` statement."""
    out = []
    for item in node.items:
        lid = lock_id(item.context_expr, ctx)
        if lid is not None:
            out.append(lid)
    return out


def held_locks(node: ast.AST, ctx: FileContext) -> list[str]:
    """Locks held at ``node`` per the lexical ``with`` stack, outermost
    first — STOPPING at the nearest enclosing function/class boundary:
    a ``with`` that merely wraps a nested ``def`` holds nothing when
    that closure actually runs (a thread target or callback defined
    under a lock executes after release), so crossing the boundary
    would both bless unlocked accesses (OL7) and fabricate
    blocking-under-lock findings (OL9) in closure bodies."""
    withs: list[ast.With] = []
    for anc in ctx.ancestors(node):  # innermost-first
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            break
        if isinstance(anc, ast.With):
            withs.append(anc)
    out: list[str] = []
    for w in reversed(withs):
        out.extend(with_lock_ids(w, ctx))
    return out


def self_attr(expr: ast.AST) -> Optional[str]:
    """``self.X`` / ``cls.X`` -> "X", else None."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")):
        return expr.attr
    return None


def callee_terminal(func: ast.AST) -> Optional[str]:
    """Terminal name of a call target: ``foo`` / ``a.b.foo`` -> "foo"."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def iter_local_functions(ctx: FileContext):
    """Every function/method in the module with its resolution key:
    "funcname" at module level (nested functions too — they're keyed by
    their own name), "Class.method" inside a class."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = None
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                cls = anc
                break
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        key = f"{cls.name}.{node.name}" if cls is not None else node.name
        yield key, node


def resolve_local_call(call: ast.Call,
                       ctx: FileContext) -> Optional[str]:
    """Resolution key for a call target defined in this module: bare
    names -> module functions, self/cls methods -> the enclosing
    class.  Matches the keys :func:`iter_local_functions` yields."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in ("self", "cls"):
        for anc in ctx.ancestors(call):
            if isinstance(anc, ast.ClassDef):
                return f"{anc.name}.{f.attr}"
    return None


def receiver_terminal(func: ast.AST) -> Optional[str]:
    """Immediate receiver name of a method call: ``self._sock.recv`` ->
    "_sock", ``conn.recv`` -> "conn", ``self.recv`` -> "self", bare
    ``recv(...)`` -> None."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    if isinstance(base, ast.Call):
        return callee_terminal(base.func)
    return None
