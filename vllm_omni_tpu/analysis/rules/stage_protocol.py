"""OL5 — stage-protocol: frame types sent without a receiver handler.

The orchestrator↔worker channel in ``entrypoints/stage_proc.py`` speaks
length-prefixed frames whose dispatch key is ``msg["type"]``.  Both
directions live in the same module (worker serve loop + ProcStage
proxy), so the contract is statically checkable: every frame type a
sender constructs must have a handler comparison somewhere in the
module, and payload keys that carry cross-process trace state
(``spans`` — the re-stamp PR 1 ships spans across the socket with) must
be read back on the receiving side.  A new frame type with no handler
is exactly the silent-drop bug this rule exists for: the frame parses,
lands in an inbox, and nothing ever reads it.

Detected:

- a ``{"type": "x", ...}`` frame literal whose type string never
  appears in a handler comparison (``msg.get("type") == "x"``,
  ``t == "x"``, ``t in ("x", ...)``, match-case)
- a frame carrying a ``"spans"``/``"metrics"``/``"trace"`` payload key
  that no receiver reads via ``msg.get(...)``/``msg[...]``
"""

from __future__ import annotations

import ast
from typing import Iterable

from vllm_omni_tpu.analysis.engine import FileContext, Finding, Rule
from vllm_omni_tpu.analysis.manifest import PROTOCOL_MODULES, in_scope
from vllm_omni_tpu.analysis.rules._jitinfo import dotted

# payload keys that ship cross-process state which MUST be re-stamped
# into the receiving process (trace spans, engine metrics snapshots,
# worker-side resilience counters)
_RESTAMP_KEYS = ("spans", "metrics", "trace", "resilience")


def _const_str(node: ast.AST):
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


class StageProtocolRule(Rule):
    id = "OL5"
    name = "stage-protocol"
    node_types = (ast.Dict, ast.Compare, ast.Assign, ast.Subscript,
                  ast.Call, ast.Match)

    def __init__(self):
        self._sent: dict[str, ast.AST] = {}      # type -> first frame node
        self._sent_keys: dict[str, ast.AST] = {}  # payload key -> node
        self._handled: set[str] = set()
        self._read_keys: set[str] = set()
        self._type_names: set[str] = set()       # names bound to .get("type")
        self._compares: list[ast.Compare] = []   # resolved in finish, once
        #                                          _type_names is complete

    def applies(self, ctx: FileContext) -> bool:
        return in_scope(ctx.path, PROTOCOL_MODULES)

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Dict):
            self._visit_dict(node)
        elif isinstance(node, ast.Assign):
            self._visit_assign(node)
        elif isinstance(node, ast.Compare):
            self._compares.append(node)
        elif isinstance(node, ast.Subscript):
            key = _const_str(node.slice)
            if key:
                if isinstance(node.ctx, ast.Load):
                    self._read_keys.add(key)
                elif isinstance(node.ctx, ast.Store):
                    # msg["spans"] = ... augments an existing frame
                    self._sent_keys.setdefault(key, node)
        elif isinstance(node, ast.Call):
            # msg.get("spans") / msg.get("type")
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args:
                key = _const_str(node.args[0])
                if key:
                    self._read_keys.add(key)
        elif isinstance(node, ast.Match):
            for case in node.cases:
                for sub in ast.walk(case.pattern):
                    if isinstance(sub, ast.MatchValue):
                        val = _const_str(sub.value)
                        if val:
                            self._handled.add(val)
        return ()

    def _visit_dict(self, node: ast.Dict) -> None:
        keys = [(_const_str(k) if k is not None else None)
                for k in node.keys]
        if "type" not in keys:
            return
        t = _const_str(node.values[keys.index("type")])
        if t is not None:
            self._sent.setdefault(t, node)
        for k in keys:
            if k and k != "type":
                self._sent_keys.setdefault(k, node)

    def _visit_assign(self, node: ast.Assign) -> None:
        # t = msg.get("type") — later comparisons against t are handlers
        if isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "get" and node.value.args \
                and _const_str(node.value.args[0]) == "type":
            for tgt in node.targets:
                name = dotted(tgt)
                if name:
                    self._type_names.add(name)

    def _visit_compare(self, node: ast.Compare) -> None:
        sides = [node.left] + list(node.comparators)
        involves_type = any(
            self._is_type_expr(s) for s in sides)
        if not involves_type:
            return
        for s in sides:
            v = _const_str(s)
            if v is not None:
                self._handled.add(v)
            elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                for e in s.elts:
                    ev = _const_str(e)
                    if ev is not None:
                        self._handled.add(ev)

    def _is_type_expr(self, node: ast.AST) -> bool:
        if dotted(node) in self._type_names:
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and _const_str(node.args[0]) == "type")

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        for cmp_node in self._compares:
            self._visit_compare(cmp_node)
        for t, node in sorted(self._sent.items()):
            if t not in self._handled:
                yield ctx.finding(
                    self.id, node,
                    f"frame type '{t}' is sent but no handler in this "
                    "module compares against it — the frame lands in an "
                    "inbox and is silently dropped")
        for key, node in sorted(self._sent_keys.items()):
            if key in _RESTAMP_KEYS and key not in self._read_keys:
                yield ctx.finding(
                    self.id, node,
                    f"frames carry a '{key}' payload that no receiver "
                    "reads back — cross-process trace/metrics state is "
                    "dropped instead of re-stamped")
