"""omnilint rule registry — one module per rule family.

| id  | name                | contract it guards                         |
|-----|---------------------|--------------------------------------------|
| OL1 | jit-hazard          | jax.jit staging rules (traced branching,   |
|     |                     | static decls, jit-in-loop re-wrapping)     |
| OL2 | host-sync           | no device→host syncs in HOT_PATHS modules  |
| OL3 | donation-safety     | no reads of donated buffers                |
| OL4 | wall-clock-in-trace | bench timing syncs before the 2nd stamp    |
| OL5 | stage-protocol      | every sent frame type has a handler; span  |
|     |                     | payloads are re-stamped cross-process      |
| OL6 | metric-drift        | Prometheus surface matches METRIC_SPECS    |
| OL7 | lock-discipline     | LOCK_GUARDS attrs touched only under their |
|     |                     | lock (helper call edges resolved)          |
| OL8 | lock-order          | no cycles in the acquisition-order graph   |
| OL9 | blocking-under-lock | no device sync / jit / socket / sleep /    |
|     |                     | connector wait while holding a lock        |
| OL10| hostile-input-taint | no TAINT_SOURCES -> TAINT_SINKS dataflow   |
|     |                     | without a declared SANITIZER crossing      |
| OL11| recompile-hazard    | jit cache keys bucketed, dispatch variants |
|     |                     | in the key, every kind warmup-reachable    |
| OL12| resource-lifecycle  | RESOURCE_PROTOCOLS acquire/release pairs   |
|     |                     | discharged on every CFG path (exc edges)   |
| OL13| typestate           | STATE_MACHINES transition validity + the   |
|     |                     | swallowed-abort stranded-state check       |

OL7-OL9 ("omnirace") have a runtime counterpart in
``analysis/runtime.py`` — traced locks that detect order inversions and
wait cycles live under ``OMNI_TPU_LOCK_CHECK=1``.  OL10/OL11
("omniflow") are package-wide: they run at ``finalize_run`` over the
whole run's ProgramGraph (symbol table + cross-module call graph)
instead of one file at a time.  OL12/OL13 ("omnileak") add the
path-sensitive layer: an intraprocedural CFG with exception edges
(engine ``FunctionCFG``) checks resource acquire/release obligations
and declared state machines along every path, normal or aborting.
"""

from vllm_omni_tpu.analysis.rules.blocking_under_lock import (
    BlockingUnderLockRule,
)
from vllm_omni_tpu.analysis.rules.donation import DonationRule
from vllm_omni_tpu.analysis.rules.host_sync import HostSyncRule
from vllm_omni_tpu.analysis.rules.jit_hazard import JitHazardRule
from vllm_omni_tpu.analysis.rules.lock_discipline import LockDisciplineRule
from vllm_omni_tpu.analysis.rules.lock_order import LockOrderRule
from vllm_omni_tpu.analysis.rules.metric_drift import MetricDriftRule
from vllm_omni_tpu.analysis.rules.recompile_hazard import (
    RecompileHazardRule,
)
from vllm_omni_tpu.analysis.rules.resource_lifecycle import (
    ResourceLifecycleRule,
)
from vllm_omni_tpu.analysis.rules.stage_protocol import StageProtocolRule
from vllm_omni_tpu.analysis.rules.taint_flow import TaintFlowRule
from vllm_omni_tpu.analysis.rules.typestate import TypestateRule
from vllm_omni_tpu.analysis.rules.wallclock import WallClockRule

ALL_RULES: tuple[type, ...] = (
    JitHazardRule,
    HostSyncRule,
    DonationRule,
    WallClockRule,
    StageProtocolRule,
    MetricDriftRule,
    LockDisciplineRule,
    LockOrderRule,
    BlockingUnderLockRule,
    TaintFlowRule,
    RecompileHazardRule,
    ResourceLifecycleRule,
    TypestateRule,
)

__all__ = [
    "ALL_RULES",
    "JitHazardRule",
    "HostSyncRule",
    "DonationRule",
    "WallClockRule",
    "StageProtocolRule",
    "MetricDriftRule",
    "LockDisciplineRule",
    "LockOrderRule",
    "BlockingUnderLockRule",
    "TaintFlowRule",
    "RecompileHazardRule",
    "ResourceLifecycleRule",
    "TypestateRule",
]
