"""OL12 — resource-lifecycle: acquire/release pairs checked path-wise.

The most expensive recurring bug class in this repo is invisible to
OL1-OL11: a resource acquired and then leaked on an abort/exception
path.  PR 12's review pass found an aborted re-role stranding a
drained donor out of rotation forever; PR 15's found a failed dump
write consuming the flight-recorder cooldown window and an un-closed
host-tier park interval; PR 9's found failover ledger entries
surviving revive.  Every one was caught by a human re-reading diffs.
This rule encodes the harvest: the ``RESOURCE_PROTOCOLS`` manifest
(analysis/manifest.py) declares each acquire->release pair with its
carrier class, and the exception-edge CFG (engine ``FunctionCFG``)
asks, per acquire site, whether some path — normal OR exception —
escapes the function with the obligation still live.

What discharges an obligation on a path:

- a release (or declared ownership ``transfer``) call on the path,
  matched by receiver-qualified spec ("kv.free" matches
  ``self.kv.free`` and ``self.scheduler.kv.free``);
- a call resolving (cross-module, bounded depth) to a helper whose
  body releases;
- a release inside a must-execute cleanup (``finally`` unwind copy /
  ``with`` exit) reachable from the crossed exception edge — a
  condition guarding the release inside a ``finally`` is the author's
  explicit intent, not a leak;
- acquisition as a ``with`` context expression (``__exit__`` is the
  release);
- for "escape" protocols, a hand-off UP the PR 14 call graph: some
  resolvable caller (bounded depth) releases, so the obligation
  propagates with the exception;
- for "normal" protocols, a hand-off OUT: returning the acquired
  value or storing it into a tracked container transfers ownership.

Like OL8/OL10, the finding is a chain report: the acquire site
anchors it, and ``Finding.trace`` carries the leaking path's
waypoints (exception crossings, escape point) into the text renderer
and SARIF ``relatedLocations``.  A leak that is safe for a reason the
rule cannot see carries a reasoned suppression::

    self.kv.allocate(req, n)  # omnilint: disable=OL12 - freed by GC sweep
"""

from __future__ import annotations

import ast
from dataclasses import replace
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import (
    FileContext,
    Finding,
    FunctionCFG,
    ProgramGraph,
    Rule,
    cfg_leak_path,
    describe_path,
    own_nodes,
    scan_calls,
)
from vllm_omni_tpu.analysis.manifest import RESOURCE_PROTOCOLS
from vllm_omni_tpu.analysis.rules._lockinfo import callee_terminal

# report priority when a site leaks several ways: the sharpest first,
# one finding per (site, protocol)
_KIND_ORDER = ("escape", "swallow", "normal")
_KIND_WORD = {
    "escape": "exception-escape",
    "swallow": "swallowed-exception",
    "normal": "normal-exit",
}
# container mutators that count as "ownership transfer into a tracked
# container" for normal-path protocols
_STORE_METHODS = frozenset({"append", "add", "put", "setdefault",
                            "insert"})


def _receiver_terminal(func: ast.AST) -> Optional[str]:
    """Terminal name of a method call's receiver:
    ``self.scheduler.kv.free`` -> "kv", ``router.drain`` -> "router"."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return None


def spec_match(call: ast.Call, spec: str) -> bool:
    """Whether a call matches a "recv.method" / "method" spec — the
    receiver part substring-matches the receiver's terminal name."""
    recv, _, meth = spec.rpartition(".")
    if callee_terminal(call.func) != meth:
        return False
    if not recv:
        return True
    term = _receiver_terminal(call.func)
    return term is not None and recv in term


def _names_in(expr: ast.AST) -> set:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


class ResourceLifecycleRule(Rule):
    id = "OL12"
    name = "resource-lifecycle"
    node_types = ()
    # overridable in tests
    protocols = RESOURCE_PROTOCOLS
    CALLEE_DEPTH = 2   # release hidden inside a helper chain
    CALLER_DEPTH = 2   # obligation handed up to a releasing caller

    def applies(self, ctx: FileContext) -> bool:
        return False  # package-wide: everything happens in finalize_run

    # ------------------------------------------------------------ finalize
    def finalize_run(self) -> Iterable[Finding]:
        graph = ProgramGraph.ensure(self.run_state)
        self._graph = graph
        self._rel_memo: dict = {}
        self._up_memo: dict = {}
        seen: dict = {}
        for key in sorted(graph.functions):
            fi = graph.functions[key]
            hits = self._acquire_sites(fi)
            if not hits:
                continue
            cfg = FunctionCFG(fi.node)
            by_call: dict = {}
            for idx, call in cfg.call_sites():
                by_call.setdefault(id(call), []).append(idx)
            for proto, call, spec in hits:
                for f in self._check_site(fi, cfg, proto, call, spec,
                                          by_call.get(id(call), ())):
                    seen.setdefault((f.path, f.line, f.message), f)
        return [seen[k] for k in sorted(seen)]

    # ------------------------------------------------------------ scanning
    def _is_carrier(self, fi, proto) -> bool:
        path, _, cls = proto["carrier"].partition("::")
        return fi.path == path and fi.cls_name == cls.split(".")[-1]

    def _acquire_sites(self, fi) -> list:
        out = []
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            for proto in self.protocols:
                if self._is_carrier(fi, proto):
                    continue
                for spec in proto.get("acquire", ()):
                    if spec_match(node, spec):
                        out.append((proto, node, spec))
                        break
        return out

    # ----------------------------------------------------------- discharge
    def _releases_within(self, fi, proto, depth: int) -> bool:
        """Whether ``fi``'s body releases/transfers the protocol,
        directly or through resolvable helpers (bounded)."""
        key = (proto["name"], fi.key, depth)
        if key in self._rel_memo:
            return self._rel_memo[key]
        self._rel_memo[key] = False  # recursion guard
        specs = (proto.get("release", ())
                 + proto.get("transfer", ()))
        result = False
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if any(spec_match(node, s) for s in specs):
                result = True
                break
            if depth > 0:
                callee = self._graph.resolve_call(node, fi.ctx)
                if callee is not None and self._releases_within(
                        callee, proto, depth - 1):
                    result = True
                    break
        self._rel_memo[key] = result
        return result

    def _handed_up(self, fi, proto) -> bool:
        """Escape discharge through the call graph: some resolvable
        caller (bounded depth) releases, so the obligation rides the
        propagating exception to a frame that settles it."""
        key = (proto["name"], fi.key)
        if key in self._up_memo:
            return self._up_memo[key]
        self._up_memo[key] = False
        frontier, result = [fi.key], False
        for _ in range(self.CALLER_DEPTH):
            nxt = []
            for fkey in frontier:
                for caller, _call in self._graph.callers_of(fkey):
                    if self._releases_within(caller, proto, 0):
                        result = True
                        break
                    nxt.append(caller.key)
                if result:
                    break
            if result or not nxt:
                break
            frontier = nxt
        self._up_memo[key] = result
        return result

    def _discharge_fn(self, fi, cfg, proto, kind, acquired_names):
        """Per-node discharge predicate for one (function, protocol)
        pair, memoized — the path search and the exception-side
        reachability scans call it many times per node."""
        specs = proto.get("release", ()) + proto.get("transfer", ())
        memo: dict = {}

        def dis(idx: int) -> bool:
            if idx in memo:
                return memo[idx]
            memo[idx] = False
            node = cfg.nodes[idx]
            result = False
            for call in scan_calls(node.owned):
                if any(spec_match(call, s) for s in specs):
                    result = True
                    break
                if kind == "normal" and acquired_names \
                        and callee_terminal(call.func) in _STORE_METHODS \
                        and any(_names_in(a) & acquired_names
                                for a in call.args):
                    result = True  # ownership into a tracked container
                    break
                callee = self._graph.resolve_call(call, fi.ctx)
                if callee is not None and self._releases_within(
                        callee, proto, self.CALLEE_DEPTH - 1):
                    result = True
                    break
            if not result and kind == "normal" and acquired_names:
                stmt = node.stmt
                if (isinstance(stmt, ast.Return) and stmt.value is not None
                        and _names_in(stmt.value) & acquired_names):
                    result = True  # ownership returned to the caller
            memo[idx] = result
            return result

        return dis

    # ------------------------------------------------------------ checking
    def _check_site(self, fi, cfg, proto, call, spec,
                    node_idxs) -> Iterable[Finding]:
        acquired_names: set = set()
        stmt = None
        for idx in node_idxs:
            stmt = cfg.nodes[idx].stmt or stmt
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    acquired_names.add(t.id)
        kinds = [k for k in _KIND_ORDER if k in proto.get("on", ())]
        for idx in node_idxs:
            if cfg.nodes[idx].kind == "with":
                continue  # context-manager acquire: __exit__ releases
            for kind in kinds:
                if kind == "escape" and self._handed_up(fi, proto):
                    continue
                dis = self._discharge_fn(fi, cfg, proto, kind,
                                         acquired_names)
                path = cfg_leak_path(cfg, idx, dis, kind)
                if path is None:
                    continue
                rels = "/".join(
                    f"'{s}'" for s in proto.get("release", ()))
                art = "an" if _KIND_WORD[kind][0] in "aeiou" else "a"
                msg = (f"{proto['name']}: '{spec}' acquired here can "
                       f"leak on {art} {_KIND_WORD[kind]} path — no {rels} "
                       f"on the way out (release in a finally/handler, "
                       f"hand the obligation to a releasing caller, or "
                       f"transfer ownership)")
                f = fi.ctx.finding("OL12", call, msg)
                yield replace(f, trace=describe_path(cfg, path, kind))
                return  # one finding per site: the sharpest kind wins
