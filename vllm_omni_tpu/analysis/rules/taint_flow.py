"""OL10 — hostile-input taint: client bytes reaching a sink unsanitized.

Every review-hardening pass since PR 7 has hand-harvested the same bug
class: a value a CLIENT controls (the ``x-omni-tenant`` /
``x-omni-priority`` headers, raw ``additional_information`` metadata,
connector payload meta) reaching a sensitive operation — metric label
dicts (unbounded cardinality + exposition injection), log lines (log
injection), filesystem paths (traversal), scheduler arithmetic (the
``float("inf")`` priority crash) — without passing one of the declared
sanitizers first.  This rule encodes the harvest: the manifest
(``analysis/manifest.py`` ``TAINT_SOURCES`` / ``SANITIZERS`` /
``TAINT_SINKS``) declares the three vocabularies, and a forward
dataflow pass flags every source→sink flow no sanitizer touches.

The analysis runs at ``finalize_run`` over the whole run's
:class:`~vllm_omni_tpu.analysis.engine.ProgramGraph`:

- **per function**: reaching definitions over names, ``self.attr``
  chains, and dict-key writes (``d["k"] = tainted`` taints ``d`` — a
  label dict carries its values), iterated to fixpoint.  The union is
  deliberately flow-INsensitive: a name sanitized on one branch and
  raw on the other keeps the raw definition, which is exactly the
  sanitizer-on-one-branch-only bug.
- **interprocedural**: calls resolved through the cross-module call
  graph propagate taint both ways to a bounded depth — a helper
  returning a raw header read taints its callers, and a tainted
  argument seeds the callee's parameter so a sink inside the callee
  reports with the full path.
- **both-ends report**: like an OL8 cycle, the finding anchors at the
  sink and names the source end plus the def-use chain between them
  (function names, not line numbers, so the fingerprint survives
  unrelated edits).

A flow that is safe for a reason the rule cannot see carries a reasoned
suppression::

    logger.info("tenant=%s", raw)  # omnilint: disable=OL10 - bounded upstream
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.engine import (
    FileContext,
    Finding,
    ProgramGraph,
    Rule,
    own_nodes,
)
from vllm_omni_tpu.analysis.manifest import (
    SANITIZERS,
    TAINT_SINKS,
    TAINT_SOURCES,
    in_scope,
)
from vllm_omni_tpu.analysis.rules._jitinfo import dotted
from vllm_omni_tpu.analysis.rules._lockinfo import callee_terminal

LOG_METHODS = ("debug", "info", "warning", "error", "exception",
               "critical", "log")

# builtins that hand a tainted argument straight back (a copy or a
# re-rendering of hostile bytes is still hostile)
PASSTHROUGH = ("str", "repr", "format", "dict", "list", "tuple", "set",
               "sorted", "reversed", "copy", "deepcopy", "join")

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
              ast.Mod, ast.Pow)


@dataclass(frozen=True)
class Taint:
    """Provenance of one hostile value: where it entered and the
    function chain it crossed (names only — fingerprints must survive
    unrelated edits)."""

    desc: str   # "'x-omni-tenant' header read"
    path: str
    qual: str   # function the source read happened in
    trail: tuple = ()

    def via(self, qual: str) -> "Taint":
        if self.trail and self.trail[-1] == qual:
            return self
        return Taint(self.desc, self.path, self.qual,
                     self.trail + (qual,))


@dataclass(frozen=True)
class _FnResult:
    returns: Optional[Taint]
    findings: tuple


_EMPTY = _FnResult(None, ())


def _target_name(expr: ast.AST) -> Optional[str]:
    """Assignment-target identity: ``x`` -> "x", ``self.x`` ->
    "self.x", anything deeper -> None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name):
        return f"{expr.value.id}.{expr.attr}"
    return None


def _receiver_name(func: ast.AST) -> Optional[str]:
    """Terminal name of a method call's receiver: ``self.headers.get``
    -> "headers", ``headers.get`` -> "headers"."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return None


def _const_str(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return None


def _fstring_tail(expr: ast.AST) -> Optional[str]:
    """Last literal fragment of an f-string (or the whole constant):
    how ``f"{key}/meta"`` declares itself a metadata fetch."""
    s = _const_str(expr)
    if s is not None:
        return s
    if isinstance(expr, ast.JoinedStr) and expr.values:
        last = expr.values[-1]
        return _const_str(last)
    return None


class TaintFlowRule(Rule):
    id = "OL10"
    name = "hostile-input-taint"
    node_types = ()
    # overridable in tests
    sources = TAINT_SOURCES
    sanitizers = SANITIZERS
    sinks = TAINT_SINKS
    MAX_DEPTH = 4

    def applies(self, ctx: FileContext) -> bool:
        return False  # package-wide: everything happens in finalize_run

    # ------------------------------------------------------------ finalize
    def finalize_run(self) -> Iterable[Finding]:
        graph = ProgramGraph.ensure(self.run_state)
        self._graph = graph
        self._memo: dict = {}
        self._stack: set = set()
        self._defs_cache: dict = {}
        seen: dict = {}
        for key in sorted(graph.functions):
            fi = graph.functions[key]
            res = self._analyze(fi, (), self.MAX_DEPTH)
            for f in res.findings:
                seen.setdefault((f.path, f.line, f.message), f)
        return [seen[k] for k in sorted(seen)]

    # ------------------------------------------------------- per function
    def _analyze(self, fi, seeds: tuple, depth: int) -> _FnResult:
        # depth is part of the key: a result computed under a
        # truncated budget (reached transitively from an
        # alphabetically-earlier caller) must not shadow the
        # full-depth top-level analysis of the same function
        memo_key = (fi.key, seeds, depth)
        if memo_key in self._memo:
            return self._memo[memo_key]
        if memo_key in self._stack or depth < 0:
            return _EMPTY  # recursion/depth bound: assume clean
        self._stack.add(memo_key)
        try:
            result = self._analyze_body(fi, dict(seeds), depth)
        finally:
            self._stack.discard(memo_key)
        self._memo[memo_key] = result
        return result

    def _collect_defs(self, fi) -> tuple:
        """(defs, container_writes): name -> [value exprs] for every
        assignment shape in the function's own body."""
        if fi.key in self._defs_cache:
            return self._defs_cache[fi.key]
        defs: dict = {}
        writes: dict = {}
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._record_target(tgt, node.value, defs, writes)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._record_target(node.target, node.value, defs, writes)
            elif isinstance(node, ast.AugAssign):
                self._record_target(node.target, node.value, defs, writes)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._record_target(node.target, node.iter, defs, writes)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._record_target(item.optional_vars,
                                            item.context_expr, defs,
                                            writes)
            elif isinstance(node, ast.NamedExpr):
                self._record_target(node.target, node.value, defs, writes)
        self._defs_cache[fi.key] = (defs, writes)
        return defs, writes

    @staticmethod
    def _record_target(tgt, value, defs, writes) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                TaintFlowRule._record_target(elt, value, defs, writes)
            return
        if isinstance(tgt, ast.Subscript):
            base = _target_name(tgt.value)
            if base is not None:
                writes.setdefault(base, []).append(value)
            return
        name = _target_name(tgt)
        if name is not None:
            defs.setdefault(name, []).append(value)

    def _analyze_body(self, fi, env: dict, depth: int) -> _FnResult:
        defs, writes = self._collect_defs(fi)
        findings: list = []
        # ---- fixpoint over the union of reaching definitions
        for _ in range(10):
            changed = False
            for name, exprs in defs.items():
                if name in env:
                    continue
                for e in exprs:
                    t = self._expr_taint(e, env, fi, depth, findings)
                    if t is not None:
                        env[name] = t
                        changed = True
                        break
            for name, exprs in writes.items():
                if name in env:
                    continue
                for e in exprs:
                    t = self._expr_taint(e, env, fi, depth, findings)
                    if t is not None:
                        env[name] = t  # container carries its values
                        changed = True
                        break
            if not changed:
                break
        # ---- sinks (and EVERY call, whatever its statement position:
        # a discarded-result statement, an `if`/`while` test, an
        # assert, a comprehension — each still carries its arguments
        # INTO the callee, so each must go through expression
        # evaluation for the seeding/descend.  own_nodes yields nested
        # calls too; re-evaluation is memoized and findings dedup at
        # finalize)
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Call):
                self._expr_taint(node, env, fi, depth, findings)
                findings.extend(self._check_sink_call(node, env, fi,
                                                      depth))
            elif (isinstance(node, ast.BinOp)
                  and isinstance(node.op, _ARITH_OPS)
                  and in_scope(fi.path,
                               self.sinks.get("sched_arith_paths", ()))):
                t = (self._expr_taint(node.left, env, fi, depth,
                                      findings)
                     or self._expr_taint(node.right, env, fi, depth,
                                         findings))
                if t is not None:
                    findings.append(self._finding(
                        fi, node, t, "scheduler arithmetic",
                        "an admission-math operand"))
        # ---- return taint
        returns: Optional[Taint] = None
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Return) and node.value is not None:
                t = self._expr_taint(node.value, env, fi, depth,
                                     findings)
                if t is not None:
                    returns = t
                    break
        return _FnResult(returns, tuple(findings))

    # ------------------------------------------------------- taint of expr
    def _expr_taint(self, e, env: dict, fi, depth: int,
                    findings: list) -> Optional[Taint]:
        if isinstance(e, ast.Constant):
            return None
        if isinstance(e, ast.Name):
            return env.get(e.id)
        if isinstance(e, ast.Attribute):
            if e.attr in self.sources.get("attrs", ()):
                return Taint(f"raw '{e.attr}' metadata read", fi.path,
                             fi.qual)
            tn = _target_name(e)
            if tn is not None and tn in env:
                return env[tn]
            return self._expr_taint(e.value, env, fi, depth, findings)
        if isinstance(e, ast.Subscript):
            hdr = _const_str(e.slice)
            recv = _target_name(e.value)
            if (hdr in self.sources.get("headers", ())
                    and recv is not None and "headers" in recv):
                return Taint(f"hostile '{hdr}' header read", fi.path,
                             fi.qual)
            if self._internal_key_read(e.value, e.slice):
                return None
            return self._expr_taint(e.value, env, fi, depth, findings)
        if isinstance(e, ast.Call):
            return self._call_taint(e, env, fi, depth, findings)
        if isinstance(e, ast.JoinedStr):
            for part in e.values:
                t = self._expr_taint(part, env, fi, depth, findings)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.FormattedValue):
            return self._expr_taint(e.value, env, fi, depth, findings)
        if isinstance(e, ast.BinOp):
            return (self._expr_taint(e.left, env, fi, depth, findings)
                    or self._expr_taint(e.right, env, fi, depth,
                                        findings))
        if isinstance(e, ast.BoolOp):
            for v in e.values:
                t = self._expr_taint(v, env, fi, depth, findings)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.IfExp):
            return (self._expr_taint(e.body, env, fi, depth, findings)
                    or self._expr_taint(e.orelse, env, fi, depth,
                                        findings))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            for elt in e.elts:
                t = self._expr_taint(elt, env, fi, depth, findings)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.Dict):
            for v in e.values:
                if v is None:
                    continue
                t = self._expr_taint(v, env, fi, depth, findings)
                if t is not None:
                    return t
            return None
        if isinstance(e, (ast.Starred, ast.Await)):
            return self._expr_taint(e.value, env, fi, depth, findings)
        if isinstance(e, ast.NamedExpr):
            return self._expr_taint(e.value, env, fi, depth, findings)
        return None

    def _internal_key_read(self, container: ast.AST,
                           key_expr: ast.AST) -> bool:
        """A read of an engine-internal (underscore-prefixed) key off a
        source dict is engine-written state, not client input."""
        if not (isinstance(container, ast.Attribute)
                and container.attr in self.sources.get("attrs", ())):
            return False
        key = _const_str(key_expr)
        return key is not None and any(
            key.startswith(p)
            for p in self.sources.get("internal_key_prefixes", ()))

    def _call_taint(self, call: ast.Call, env: dict, fi, depth: int,
                    findings: list) -> Optional[Taint]:
        term = callee_terminal(call.func)
        # 1. a declared sanitizer launders whatever flows through it
        if term in self.sanitizers:
            return None
        # 1b. engine-internal key reads off the metadata dict
        if (term in ("get", "pop") and call.args
                and isinstance(call.func, ast.Attribute)
                and self._internal_key_read(call.func.value,
                                            call.args[0])):
            return None
        # 2. source patterns
        if term == "get" and call.args:
            hdr = _const_str(call.args[0])
            recv = _receiver_name(call.func)
            if (hdr in self.sources.get("headers", ())
                    and recv is not None and "headers" in recv):
                return Taint(f"hostile '{hdr}' header read", fi.path,
                             fi.qual)
        if term in ("get", "fetch", "recv") and call.args:
            tail = _fstring_tail(call.args[0])
            if tail is not None and any(
                    tail.endswith(sfx)
                    for sfx in self.sources.get("meta_suffixes", ())):
                return Taint("connector payload metadata "
                             f"('...{tail}')", fi.path, fi.qual)
        # 3. interprocedural: resolve through the program graph
        target = self._graph.resolve_call(call, fi.ctx)
        if target is not None and target.key != fi.key:
            seeds = []
            for param in target.param_names():
                if param in ("self", "cls"):
                    continue
                arg = ProgramGraph.call_arg_for_param(call, target, param)
                if arg is None:
                    continue
                t = self._expr_taint(arg, env, fi, depth, findings)
                if t is not None:
                    seeds.append((param, t.via(fi.qual)))
            res = self._analyze(target, tuple(sorted(seeds)), depth - 1)
            findings.extend(res.findings)
            if res.returns is not None:
                return res.returns.via(fi.qual)
            return None
        # 4. unresolvable: a method ON a tainted object yields hostile
        # bytes; pass-through builtins hand tainted args back
        if isinstance(call.func, ast.Attribute):
            t = self._expr_taint(call.func.value, env, fi, depth,
                                 findings)
            if t is not None:
                return t
        if term in PASSTHROUGH:
            for arg in call.args:
                t = self._expr_taint(arg, env, fi, depth, findings)
                if t is not None:
                    return t
        return None

    # --------------------------------------------------------------- sinks
    def _check_sink_call(self, call: ast.Call, env: dict, fi,
                         depth: int) -> list:
        out: list = []
        term = callee_terminal(call.func)
        dotted_name = dotted(call.func)
        kind = None
        what = None
        if term in self.sinks.get("metric_labels", ()):
            kind, what = "metric-label", f"`{term}(...)`"
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr in LOG_METHODS
              and (_receiver_name(call.func) or "")
              in self.sinks.get("log_receivers", ())):
            kind = "log"
            what = f"`{_receiver_name(call.func)}.{call.func.attr}(...)`"
        elif dotted_name in self.sinks.get("fs_calls", ()):
            kind, what = "filesystem-path", f"`{dotted_name}(...)`"
        if kind is None:
            return out
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            t = self._expr_taint(arg, env, fi, depth, out)
            if t is not None:
                out.append(self._finding(fi, call, t, kind, what))
                break
        return out

    def _finding(self, fi, node, taint: Taint, kind: str,
                 what: str) -> Finding:
        chain = " -> ".join(dict.fromkeys(
            taint.trail + (fi.qual or "module",)))
        src_qual = taint.qual or "module"
        return fi.ctx.finding(
            self.id, node,
            f"hostile input reaches {kind} sink unsanitized: "
            f"{taint.desc} ({src_qual} in {taint.path}) flows into "
            f"{what} via {chain} — route it through a declared "
            "sanitizer (SANITIZERS, analysis/manifest.py) first")
