"""omnilint — JAX/TPU-aware static analysis for vllm-omni-tpu.

A stock linter sees valid Python; this package checks the contracts the
serving stack actually hangs on: jit staging rules (OL1), hot-path
host↔device syncs (OL2), buffer donation (OL3), async-dispatch-safe
benchmarking (OL4), the cross-process stage frame protocol (OL5), and
Prometheus metric-surface drift (OL6).

CLI::

    python -m vllm_omni_tpu.analysis [--format text|json]
        [--update-baseline] [--no-baseline] [paths...]

Library::

    from vllm_omni_tpu.analysis import analyze_paths, new_findings

See docs/static_analysis.md for the rule catalogue, the suppression
syntax (``# omnilint: disable=OL2 - reason``), and the baseline
workflow.  No jax import anywhere in this package — safe for any CI
lane.
"""

from vllm_omni_tpu.analysis.engine import (
    DEFAULT_BASELINE,
    Finding,
    Rule,
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
    new_findings,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "Rule",
    "analyze_paths",
    "analyze_source",
    "apply_baseline",
    "load_baseline",
    "new_findings",
    "save_baseline",
]
