"""omnilint — JAX/TPU-aware static analysis for vllm-omni-tpu.

A stock linter sees valid Python; this package checks the contracts the
serving stack actually hangs on: jit staging rules (OL1), hot-path
host↔device syncs (OL2), buffer donation (OL3), async-dispatch-safe
benchmarking (OL4), the cross-process stage frame protocol (OL5),
Prometheus metric-surface drift (OL6), the omnirace concurrency
families — lock discipline against the LOCK_GUARDS manifest (OL7),
lock-order cycles (OL8), and blocking calls under a lock (OL9), with a
runtime lock-order/deadlock detector in ``analysis.runtime``
(``OMNI_TPU_LOCK_CHECK=1``) — and the omniflow package-wide families:
hostile-input taint against the TAINT_SOURCES/SANITIZERS/TAINT_SINKS
manifest (OL10) and jit recompile hazards against the RECOMPILE
manifest (OL11), both resolved over a cross-module symbol table + call
graph (``engine.ProgramGraph``).

CLI::

    python -m vllm_omni_tpu.analysis [--format text|json|sarif]
        [--sarif-out path] [--update-baseline] [--no-baseline]
        [--report-stale-suppressions] [paths...]

Library::

    from vllm_omni_tpu.analysis import analyze_paths, new_findings

See docs/static_analysis.md for the rule catalogue, the suppression
syntax (``# omnilint: disable=OL2 - reason``), and the baseline
workflow.  No jax import anywhere in this package — safe for any CI
lane.
"""

# Lazy (PEP 562) re-exports: production modules import
# ``vllm_omni_tpu.analysis.runtime`` for ``traced()`` at lock
# construction, and importing ANY submodule executes this __init__ —
# eagerly pulling the whole AST rule engine into every server/worker
# start would tax exactly the processes the zero-cost-when-off
# contract protects.  The engine loads on first actual use.
__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "ProgramGraph",
    "Rule",
    "analyze_paths",
    "analyze_source",
    "analyze_sources",
    "apply_baseline",
    "finalize_findings",
    "load_baseline",
    "new_findings",
    "save_baseline",
    "stale_baseline_entries",
    "stale_suppressions",
]


def __getattr__(name):
    if name in __all__:
        from vllm_omni_tpu.analysis import engine

        return getattr(engine, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
