"""SARIF 2.1.0 output for omnilint findings.

SARIF (Static Analysis Results Interchange Format) is what CI
annotation surfaces (GitHub code scanning, reviewdog, VS Code SARIF
viewers) ingest — one document carries the rule catalogue, per-finding
locations, and stable fingerprints, so a PR gate can pin an omnilint
finding to the exact diff line without knowing anything about the
engine.  ``python -m vllm_omni_tpu.analysis --format sarif`` prints
the document; ``--sarif-out PATH`` (or ``OMNI_LINT_SARIF=path`` through
``scripts/omnilint.sh``) writes it alongside the human output.

Only NEW findings become ``results`` — suppressed/baselined ones are
the gate's accepted debt and would spam every PR with pre-existing
annotations.  The finding's engine fingerprint ((rule|path|symbol|
message), line-free by design) rides ``partialFingerprints`` so the
consumer's dedup survives unrelated edits, exactly like the baseline
does.

No jax import, stdlib-only — same any-lane stance as the engine.
"""

from __future__ import annotations

import json
from typing import Iterable

from vllm_omni_tpu.analysis.engine import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

#: one-line rule descriptions for the tool.driver.rules catalogue —
#: kept here (not in each rule class) so the SARIF surface and the
#: docs table stay reviewable side by side
RULE_DESCRIPTIONS: dict[str, str] = {
    "OL0": "file does not parse",
    "OL1": "jit-hazard: jax.jit staging rules (traced branching, "
           "static decls, jit-in-loop re-wrapping)",
    "OL2": "host-sync: no device-to-host syncs in HOT_PATHS modules",
    "OL3": "donation-safety: no reads of donated buffers",
    "OL4": "wall-clock-in-trace: bench timing must sync before the "
           "second stamp",
    "OL5": "stage-protocol: every sent frame type has a handler",
    "OL6": "metric-drift: Prometheus surface matches METRIC_SPECS",
    "OL7": "lock-discipline: LOCK_GUARDS attrs touched only under "
           "their lock",
    "OL8": "lock-order: no cycles in the acquisition-order graph",
    "OL9": "blocking-under-lock: no blocking call while holding a lock",
    "OL10": "hostile-input-taint: no TAINT_SOURCES to TAINT_SINKS "
            "dataflow without a declared SANITIZER crossing",
    "OL11": "recompile-hazard: jit cache keys bucketed, dispatch "
            "variants observed by the key, every kind warmed",
    "OL12": "resource-lifecycle: RESOURCE_PROTOCOLS acquire/release "
            "obligations discharged on every CFG path, normal or "
            "exception",
    "OL13": "typestate: STATE_MACHINES transition validity and the "
            "swallowed-abort stranded-state check",
}


def to_sarif(findings: Iterable[Finding],
             tool_version: str = "1.0") -> dict:
    """SARIF 2.1.0 document for the run's NEW findings."""
    new = [f for f in findings if not f.suppressed and not f.baselined]
    used_rules = sorted({f.rule for f in new} | set(RULE_DESCRIPTIONS))
    rule_index = {rid: i for i, rid in enumerate(used_rules)}
    results = []
    for f in new:
        result = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": "error",
            "message": {"text": f.message
                        + (f" ({f.symbol})" if f.symbol else "")},
            "locations": [{
                "physicalLocation": {
                    # bare repo-relative URI: consumers (GitHub code
                    # scanning, reviewdog) resolve it against the
                    # checkout root — a uriBaseId would need an
                    # originalUriBaseIds declaration to be valid SARIF
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
                "logicalLocations": ([{"fullyQualifiedName": f.symbol}]
                                     if f.symbol else []),
            }],
            "partialFingerprints": {
                "omnilintFingerprint/v1": f.fingerprint,
            },
        }
        if f.trace:
            # OL12/OL13 chain reports: the leaking path's waypoints
            # (acquire site -> exception crossings -> escape point)
            # as relatedLocations, so SARIF viewers render the path
            # the same way the text output does
            result["relatedLocations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(int(line), 1)},
                },
                "message": {"text": note},
            } for line, note in f.trace]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "omnilint",
                    "informationUri":
                        "docs/static_analysis.md",
                    "version": tool_version,
                    "rules": [
                        {"id": rid,
                         "name": RULE_DESCRIPTIONS.get(
                             rid, "").split(":", 1)[0] or rid,
                         "shortDescription": {
                             "text": RULE_DESCRIPTIONS.get(rid, rid)}}
                        for rid in used_rules
                    ],
                }
            },
            "results": results,
        }],
    }


def write_sarif(findings: Iterable[Finding], path: str) -> dict:
    doc = to_sarif(findings)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    return doc
