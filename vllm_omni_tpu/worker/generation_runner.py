"""One-shot generation model runner.

TPU-native counterpart of the reference's GPUGenerationModelRunner
(reference: worker/gpu_generation_model_runner.py:44 — no sampler;
``_run_generation_model`` returns waveform/image tensors :408-447).  Paired
with ``GenerationScheduler``: every request arrives as a single full-prompt
prefill and finishes in one step; the model's forward output (not sampled
tokens) is the result, stored into ``request.multimodal_output``.

Model protocol (duck-typed):
- ``forward(params, token_ids [B, S], lengths [B]) -> dict[str, jax.Array]``
  batched over padded inputs; jit-compatible.
- ``slice_output(outputs, row, in_len) -> dict[str, np.ndarray]``
  extract one request's result from the padded batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.core.scheduler import SchedulerOutput
from vllm_omni_tpu.worker.model_runner import (
    RunnerOutput,
    _bucket,
    _bucketed_prefill_shapes,
    _make_buckets,
)


class GenerationModelRunner:
    def __init__(self, params, model, max_num_seqs: int = 8,
                 max_model_len: int = 4096):
        self.params = params
        self.model = model
        self._batch_buckets = _make_buckets(1, max(max_num_seqs, 1))
        self._seq_buckets = _make_buckets(16, max(max_model_len, 16))
        self._forward = jax.jit(model.forward)

    def precompile(self, prefill_shapes=(), progress_fn=None) -> int:
        """Warm the cond-free padded-batch forward for declared
        (batch, seq_len) shapes (same motivation as
        ARModelRunner.precompile: a shape-cache miss mid-traffic stalls
        in-flight requests for a full XLA compile).  Conditioning
        models run this same 3-arg executable whenever
        ``batch_conditioning`` returns None (an all-unconditioned
        batch), so it is warmed for them too; only the conditioned
        4-arg specialization depends on the per-request conditioning
        pytree and cannot be warmed generically."""
        built = 0
        for b, s in _bucketed_prefill_shapes(
                prefill_shapes, self._batch_buckets, self._seq_buckets):
            if progress_fn is not None:
                progress_fn(f"precompile generation b={b} s={s}")
            out = self._forward(
                self.params, jnp.zeros((b, s), jnp.int32),
                jnp.full((b,), s, jnp.int32))
            jax.block_until_ready(out)
            built += 1
        return built

    def execute(self, sched_out: SchedulerOutput,
                extract_kv: bool = True) -> RunnerOutput:
        out = RunnerOutput()
        scheds = sched_out.prefills
        if not scheds:
            return out
        b = _bucket(len(scheds), self._batch_buckets)
        s_len = _bucket(max(s.num_new_tokens for s in scheds),
                        self._seq_buckets)
        token_ids = np.zeros((b, s_len), np.int32)
        lengths = np.zeros((b,), np.int32)
        for i, sc in enumerate(scheds):
            n = sc.num_new_tokens
            token_ids[i, :n] = sc.request.prompt_token_ids[:n]
            lengths[i] = n
        # optional conditioning extension: models exposing
        # ``batch_conditioning(requests, batch) -> pytree`` take it as a
        # fourth forward argument (per-request voice vectors etc.);
        # jax.jit specializes per call signature, so the cond-free path
        # keeps its own cached executable
        cond = None
        if hasattr(self.model, "batch_conditioning"):
            cond = self.model.batch_conditioning(
                [sc.request for sc in scheds], b)
        if cond is not None:
            outputs = self._forward(
                self.params, jnp.asarray(token_ids),
                jnp.asarray(lengths), cond)
        else:
            outputs = self._forward(
                self.params, jnp.asarray(token_ids), jnp.asarray(lengths)
            )
        # one pytree transfer, not a sync per output key (first
        # omnilint OL2 harvest)
        # omnilint: disable=OL2 - single batched sync per one-shot batch
        outputs = {k: np.asarray(v)
                   for k, v in jax.device_get(outputs).items()}
        for i, sc in enumerate(scheds):
            sc.request.multimodal_output.update(
                self.model.slice_output(outputs, i, int(lengths[i]))
            )
        return out

    def extract_kv(self, block_ids, seq_len):
        raise NotImplementedError("generation models have no KV cache")
