"""AR model runner: bucketed-jit execution of scheduler output.

TPU-native counterpart of the reference's GPUARModelRunner (reference:
worker/gpu_ar_model_runner.py:59).  Where the CUDA runner manages CUDA-graph
capture + padded dispatch (:180-205), the TPU runner relies on XLA: every
(bucket_batch, bucket_seq) shape compiles once and is cached; padding rides
slot -1 (dropped by the KV scatter) and masked sampling.

Responsibilities (mirroring :90-396 / :398-588):
- assemble padded device inputs from ``SchedulerOutput``
- run jitted prefill / decode steps with donated KV caches
- sample next tokens (sample/sampler.py)
- slice per-request hidden states for next-stage payloads
  (pooler_output analogue, reference :525-568)
- extract KV pages for cross-stage transfer and ACK them
  (device half of OmniKVTransferManager, reference:
  distributed/omni_connectors/kv_transfer_manager.py:47)
"""

from __future__ import annotations

import dataclasses
import functools
import secrets
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.core.scheduler import ScheduledRequest, SchedulerOutput
from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.ops.paged_attention import init_kv_cache, write_kv_cache
from vllm_omni_tpu.ops.ragged_paged_attention import align_to_block
from vllm_omni_tpu.sample.sampler import SamplingTensors, sample_tokens
from vllm_omni_tpu.sampling_params import SamplingParams


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def _bucketed_prefill_shapes(prefill_shapes, batch_buckets,
                             seq_buckets) -> list[tuple[int, int]]:
    """Expand declared (batch, seq_len) traffic shapes into the bucketed
    (b, s) set to warm: every batch bucket up to the declared batch (the
    scheduler admits whatever arrived, so smaller waves bucket lower),
    seq clamped to its bucket.  Shared by the AR and generation runners'
    precompile so their coverage policy cannot drift apart."""
    todo = set()
    for raw_b, raw_s in prefill_shapes:
        b_top = _bucket(min(raw_b, batch_buckets[-1]), batch_buckets)
        s = _bucket(min(raw_s, seq_buckets[-1]), seq_buckets)
        todo.update((b, s) for b in batch_buckets if b <= b_top)
    return sorted(todo)


def _make_buckets(start: int, limit: int) -> tuple[int, ...]:
    """Powers of two from ``start`` up to (and covering) ``limit``."""
    buckets = []
    b = start
    while b < limit:
        buckets.append(b)
        b *= 2
    buckets.append(limit)
    return tuple(buckets)


@dataclass
class RunnerOutput:
    # request_id -> sampled token (only for requests that reached
    # sampling); a spec-decode verify step stores the LIST of accepted
    # tokens instead of a single int
    sampled: dict[str, "int | list[int]"] = field(default_factory=dict)
    # request_id -> extracted KV payload (per-layer (k, v) numpy arrays)
    extracted_kv: dict[str, list] = field(default_factory=dict)
    kv_extracted_req_ids: set[str] = field(default_factory=set)


class UnifiedBatch(NamedTuple):
    """Host-assembled device inputs for one token-packed unified step
    (the layout contract of ops/ragged_paged_attention.py)."""

    token_ids: np.ndarray   # [T_pad]
    positions: np.ndarray   # [T_pad] ([3, T_pad] under mrope)
    slots: np.ndarray       # [T_pad] flat KV slots (-1 padding)
    tables: np.ndarray      # [S_max, max_pages]
    seq_lens: np.ndarray    # [S_max]
    cu_q_lens: np.ndarray   # [S_max + 1] aligned segment starts
    q_lens: np.ndarray      # [S_max]
    last_idx: np.ndarray    # [S_max] packed row of each seq's last token
    t_pad: int              # token bucket the batch padded to
    total: int              # aligned rows actually occupied


@dataclass
class InflightDecode:
    """Handle for a dispatched-but-not-retired pipelined decode step.

    ``tokens`` stays DEVICE-resident: the next dispatch gathers its
    input tokens straight from it (no host round trip), and the engine
    retires it one step later with the single lagged ``device_get``
    (the async pipeline's whole point — host readback leaves the
    critical path)."""

    tokens: jax.Array                 # [B_padded] i32, on device
    rows: dict[str, int]              # request_id -> padded batch row


def _params_key(sp: SamplingParams) -> tuple:
    """The fields SamplingTensors actually consumes, by VALUE — cache
    keys must not use id(sp): CPython reuses freed addresses, so a
    recycled request_id could silently hit a stale entry built from a
    dead request's params."""
    return (sp.temperature, sp.top_k, sp.top_p, sp.seed)


# Bucket-padding rows must be GREEDY: sample_tokens skips its
# full-vocab-sort sampling branch only when no row has temperature > 0,
# and default-temperature padding would defeat that fast path for every
# batch that doesn't exactly fill its bucket (padding tokens are
# discarded either way).
_PAD_SAMPLING = SamplingParams(temperature=0.0)


class ARModelRunner:
    def __init__(
        self,
        params,
        cfg: tfm.TransformerConfig,
        num_pages: int,
        page_size: int,
        max_model_len: int = 4096,
        dtype=jnp.bfloat16,
        collect_hidden: bool = False,
        seed: Optional[int] = None,
        max_num_seqs: int = 64,
        mesh=None,  # 1-axis "tp" Mesh => tensor-parallel execution
        multi_step_decode: int = 1,  # decode window per device call
        async_scheduling: bool = False,  # precompile the dispatch path
        unified_batching: bool = False,  # build the ragged unified step
        max_num_batched_tokens: int = 2048,  # sizes the token buckets
        deterministic_decode: bool = False,  # pin decode batches to one bucket
    ):
        self.multi_step_decode = max(1, int(multi_step_decode))
        self.async_scheduling = bool(async_scheduling)
        self.unified_batching = bool(unified_batching)
        self.deterministic_decode = bool(deterministic_decode)
        self.mesh = mesh
        if mesh is not None:
            # Megatron-style TP inside shard_map: heads and MLP columns
            # divide across the tp axis; the per-layer code runs on LOCAL
            # shapes and cfg.tp_axis inserts the psum/all_gather
            # collectives (reference: tensor_parallel_size,
            # stage_configs/qwen3_omni_moe.yaml:27).
            from vllm_omni_tpu.parallel.mesh import AXIS_TP
            from vllm_omni_tpu.parallel.sharding import shard_ar_params

            tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
                AXIS_TP, 1)
            if cfg.num_heads % tp or cfg.num_kv_heads % tp:
                raise ValueError(
                    f"tp={tp} must divide num_heads={cfg.num_heads} and "
                    f"num_kv_heads={cfg.num_kv_heads}")
            cfg = dataclasses.replace(cfg, tp_axis=AXIS_TP)
            params = shard_ar_params(params, mesh)
        self.params = params
        self.cfg = cfg
        self.params_dtype = jax.tree_util.tree_leaves(params)[0].dtype
        self.page_size = page_size
        self.max_pages_per_seq = -(-max_model_len // page_size)
        # bucket tables sized to the engine limits — the scheduler never
        # emits a batch/chunk beyond them, so _bucket cannot overflow
        self._batch_buckets = _make_buckets(1, max(max_num_seqs, 1))
        self._seq_buckets = _make_buckets(16, max(max_model_len, 16))
        # unified ragged batching pads to TOKEN-count buckets: a 1-D
        # bucket line replacing the (batch, seq) grid of the split path.
        # Worst packed size = the step token budget plus per-sequence
        # q-block alignment (ops/ragged_paged_attention.py layout).
        t_cap = align_to_block(
            max_num_batched_tokens
            + max(max_num_seqs, 1) * (align_to_block(1) - 1))
        self._token_buckets = _make_buckets(16, max(t_cap, 16))
        self.collect_hidden = collect_hidden
        # --- telemetry (metrics/stats.py pulls these per step) ---
        # device dispatches: one jitted-executable launch each; tests
        # assert a mixed unified step is exactly ONE of these
        self.dispatch_count = 0
        # padding efficiency: real tokens vs. padded device rows
        self.useful_tokens = 0
        self.padded_tokens = 0
        # jit shape-cache telemetry: fresh compiles vs. cache hits and
        # cumulative first-call (compile-dominated) seconds, keyed by
        # this runner's own (kind, shape) signatures
        # "in_flight" is the stall watchdog's compile-stall signal: set
        # around the fresh-compile branch of _run_jit so a mid-traffic
        # XLA compile reads as "compiling", never as a hung engine
        self.compile_stats = {"compiles": 0, "cache_hits": 0,
                              "compile_s": 0.0, "in_flight": 0}
        self._jit_seen: set[tuple] = set()
        self.kv_caches = init_kv_cache(
            cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
            cfg.head_dim, dtype,
        )
        # device-memory ledger components (introspection/memory_ledger):
        # static buffer sizes, summed ONCE from array metadata — .nbytes
        # never syncs the device.  Spec-decode verify buffers are added
        # by set_draft_fn.
        self._weights_bytes = sum(
            getattr(x, "nbytes", 0)
            for x in jax.tree_util.tree_leaves(params))
        self._kv_bytes = sum(k.nbytes + v.nbytes
                             for k, v in self.kv_caches)
        self._spec_bytes = 0
        if mesh is not None:
            from jax.sharding import NamedSharding

            from vllm_omni_tpu.parallel.sharding import ar_kv_cache_spec

            k_spec, v_spec = ar_kv_cache_spec()
            self.kv_caches = [
                (jax.device_put(k, NamedSharding(mesh, k_spec)),
                 jax.device_put(v, NamedSharding(mesh, v_spec)))
                for k, v in self.kv_caches
            ]
        self._step = 0
        # engine-level entropy for unseeded requests (fresh per process
        # unless a seed is pinned for reproducibility)
        self._base_seed = seed if seed is not None else secrets.randbits(31)
        # host-side hot-path caches: crc32 sampling salts per request_id
        # and assembled SamplingTensors per batch composition — a
        # pure-decode batch keeps the same (requests, params) for
        # hundreds of steps, and _sample_and_record used to rebuild both
        # every step (only the PRNG keys actually depend on the step)
        self._salt_cache: dict[str, int] = {}
        self._st_cache: dict[tuple, tuple] = {}
        # multimodal 3D-RoPE: positions carry 3 streams ([B, 3, S] / [B, 3])
        self.use_mrope = cfg.mrope_sections is not None

        cfg_ = cfg

        # KV caches are donated: each step consumes the old cache buffers and
        # returns updated ones — no copy, the XLA equivalent of in-place
        # CUDA cache writes.
        # one closure serves both paths: inputs_embeds=None and =array are
        # two jit specializations of the same function
        def _prefill(params, token_ids, kv_caches, positions, slot_mapping,
                     last_idx, inputs_embeds=None, embeds_mask=None,
                     deepstack=None):
            hidden, new_caches = tfm.forward_prefill(
                params, cfg_, token_ids, positions, kv_caches, slot_mapping,
                inputs_embeds=inputs_embeds, embeds_mask=embeds_mask,
                deepstack=deepstack,
            )
            b = token_ids.shape[0]
            last_hidden = hidden[jnp.arange(b), last_idx]  # [B, H]
            logits = tfm.logits_from_hidden(params, cfg_, last_hidden)
            return logits, last_hidden, hidden, new_caches

        def _chunk_prefill(params, token_ids, kv_caches, positions,
                           slot_mapping, last_idx, block_tables,
                           context_lens, q_starts, inputs_embeds=None,
                           embeds_mask=None, deepstack=None):
            hidden, new_caches = tfm.forward_prefill_chunked(
                params, cfg_, token_ids, positions, kv_caches, slot_mapping,
                block_tables, context_lens, q_starts,
                inputs_embeds=inputs_embeds, embeds_mask=embeds_mask,
                deepstack=deepstack,
            )
            b = token_ids.shape[0]
            last_hidden = hidden[jnp.arange(b), last_idx]
            logits = tfm.logits_from_hidden(params, cfg_, last_hidden)
            return logits, last_hidden, hidden, new_caches

        def _verify(params, token_ids, kv_caches, positions, slot_mapping,
                    block_tables, context_lens, q_starts):
            # spec-decode verify: logits at EVERY candidate position
            # (the chunked forward writes KV for all candidates; rejected
            # slots are position-keyed and get overwritten by real tokens)
            hidden, new_caches = tfm.forward_prefill_chunked(
                params, cfg_, token_ids, positions, kv_caches, slot_mapping,
                block_tables, context_lens, q_starts,
            )
            logits = tfm.logits_from_hidden(params, cfg_, hidden)
            return logits, hidden, new_caches

        def _decode(params, token_ids, kv_caches, positions, slot_mapping,
                    block_tables, context_lens):
            hidden, new_caches = tfm.forward_decode(
                params, cfg_, token_ids, positions, kv_caches, slot_mapping,
                block_tables, context_lens,
            )
            logits = tfm.logits_from_hidden(params, cfg_, hidden)
            return logits, hidden, new_caches

        def _decode_sample(params, token_ids, kv_caches, positions,
                           slot_mapping, block_tables, context_lens,
                           temperature, top_k, top_p, keys):
            # single-step decode with ON-DEVICE sampling — the sampling
            # hoist out of _decode_multi's scan body that enables the
            # async pipelined engine step: the sampled tokens stay
            # device-resident and feed the NEXT decode dispatch directly,
            # so jax.device_get moves off the critical path and becomes
            # a one-step-lagged retire (engine/llm_engine.py)
            hidden, new_caches = tfm.forward_decode(
                params, cfg_, token_ids, positions, kv_caches, slot_mapping,
                block_tables, context_lens,
            )
            logits = tfm.logits_from_hidden(params, cfg_, hidden)
            toks = sample_tokens(logits, temperature, top_k, top_p, keys)
            return toks, new_caches

        def _unified(params, token_ids, kv_caches, positions, slot_mapping,
                     page_tables, seq_lens, cu_q_lens, q_lens, num_seqs,
                     last_idx, temperature, top_k, top_p, keys):
            # ONE executable for a mixed prefill+decode step: the
            # token-packed ragged forward (ops/ragged_paged_attention.py)
            # writes KV through the same slot-mapping scatter, then
            # samples ON DEVICE from each sequence's last-token row —
            # non-final chunk rows sample discarded tokens (greedy
            # padding params keep the sampler's fast path).  Shapes vary
            # only in the token axis, so the jit cache is a 1-D
            # token-bucket line instead of a (batch, seq) grid.
            hidden, new_caches = tfm.forward_unified(
                params, cfg_, token_ids, positions, kv_caches,
                slot_mapping, page_tables, seq_lens, cu_q_lens, q_lens,
                num_seqs,
            )
            last_hidden = hidden[last_idx]  # [S, hidden]
            logits = tfm.logits_from_hidden(params, cfg_, last_hidden)
            toks = sample_tokens(logits, temperature, top_k, top_p, keys)
            return toks, new_caches

        ps_ = page_size

        def _decode_multi(params, token_ids, kv_caches, positions, gpos,
                          valid, block_tables, temperature, top_k, top_p,
                          base_keys, n_steps):
            """``n_steps`` decode iterations in ONE device execution:
            forward -> sample (on device) -> feed back, via lax.scan.
            Amortizes the host<->device round trip that dominates decode
            latency on remote-attached chips (vLLM's TPU backend does
            the same).  Per-step KV slots derive on device from the
            block table and the running global position ``gpos`` — the
            scheduler pre-allocated pages for the whole window.  Returns
            (tokens [n_steps, B], new kv_caches)."""

            def body(carry, step):
                tok, pos, g, kv = carry
                page = jnp.take_along_axis(
                    block_tables, (g // ps_)[:, None], axis=1)[:, 0]
                slot = jnp.where(valid, page * ps_ + g % ps_, -1)
                hidden, kv = tfm.forward_decode(
                    params, cfg_, tok, pos, kv, slot, block_tables,
                    g + 1)
                logits = tfm.logits_from_hidden(params, cfg_, hidden)
                keys = jax.vmap(
                    lambda kd: jax.random.key_data(jax.random.fold_in(
                        jax.random.wrap_key_data(kd), step)))(base_keys)
                nxt = sample_tokens(logits, temperature, top_k, top_p,
                                    keys)
                return (nxt, pos + 1, g + 1, kv), nxt

            (_, _, _, kv_caches), toks = jax.lax.scan(
                body, (token_ids, positions, gpos, kv_caches),
                jnp.arange(n_steps))
            return toks, kv_caches

        if mesh is None:
            jit2 = functools.partial(jax.jit, donate_argnums=(2,))
            self._prefill_fn = jit2(_prefill)
            self._chunk_prefill_fn = jit2(_chunk_prefill)
            self._verify_fn = jit2(_verify)
            self._decode_fn = jit2(_decode)
            self._decode_sample_fn = jit2(_decode_sample)
            self._unified_fn = (jit2(_unified)
                                if self.unified_batching else None)
            self._decode_multi_fn = jax.jit(
                _decode_multi, donate_argnums=(2,),
                static_argnums=(11,))
        else:
            # TP: shard_map over the tp axis — params/KV are the only
            # sharded operands; token inputs replicate, and the psums in
            # _layer_step make activations (logits/hidden) replicated
            # outputs. shard_map (not GSPMD) because the Pallas attention
            # kernels cannot be auto-partitioned by XLA.
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            from vllm_omni_tpu.parallel.sharding import (
                ar_kv_cache_spec,
                ar_param_specs_tree,
            )

            pspecs = ar_param_specs_tree(params)
            kv_specs = [ar_kv_cache_spec()] * cfg.num_layers
            rep = P()

            def wrap(f, n_rest, n_out):
                sm = shard_map(
                    f, mesh=mesh,
                    in_specs=(pspecs, rep, kv_specs) + (rep,) * n_rest,
                    out_specs=(rep,) * n_out + (kv_specs,),
                    check_vma=False,
                )
                return jax.jit(sm, donate_argnums=(2,))

            self._prefill_fn = wrap(_prefill, 6, 3)
            self._chunk_prefill_fn = wrap(_chunk_prefill, 9, 3)
            self._verify_fn = wrap(_verify, 5, 2)
            self._decode_fn = wrap(_decode, 4, 2)
            # sampling is deterministic in (logits, keys) and the
            # per-layer psums make logits replicated, so every shard
            # samples the same token — same argument as _decode_multi_tp
            self._decode_sample_fn = wrap(_decode_sample, 8, 1)
            # unified ragged step under TP: the ragged kernel runs on
            # LOCAL head shapes inside the same shard_map wrap as the
            # decode path (TPLA stance, PAPERS.md); metadata replicates
            self._unified_fn = (wrap(_unified, 12, 1)
                                if self.unified_batching else None)

            # Multi-step decode under TP: the scan lives INSIDE the
            # shard_map body, so the KV carry stays on local shard
            # shapes throughout the window.  The per-layer psums make
            # hidden/logits replicated, and sampling is deterministic
            # in (logits, keys) — every shard samples the same token,
            # so the fed-back carry stays consistent without a
            # collective.  n_steps must be static for the scan length:
            # the shard_map closes over it per jit specialization.
            @functools.partial(jax.jit, donate_argnums=(2,),
                               static_argnums=(11,))
            def _decode_multi_tp(params, token_ids, kv_caches, positions,
                                 gpos, valid, block_tables, temperature,
                                 top_k, top_p, base_keys, n_steps):
                sm = shard_map(
                    lambda p, t, k, *rest: _decode_multi(
                        p, t, k, *rest, n_steps),
                    mesh=mesh,
                    in_specs=(pspecs, rep, kv_specs) + (rep,) * 8,
                    out_specs=(rep, kv_specs),
                    check_vma=False,
                )
                return sm(params, token_ids, kv_caches, positions, gpos,
                          valid, block_tables, temperature, top_k, top_p,
                          base_keys)

            self._decode_multi_fn = _decode_multi_tp
        # speculative decoding (MTP draft head): draft_fn(last_hidden [M,H],
        # last_token [M], positions [M]) -> [M, k] proposals
        self.draft_fn = None
        self.num_draft_tokens = 0
        self.spec_stats = {"verify_steps": 0, "proposed": 0, "accepted": 0}
        # width of upstream embeds accepted by this model: the embed_proj
        # input dim when present (thinker width for the talker), else the
        # model's own hidden size
        self.embeds_width = (
            params["embed_proj"]["w"].shape[0]
            if "embed_proj" in params else cfg.hidden_size
        )

    def set_draft_fn(self, draft_fn, num_draft_tokens: int) -> None:
        """Install the MTP draft head (talker spec decode, reference:
        gpu_ar_model_runner.py:466-497 EAGLE propose).  A draft_fn taking
        a ``contexts`` kwarg also receives each drafted request's full
        post-step token history (oracle/tree drafters)."""
        import inspect

        self.draft_fn = draft_fn
        self.num_draft_tokens = num_draft_tokens
        # memory-ledger estimate of the verify-path buffers: the widest
        # batch's (k+1)-row logits at float32 (deterministic — the
        # ledger's CPU fallback must not depend on allocator probes)
        self._spec_bytes = (self._batch_buckets[-1]
                            * (num_draft_tokens + 1)
                            * self.cfg.vocab_size * 4)
        try:
            sig = inspect.signature(draft_fn)
            self._draft_takes_contexts = "contexts" in sig.parameters
        except (TypeError, ValueError):
            self._draft_takes_contexts = False

    # -------------------------------------------------- dispatch telemetry
    def _run_jit(self, kind: str, shape_key: tuple, thunk):
        """Invoke one jitted executable through the telemetry choke
        point: counts the device dispatch (mixed-step tests assert ONE
        per unified step) and classifies it fresh-compile vs cache-hit
        by this runner's own (kind, shape) signature.  A fresh signature
        is timed TO COMPLETION (block_until_ready) so compile_s measures
        the real compile+first-run stall — warmup prepopulates the
        signatures, so steady-state traffic takes the unsynced branch."""
        self.dispatch_count += 1
        key = (kind,) + tuple(shape_key)
        if key in self._jit_seen:
            self.compile_stats["cache_hits"] += 1
            return thunk()
        self._jit_seen.add(key)
        t0 = time.perf_counter()
        self.compile_stats["in_flight"] = 1
        try:
            result = thunk()
            jax.block_until_ready(result)
        finally:
            self.compile_stats["in_flight"] = 0
        self.compile_stats["compiles"] += 1
        self.compile_stats["compile_s"] += time.perf_counter() - t0
        return result

    def memory_components(self) -> dict:
        """Attributable device-memory components for the engine's
        ledger (introspection/memory_ledger.py): static buffer sizes
        from array metadata — never a device sync."""
        comps = {"weights": self._weights_bytes,
                 "kv_pages": self._kv_bytes}
        if self._spec_bytes:
            comps["spec_buffers"] = self._spec_bytes
        return comps

    def _note_padding(self, useful: int, padded: int) -> None:
        self.useful_tokens += int(useful)
        self.padded_tokens += int(padded)

    def _decode_bucket(self, n: int) -> int:
        """Batch bucket for the single-token decode family.  With
        ``deterministic_decode`` every decode step pads to the TOP
        bucket: XLA fuses the [B]-leading decode matmuls differently
        per bucket shape, so the same row decoded in a bucket-4 batch
        and a bucket-8 batch can differ in the last bf16 bit — enough
        to flip a greedy argmax on near-flat logits.  One fixed bucket
        makes a request's stream invariant to co-batch occupancy
        (preemptions and arrivals stop perturbing OTHER requests'
        tokens) at the cost of padded rows when the batch runs small."""
        if self.deterministic_decode:
            return self._batch_buckets[-1]
        return _bucket(n, self._batch_buckets)

    # ---------------------------------------------------------- precompile
    def precompile(self, prefill_shapes=(), decode: bool = True,
                   progress_fn=None) -> int:
        """Build bucketed executables BEFORE serving traffic.

        XLA compiles one executable per input-shape signature, and a
        cache miss mid-traffic stalls every in-flight request for the
        full compile — measured 20-40 s per shape on a remote-attached
        chip (the reference warms its runner at startup for the same
        reason: worker warmup / CUDA-graph capture,
        vllm_omni/worker/gpu_ar_model_runner.py capture path).

        ``decode`` compiles the single-step and (when configured)
        multi-step executables for every batch bucket — engine traffic
        can only ever produce those two scan lengths (core/scheduler.py
        hands out the full window or 1) — plus, when a draft head is
        installed, the spec-verify executable at its candidate length.
        ``prefill_shapes`` is an iterable of (batch, seq_len) pairs for
        the prompt shapes the deployment expects — bucketed and deduped
        here, so callers pass raw traffic shapes.  Each pair warms BOTH
        the fresh-prefill and the chunked-continuation executable at
        EVERY batch bucket up to the given batch (APC prefix hits and
        scheduler admission split one arrival wave into smaller
        fresh/chunked sub-batches, each bucketed separately); a
        continuation whose remainder buckets to a seq bucket not listed
        still compiles on first hit — include the chunk lengths you
        expect in ``prefill_shapes``.  Dummy inputs
        write to KV slot -1, which the paged cache update drops
        (ops/paged_attention.py write_kv mode="drop"), so the live KV
        pool is untouched.

        Returns the number of executables requested (cached ones are
        free)."""
        built = 0

        def note(msg):
            if progress_fn is not None:
                progress_fn(msg)

        def pos_shape(b, s=None):
            if s is None:
                return (b, 3) if self.use_mrope else (b,)
            return (b, 3, s) if self.use_mrope else (b, s)

        def warm(kind, key, thunk):
            nonlocal built
            res = self._run_jit(kind, key, thunk)
            built += 1
            return res

        if decode:
            # deterministic decode runs every step at the top bucket —
            # the smaller executables can never be dispatched
            decode_buckets = (self._batch_buckets[-1:]
                              if self.deterministic_decode
                              else self._batch_buckets)
            for b in decode_buckets:
                note(f"precompile decode b={b}")
                zeros_b = jnp.zeros((b,), jnp.int32)
                tables = jnp.zeros((b, self.max_pages_per_seq), jnp.int32)
                _, _, self.kv_caches = warm(
                    "decode", (b,), lambda: self._decode_fn(
                        self.params, zeros_b, self.kv_caches,
                        jnp.zeros(pos_shape(b), jnp.int32),
                        jnp.full((b,), -1, jnp.int32), tables,
                        jnp.ones((b,), jnp.int32)))
                if self.async_scheduling:
                    # the async pipeline's dispatch path (forward +
                    # on-device sampling) is its own executable
                    t = SamplingTensors.build(
                        [_PAD_SAMPLING] * b, step=0,
                        base_seed=self._base_seed)
                    _, self.kv_caches = warm(
                        "dispatch", (b,), lambda: self._decode_sample_fn(
                            self.params, zeros_b, self.kv_caches,
                            jnp.zeros(pos_shape(b), jnp.int32),
                            jnp.full((b,), -1, jnp.int32), tables,
                            jnp.ones((b,), jnp.int32),
                            t.temperature, t.top_k, t.top_p, t.keys))
                if (self.multi_step_decode > 1
                        and self._decode_multi_fn is not None):
                    t = SamplingTensors.build(
                        [_PAD_SAMPLING] * b, step=0,
                        base_seed=self._base_seed)
                    # valid=False derives slot -1 on device: the whole
                    # window's KV writes drop
                    _, self.kv_caches = warm(
                        "multi", (b, self.multi_step_decode),
                        lambda: self._decode_multi_fn(
                            self.params, zeros_b, self.kv_caches,
                            jnp.zeros(pos_shape(b), jnp.int32), zeros_b,
                            jnp.zeros((b,), bool), tables,
                            t.temperature, t.top_k, t.top_p, t.keys,
                            self.multi_step_decode))
                if self.draft_fn is not None and self.num_draft_tokens:
                    # spec-decode verify batches run at the candidate
                    # length (1 regular + k draft positions)
                    s = _bucket(1 + self.num_draft_tokens,
                                self._seq_buckets)
                    _, _, self.kv_caches = warm(
                        "verify", (b, s, self.max_pages_per_seq),
                        lambda: self._verify_fn(
                            self.params, jnp.zeros((b, s), jnp.int32),
                            self.kv_caches,
                            jnp.zeros(pos_shape(b, s), jnp.int32),
                            jnp.full((b, s), -1, jnp.int32), tables,
                            jnp.ones((b,), jnp.int32),
                            jnp.zeros((b,), jnp.int32)))
        if self._unified_fn is not None:
            # ONE executable per token bucket — the 1-D shape-cache line
            # that replaces the (batch, seq) grid for mixed steps
            s_max = self._batch_buckets[-1]
            t = SamplingTensors.build(
                [_PAD_SAMPLING] * s_max, step=0,
                base_seed=self._base_seed)
            for t_pad in self._token_buckets:
                note(f"precompile unified t={t_pad}")
                pos = (jnp.zeros((3, t_pad), jnp.int32) if self.use_mrope
                       else jnp.zeros((t_pad,), jnp.int32))
                _, self.kv_caches = warm(
                    "unified", (t_pad,), lambda: self._unified_fn(
                        self.params, jnp.zeros((t_pad,), jnp.int32),
                        self.kv_caches, pos,
                        jnp.full((t_pad,), -1, jnp.int32),
                        jnp.zeros((s_max, self.max_pages_per_seq),
                                  jnp.int32),
                        jnp.zeros((s_max,), jnp.int32),
                        jnp.zeros((s_max + 1,), jnp.int32),
                        jnp.zeros((s_max,), jnp.int32),
                        jnp.zeros((1,), jnp.int32),
                        jnp.zeros((s_max,), jnp.int32),
                        t.temperature, t.top_k, t.top_p, t.keys))

        seen_chunks = set()
        for b, s in _bucketed_prefill_shapes(
                prefill_shapes, self._batch_buckets, self._seq_buckets):
            note(f"precompile prefill b={b} s={s}")
            # trailing (None, None, None) mirrors _prefill_common's
            # *embeds_args for a token-only batch: jit's cache key
            # covers the argument TREE, so the same shapes with a
            # different arity would still be a fresh executable
            _, _, _, self.kv_caches = warm(
                "prefill", (b, s, False, False), lambda: self._prefill_fn(
                    self.params, jnp.zeros((b, s), jnp.int32),
                    self.kv_caches, jnp.zeros(pos_shape(b, s), jnp.int32),
                    jnp.full((b, s), -1, jnp.int32),
                    jnp.zeros((b,), jnp.int32), None, None, None))
            # APC prefix hits / chunked-prefill continuations run the
            # chunked executable; its signature is (batch, chunk bucket,
            # context pages) where pages derive from the CONTEXT's seq
            # bucket (_cont_tables).  Warm the two dominant combos for
            # this context: a full-width chunk (recompute/resume) and a
            # minimum-bucket chunk (short APC remainder after a long
            # cached prefix).  Intermediate chunk buckets still compile
            # on first hit — list them in prefill_shapes if expected.
            pages = -(-s // self.page_size)
            for s_chunk in {s, self._seq_buckets[0]}:
                key = ("chunk", b, s_chunk, pages)
                if key in seen_chunks:
                    continue
                seen_chunks.add(key)
                _, _, _, self.kv_caches = warm(
                    "chunk", (b, s_chunk, pages, False, False),
                    lambda: self._chunk_prefill_fn(
                        self.params, jnp.zeros((b, s_chunk), jnp.int32),
                        self.kv_caches,
                        jnp.zeros(pos_shape(b, s_chunk), jnp.int32),
                        jnp.full((b, s_chunk), -1, jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, pages), jnp.int32),
                        jnp.ones((b,), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        None, None, None))
        return built

    # ---------------------------------------------------------------- step
    def execute(
        self, sched_out: SchedulerOutput, extract_kv: bool = True
    ) -> RunnerOutput:
        self._step += 1
        out = RunnerOutput()
        if self._unified_eligible(sched_out):
            # mixed (or pure-prefill) step as ONE token-packed dispatch
            self._run_unified(sched_out.decodes + sched_out.prefills, out)
        else:
            self._execute_split(sched_out, out)
        for req, block_ids, seq_len in sched_out.kv_transfer_requests:
            # skip the device→host gather when no sink consumes it, but
            # still ACK so the scheduler releases the pinned pages
            if extract_kv:
                out.extracted_kv[req.request_id] = self.extract_kv(
                    block_ids, seq_len
                )
            out.kv_extracted_req_ids.add(req.request_id)
        return out

    def _execute_split(self, sched_out: SchedulerOutput,
                       out: RunnerOutput) -> None:
        """The bucketed-jit split path: up to three separately padded
        executables per step (fresh prefill / chunked continuation /
        decode) — the fallback matrix behind the unified ragged path
        (spec decode, logprobs, collect_hidden, embeds inputs; see
        docs/ragged_batching.md)."""
        plain = [s for s in sched_out.decodes if s.num_new_tokens == 1]
        spec = [s for s in sched_out.decodes if s.num_new_tokens > 1]
        if plain:
            # Multi-step window: the scheduler hands out the FULL
            # configured window or window=1, never an intermediate
            # length (each distinct scan length is its own executable —
            # a mid-run tail compile measured 21 s on a remote chip).
            # The rare window=1 stragglers (near max_model_len / budget
            # exhaustion) run as their own single-step batch instead of
            # cliffing the windowed batch down with them.
            full = [s for s in plain if s.window > 1]
            single = [s for s in plain if s.window == 1]
            if (full and self._decode_multi_fn is not None
                    and self.draft_fn is None
                    and not self.collect_hidden
                    and all(s.request.sampling_params.logprobs is None
                            for s in full)):
                self._run_decode_multi(full, full[0].window, out)
                if single:
                    self._run_decode(single, out)
            else:
                self._run_decode(plain, out)
        if spec:
            self._run_spec_decode(spec, out)
        if sched_out.prefills:
            # Three-way split: continuation chunks (cached prefix; the
            # chunked kernel gathers context pages) run separately from
            # fresh prefills, and embeds-as-input prefills (downstream
            # stages consuming upstream hidden states) run as a separate
            # padded batch — the jit signature differs per variant.
            fresh = [s for s in sched_out.prefills if s.start_pos == 0]
            cont = [s for s in sched_out.prefills if s.start_pos > 0]
            for group, runner in ((fresh, self._run_prefill),
                                  (cont, self._run_chunk_prefill)):
                with_embeds = [s for s in group
                               if s.request.prompt_embeds is not None]
                token_only = [s for s in group
                              if s.request.prompt_embeds is None]
                if token_only:
                    runner(token_only, out)
                if with_embeds:
                    runner(with_embeds, out, use_embeds=True)

    # ---------------------------------------------------- unified ragged
    def _unified_eligible(self, sched_out: SchedulerOutput) -> bool:
        """Mixed/prefill steps ride the unified token-packed executable
        when the scheduler emitted a unified batch and nothing in it
        needs the split path (the fallback matrix: spec decode,
        logprobs, collect_hidden, embeds/deepstack inputs, multi-step
        windows).  Pure-decode steps keep the dedicated [B] decode
        executables — 1 row per sequence beats token-block alignment."""
        if self._unified_fn is None or not getattr(
                sched_out, "unified", False):
            return False
        if not sched_out.prefills:
            return False
        if self.collect_hidden or self.draft_fn is not None:
            return False
        scheds = sched_out.decodes + sched_out.prefills
        if len(scheds) > self._batch_buckets[-1]:
            return False
        total = sum(align_to_block(s.num_new_tokens) for s in scheds)
        if total > self._token_buckets[-1]:
            return False
        for s in sched_out.decodes:
            if s.num_new_tokens != 1 or s.window != 1:
                return False
        for s in scheds:
            req = s.request
            if (req.sampling_params.logprobs is not None
                    or req.prompt_embeds is not None
                    or req.deepstack_embeds):
                return False
        return True

    def _assemble_unified(self, scheds: list[ScheduledRequest]):
        """Token-packed device inputs for a mixed batch: each sequence's
        chunk occupies a token-block-aligned segment of the flat token
        axis (the layout contract of ops/ragged_paged_attention.py);
        metadata arrays are fixed [S_max] width so shapes vary only in
        the token bucket."""
        s_max = self._batch_buckets[-1]
        n = len(scheds)
        cu = np.zeros((s_max + 1,), np.int32)
        q_lens = np.zeros((s_max,), np.int32)
        seq_lens = np.zeros((s_max,), np.int32)
        tables = np.zeros((s_max, self.max_pages_per_seq), np.int32)
        total = 0
        for i, sc in enumerate(scheds):
            cu[i] = total
            q_lens[i] = sc.num_new_tokens
            seq_lens[i] = sc.start_pos + sc.num_new_tokens
            t = sc.block_table[: self.max_pages_per_seq]
            tables[i, : len(t)] = t
            total += align_to_block(sc.num_new_tokens)
        cu[n:] = total
        t_pad = _bucket(max(total, self._token_buckets[0]),
                        self._token_buckets)
        token_ids = np.zeros((t_pad,), np.int32)
        positions = (np.zeros((3, t_pad), np.int32) if self.use_mrope
                     else np.zeros((t_pad,), np.int32))
        slots = np.full((t_pad,), -1, np.int32)
        last_idx = np.zeros((s_max,), np.int32)
        for i, sc in enumerate(scheds):
            m = sc.num_new_tokens
            lo = int(cu[i])
            # an async-fed decode row's input token is still in flight
            # (all_token_ids slice comes back empty): dispatch_unified
            # scatters it device-side from the previous handle
            toks = sc.request.all_token_ids[sc.start_pos: sc.start_pos + m]
            token_ids[lo: lo + len(toks)] = toks
            p = np.arange(sc.start_pos, sc.start_pos + m)
            if self.use_mrope:
                positions[:, lo: lo + m] = self._mrope_cols(sc.request, p)
            else:
                positions[lo: lo + m] = p
            slots[lo: lo + m] = sc.slot_mapping
            last_idx[i] = lo + m - 1
        return UnifiedBatch(token_ids, positions, slots, tables,
                            seq_lens, cu, q_lens, last_idx, t_pad, total)

    def _unified_sampling(self, scheds, key_tag: str, t_pad: int):
        """[S_max]-wide SamplingTensors: real params on rows whose chunk
        reaches the sequence's last token (the sequence-final flag),
        greedy padding elsewhere (keeps sample_tokens' fast path)."""
        s_max = self._batch_buckets[-1]
        params_list = [_PAD_SAMPLING] * s_max
        salts = [0] * s_max
        final = []
        for i, sc in enumerate(scheds):
            req = sc.request
            if sc.samples_final:
                final.append((i, sc))
                params_list[i] = req.sampling_params
                salts[i] = self._salt_of(req.request_id)
        key = (key_tag, t_pad) + tuple(
            (i, sc.request.request_id)
            + _params_key(sc.request.sampling_params) for i, sc in final)
        return self._sampling_tensors(key, params_list, salts), final

    def _call_unified(self, asm: UnifiedBatch, tensors, token_ids,
                      n: int):
        """Shared device-invocation half of the sync and async unified
        paths — ONE dispatch for the whole mixed batch."""
        self._note_padding(int(asm.q_lens.sum()), asm.t_pad)
        toks, self.kv_caches = self._run_jit(
            "unified", (asm.t_pad,), lambda: self._unified_fn(
                self.params, token_ids, self.kv_caches,
                jnp.asarray(asm.positions), jnp.asarray(asm.slots),
                jnp.asarray(asm.tables), jnp.asarray(asm.seq_lens),
                jnp.asarray(asm.cu_q_lens), jnp.asarray(asm.q_lens),
                jnp.asarray([n], jnp.int32), jnp.asarray(asm.last_idx),
                tensors.temperature, tensors.top_k, tensors.top_p,
                tensors.keys))
        return toks

    def _run_unified(self, scheds: list[ScheduledRequest],
                     out: RunnerOutput) -> None:
        asm = self._assemble_unified(scheds)
        tensors, final = self._unified_sampling(scheds, "unified",
                                                asm.t_pad)
        toks = self._call_unified(asm, tensors,
                                  jnp.asarray(asm.token_ids),
                                  len(scheds))
        # omnilint: disable=OL2 - batch boundary: scheduler needs tokens
        toks = np.asarray(jax.device_get(toks))
        for i, sc in final:
            out.sampled[sc.request.request_id] = int(toks[i])

    def dispatch_unified(
        self, sched_out: SchedulerOutput,
        prev: Optional[InflightDecode] = None,
    ) -> InflightDecode:
        """Async dispatch of a unified MIXED step: prefill chunks no
        longer force the two-slot pipeline to drain (engine/
        llm_engine.py).  Decode rows whose input token is still in
        flight gather it device-side from ``prev.tokens`` — the same
        device-resident feedback as ``dispatch_decode``; the returned
        handle is retire-compatible with it (``retire_decode``)."""
        self._step += 1
        scheds = sched_out.decodes + sched_out.prefills
        asm = self._assemble_unified(scheds)
        tensors, final = self._unified_sampling(scheds, "udispatch",
                                                asm.t_pad)
        feed_dst: list[int] = []
        feed_src: list[int] = []
        for i, sc in enumerate(scheds):
            if sc.start_pos >= sc.request.num_tokens:
                # input token sampled by the previous dispatch, still
                # device-resident
                feed_dst.append(int(asm.cu_q_lens[i]))
                feed_src.append(prev.rows[sc.request.request_id])
        token_ids = jnp.asarray(asm.token_ids)
        if feed_dst:
            token_ids = token_ids.at[jnp.asarray(feed_dst)].set(
                prev.tokens[jnp.asarray(feed_src)])
        toks = self._call_unified(asm, tensors, token_ids, len(scheds))
        return InflightDecode(
            tokens=toks,
            rows={sc.request.request_id: i for i, sc in final},
        )

    # ------------------------------------------------------------- prefill
    def _run_prefill(self, scheds: list[ScheduledRequest], out: RunnerOutput,
                     use_embeds: bool = False):
        self._prefill_common(scheds, out, use_embeds, cont=False)

    def _run_chunk_prefill(self, scheds: list[ScheduledRequest],
                           out: RunnerOutput, use_embeds: bool = False):
        """Later chunks of a chunked prefill: the chunk attends the cached
        KV of earlier chunks through its block table."""
        self._prefill_common(scheds, out, use_embeds, cont=True)

    def _prefill_common(self, scheds: list[ScheduledRequest],
                        out: RunnerOutput, use_embeds: bool, cont: bool):
        """Shared padded-batch assembly for fresh prefills and chunk
        continuations; ``cont`` adds the block-table/context/q-start
        operands the cached-context kernel needs."""
        b = _bucket(len(scheds), self._batch_buckets)
        max_n = max(s.num_new_tokens for s in scheds)
        s_len = _bucket(max_n, self._seq_buckets)

        token_ids = np.zeros((b, s_len), np.int32)
        positions = (np.zeros((b, 3, s_len), np.int32) if self.use_mrope
                     else np.zeros((b, s_len), np.int32))
        slots = np.full((b, s_len), -1, np.int32)
        last_idx = np.zeros((b,), np.int32)
        embeds = (np.zeros((b, s_len, self.embeds_width), np.float32)
                  if use_embeds else None)
        embeds_mask = np.zeros((b, s_len), bool) if use_embeds else None
        # deepstack multiscale visual features, shipped as sparse
        # (offset, [n_deep, T_item, hidden]) spans on the request and
        # scattered here (zeros at non-visual rows): level i adds to the
        # residual stream after decoder layer i
        n_deep = max((arr.shape[0]
                      for s in scheds
                      for off, arr in (s.request.deepstack_embeds or ())
                      if off < s.start_pos + s.num_new_tokens
                      and off + arr.shape[1] > s.start_pos),
                     default=0)
        deep = (np.zeros((b, n_deep, s_len, self.cfg.hidden_size),
                         np.float32) if n_deep else None)
        if cont:
            tables, ctx, q_starts, pages = self._cont_tables(scheds, b)
        for i, sc in enumerate(scheds):
            n = sc.num_new_tokens
            toks = sc.request.all_token_ids[sc.start_pos: sc.start_pos + n]
            token_ids[i, :n] = toks
            p = np.arange(sc.start_pos, sc.start_pos + n)
            if self.use_mrope:
                positions[i, :, :n] = self._mrope_cols(sc.request, p)
            else:
                positions[i, :n] = p
            slots[i, :n] = sc.slot_mapping
            last_idx[i] = n - 1
            if use_embeds:
                # embeds cover prompt rows only; a recompute-resumed request
                # also re-prefills its generated tokens, which embed from
                # the table (mask False)
                pe = np.asarray(sc.request.prompt_embeds)
                lo = min(sc.start_pos, pe.shape[0])
                hi = min(sc.start_pos + n, pe.shape[0])
                embeds[i, : hi - lo] = pe[lo:hi]
                embeds_mask[i, : hi - lo] = True
            if deep is not None:
                # intersect each visual span with this chunk's window
                # [start_pos, start_pos+n); rows outside any span (text,
                # re-prefilled generated tokens) stay zero
                for off, arr in sc.request.deepstack_embeds or ():
                    lo = max(off, sc.start_pos)
                    hi = min(off + arr.shape[1], sc.start_pos + n)
                    if lo < hi:
                        deep[i, : arr.shape[0],
                             lo - sc.start_pos: hi - sc.start_pos] = (
                            arr[:, lo - off: hi - off])

        embeds_args = (
            (jnp.asarray(embeds, dtype=self.params_dtype)
             if use_embeds else None),
            jnp.asarray(embeds_mask) if use_embeds else None,
            (jnp.asarray(deep, dtype=self.params_dtype)
             if deep is not None else None),
        )
        self._note_padding(sum(s.num_new_tokens for s in scheds),
                           b * s_len)
        if cont:
            logits, last_hidden, hidden, self.kv_caches = self._run_jit(
                "chunk", (b, s_len, pages, use_embeds, deep is not None),
                lambda: self._chunk_prefill_fn(
                    self.params, jnp.asarray(token_ids), self.kv_caches,
                    jnp.asarray(positions), jnp.asarray(slots),
                    jnp.asarray(last_idx), jnp.asarray(tables),
                    jnp.asarray(ctx), jnp.asarray(q_starts), *embeds_args,
                )
            )
        else:
            logits, last_hidden, hidden, self.kv_caches = self._run_jit(
                "prefill", (b, s_len, use_embeds, deep is not None),
                lambda: self._prefill_fn(
                    self.params, jnp.asarray(token_ids), self.kv_caches,
                    jnp.asarray(positions), jnp.asarray(slots),
                    jnp.asarray(last_idx), *embeds_args,
                )
            )
        self._sample_and_record(scheds, logits, last_hidden, out,
                                full_hidden=hidden)
        self._maybe_draft(scheds, last_hidden, out)

    def _cont_tables(self, scheds: list[ScheduledRequest], b: int):
        """Block-table / context-length / q-start operands shared by the
        chunk-continuation and spec-verify paths (both feed
        forward_prefill_chunked — one assembly, one bucketing policy)."""
        max_ctx = max(s.start_pos + s.num_new_tokens for s in scheds)
        ctx_bucket = _bucket(max_ctx, self._seq_buckets)
        pages = -(-ctx_bucket // self.page_size)
        tables = np.zeros((b, pages), np.int32)
        ctx = np.zeros((b,), np.int32)
        q_starts = np.zeros((b,), np.int32)
        for i, sc in enumerate(scheds):
            t = sc.block_table[:pages]
            tables[i, : len(t)] = t
            ctx[i] = sc.start_pos + sc.num_new_tokens
            q_starts[i] = sc.start_pos
        return tables, ctx, q_starts, pages

    # ---------------------------------------------------- mrope positions
    def _mrope_cols(self, req, p: np.ndarray) -> np.ndarray:
        """[3, len(p)] position columns for global token indices ``p``:
        prompt rows come from the request's precomputed table, generated
        rows sit at p + delta on all three streams."""
        mp = req.mrope_positions
        if mp is None:
            return np.broadcast_to(p, (3, len(p)))
        mp = np.asarray(mp)
        out = np.empty((3, len(p)), np.int32)
        in_prompt = p < mp.shape[1]
        out[:, in_prompt] = mp[:, p[in_prompt]]
        out[:, ~in_prompt] = p[~in_prompt][None, :] + req.mrope_delta
        return out

    # -------------------------------------------------------------- decode
    def _assemble_decode_rows(self, scheds: list[ScheduledRequest], b: int):
        """Padded (positions, slots, tables, ctx) rows for a
        single-token decode batch — ONE assembly shared by the
        synchronous decode and the pipelined dispatch, so their input
        semantics (mrope columns, ctx = start_pos + 1, table
        truncation) cannot drift apart."""
        positions = (np.zeros((b, 3), np.int32) if self.use_mrope
                     else np.zeros((b,), np.int32))
        slots = np.full((b,), -1, np.int32)
        tables = np.zeros((b, self.max_pages_per_seq), np.int32)
        ctx = np.zeros((b,), np.int32)
        for i, sc in enumerate(scheds):
            if self.use_mrope:
                positions[i] = self._mrope_cols(
                    sc.request, np.asarray([sc.start_pos]))[:, 0]
            else:
                positions[i] = sc.start_pos
            slots[i] = sc.slot_mapping[0]
            t = sc.block_table[: self.max_pages_per_seq]
            tables[i, : len(t)] = t
            ctx[i] = sc.start_pos + 1
        return positions, slots, tables, ctx

    def _run_decode(self, scheds: list[ScheduledRequest], out: RunnerOutput):
        b = self._decode_bucket(len(scheds))
        token_ids = np.zeros((b,), np.int32)
        for i, sc in enumerate(scheds):
            token_ids[i] = sc.request.all_token_ids[sc.start_pos]
        positions, slots, tables, ctx = self._assemble_decode_rows(
            scheds, b)
        self._note_padding(len(scheds), b)
        logits, hidden, self.kv_caches = self._run_jit(
            "decode", (b,), lambda: self._decode_fn(
                self.params, jnp.asarray(token_ids), self.kv_caches,
                jnp.asarray(positions), jnp.asarray(slots),
                jnp.asarray(tables), jnp.asarray(ctx),
            )
        )
        self._sample_and_record(scheds, logits, hidden, out)
        self._maybe_draft(scheds, hidden, out)

    # ------------------------------------------------ pipelined dispatch
    def dispatch_decode(
        self, scheds: list[ScheduledRequest],
        prev: Optional[InflightDecode] = None,
    ) -> InflightDecode:
        """Dispatch half of the async pipelined step: launch forward +
        on-device sampling for a pure single-token decode batch and
        return WITHOUT waiting.  Input tokens that are not host-visible
        yet (they were sampled by ``prev``, still in flight) are
        gathered device-side from ``prev.tokens`` — the device-resident
        feedback that keeps the host out of the token loop.  The engine
        retires the handle one step later (``retire_decode``)."""
        self._step += 1
        b = self._decode_bucket(len(scheds))
        token_host = np.zeros((b,), np.int32)
        feed_rows: list[int] = []
        feed_src: list[int] = []
        params_list = [_PAD_SAMPLING] * b
        salts = [0] * b
        for i, sc in enumerate(scheds):
            req = sc.request
            if sc.start_pos < req.num_tokens:
                token_host[i] = req.all_token_ids[sc.start_pos]
            else:
                # input token still in flight from the previous dispatch
                feed_rows.append(i)
                feed_src.append(prev.rows[req.request_id])
            params_list[i] = req.sampling_params
            salts[i] = self._salt_of(req.request_id)
        positions, slots, tables, ctx = self._assemble_decode_rows(
            scheds, b)
        token_ids = jnp.asarray(token_host)
        if feed_rows:
            token_ids = token_ids.at[jnp.asarray(feed_rows)].set(
                prev.tokens[jnp.asarray(feed_src)])
        key = ("dispatch", b) + tuple(
            (sc.request.request_id,) + _params_key(
                sc.request.sampling_params) for sc in scheds)
        tensors = self._sampling_tensors(key, params_list, salts)
        self._note_padding(len(scheds), b)
        toks, self.kv_caches = self._run_jit(
            "dispatch", (b,), lambda: self._decode_sample_fn(
                self.params, token_ids, self.kv_caches,
                jnp.asarray(positions), jnp.asarray(slots),
                jnp.asarray(tables), jnp.asarray(ctx),
                tensors.temperature, tensors.top_k, tensors.top_p,
                tensors.keys,
            )
        )
        return InflightDecode(
            tokens=toks,
            rows={sc.request.request_id: i for i, sc in enumerate(scheds)},
        )

    def retire_decode(self, handle: InflightDecode) -> dict[str, int]:
        """Retire half: the ONE host readback of a pipelined step,
        lagged a full step behind dispatch so it overlaps the next
        step's device compute instead of serializing against it."""
        # omnilint: disable=OL2 - the single lagged retire sync of the
        # async pipeline: by the time the engine calls this, the NEXT
        # step is already dispatched, so this get overlaps its compute
        toks = np.asarray(jax.device_get(handle.tokens))
        return {rid: int(toks[i]) for rid, i in handle.rows.items()}

    # ----------------------------------------------- sampling host caches
    def _salt_of(self, request_id: str) -> int:
        """Cached zlib.crc32 sampling salt (recomputing it for every
        request every step was measurable in the step-phase breakdown)."""
        s = self._salt_cache.get(request_id)
        if s is None:
            if len(self._salt_cache) > 8192:
                self._salt_cache.clear()
            s = self._salt_cache[request_id] = zlib.crc32(
                request_id.encode())
        return s

    def _sampling_tensors(self, key: tuple, params_list, salts
                          ) -> SamplingTensors:
        """SamplingTensors for this batch, reused across steps while the
        (request set, params) composition is unchanged.  Only the PRNG
        keys fold the step index, so a cache hit re-keys in one tiny
        dispatch — and an all-greedy batch (keys unused by argmax) skips
        even that."""
        hit = self._st_cache.get(key)
        if hit is not None:
            tensors, any_sampling = hit
            return tensors.rekey(self._step) if any_sampling else tensors
        tensors = SamplingTensors.build(
            params_list, step=self._step, base_seed=self._base_seed,
            salts=salts,
        )
        if len(self._st_cache) > 8:
            self._st_cache.clear()
        self._st_cache[key] = (
            tensors, any(p.temperature > 0.0 for p in params_list))
        return tensors

    # ---------------------------------------------------- multi-step decode
    def _run_decode_multi(self, scheds: list[ScheduledRequest], w: int,
                          out: RunnerOutput):
        """Advance the whole decode batch ``w`` steps in one device call
        (sampling on device inside the scan).  Tokens come back [w, B];
        each request's run is trimmed at its first stop condition — KV
        written past a stop is position-keyed garbage in that request's
        own pages, never attended and freed with the request."""
        b = self._decode_bucket(len(scheds))
        token_ids = np.zeros((b,), np.int32)
        positions = (np.zeros((b, 3), np.int32) if self.use_mrope
                     else np.zeros((b,), np.int32))
        gpos = np.zeros((b,), np.int32)
        valid = np.zeros((b,), bool)
        tables = np.zeros((b, self.max_pages_per_seq), np.int32)
        params_list = [_PAD_SAMPLING] * b
        salts = [0] * b
        for i, sc in enumerate(scheds):
            req = sc.request
            token_ids[i] = req.all_token_ids[sc.start_pos]
            if self.use_mrope:
                positions[i] = self._mrope_cols(
                    req, np.asarray([sc.start_pos]))[:, 0]
            else:
                positions[i] = sc.start_pos
            gpos[i] = sc.start_pos
            valid[i] = True
            t = sc.block_table[: self.max_pages_per_seq]
            tables[i, : len(t)] = t
            params_list[i] = req.sampling_params
            salts[i] = self._salt_of(req.request_id)
        key = ("multi", b) + tuple(
            (sc.request.request_id,) + _params_key(
                sc.request.sampling_params) for sc in scheds)
        tensors = self._sampling_tensors(key, params_list, salts)
        self._note_padding(len(scheds) * w, b * w)
        toks, self.kv_caches = self._run_jit(
            "multi", (b, w), lambda: self._decode_multi_fn(
                self.params, jnp.asarray(token_ids), self.kv_caches,
                jnp.asarray(positions), jnp.asarray(gpos),
                jnp.asarray(valid), jnp.asarray(tables),
                tensors.temperature, tensors.top_k, tensors.top_p,
                tensors.keys, w,
            )
        )
        # omnilint: disable=OL2 - the ONE sync per window (the point of
        # multi-step decode: W steps, one host round trip)
        toks = np.asarray(jax.device_get(toks))  # [w, b]
        for i, sc in enumerate(scheds):
            run = [int(x) for x in toks[:, i]]
            out.sampled[sc.request.request_id] = \
                self._truncate_at_stop(sc.request, run)

    # ------------------------------------------------- speculative decode
    def _run_spec_decode(self, scheds: list[ScheduledRequest],
                         out: RunnerOutput):
        """Verify step: run the backbone over [last_sampled, drafts...] in
        one forward (chunked-prefill kernel), accept the longest draft
        prefix that matches greedy argmax, and re-draft from the last
        accepted position."""
        b = _bucket(len(scheds), self._batch_buckets)
        max_n = max(s.num_new_tokens for s in scheds)
        s_len = _bucket(max_n, self._seq_buckets)

        token_ids = np.zeros((b, s_len), np.int32)
        positions = (np.zeros((b, 3, s_len), np.int32) if self.use_mrope
                     else np.zeros((b, s_len), np.int32))
        slots = np.full((b, s_len), -1, np.int32)
        tables, ctx, q_starts, _ = self._cont_tables(scheds, b)
        cands: list[list[int]] = []
        for i, sc in enumerate(scheds):
            req = sc.request
            n = sc.num_new_tokens
            row = ([req.all_token_ids[sc.start_pos]]
                   + list(req.spec_draft_tokens[: n - 1]))
            cands.append(row)
            token_ids[i, :n] = row
            p = np.arange(sc.start_pos, sc.start_pos + n)
            if self.use_mrope:
                positions[i, :, :n] = self._mrope_cols(req, p)
            else:
                positions[i, :n] = p
            slots[i, :n] = sc.slot_mapping

        self._note_padding(sum(s.num_new_tokens for s in scheds),
                           b * s_len)
        logits, hidden, self.kv_caches = self._run_jit(
            "verify", (b, s_len, tables.shape[1]),
            lambda: self._verify_fn(
                self.params, jnp.asarray(token_ids), self.kv_caches,
                jnp.asarray(positions), jnp.asarray(slots),
                jnp.asarray(tables), jnp.asarray(ctx),
                jnp.asarray(q_starts),
            )
        )
        # omnilint: disable=OL2 - batch boundary: verify needs argmax host-side
        greedy = np.asarray(jax.device_get(
            jnp.argmax(logits, axis=-1)))  # [B, S]
        # target distributions for every SAMPLED request in ONE batched
        # device call (greedy rows verify off the argmax above)
        sampled_probs = self._batched_verify_probs(scheds, logits)
        # one verify forward per call, however many requests it batched
        self.spec_stats["verify_steps"] += 1
        accepted_idx: list[int] = []
        for i, sc in enumerate(scheds):
            req = sc.request
            n = sc.num_new_tokens
            drafts = cands[i][1:]
            if req.sampling_params.temperature == 0.0:
                # greedy verify: accept the longest prefix matching argmax
                acc = [int(greedy[i, 0])]
                for j, d in enumerate(drafts):
                    if d != acc[-1]:
                        break  # draft j diverges from the true token
                    acc.append(int(greedy[i, j + 1]))
            else:
                acc = self._rejection_accept(req, sampled_probs[i],
                                             drafts)
            acc = self._truncate_at_stop(req, acc)
            out.sampled[req.request_id] = acc
            accepted_idx.append(len(acc) - 1)
            self.spec_stats["proposed"] += len(drafts)
            self.spec_stats["accepted"] += len(acc) - 1
        if self.collect_hidden:
            # ONE batched transfer for every request's accepted rows —
            # a per-request device_get in the loop above was a sync per
            # request per verify step (first omnilint OL2 harvest)
            slices = [hidden[i, : accepted_idx[i] + 1]
                      for i in range(len(scheds))]
            # omnilint: disable=OL2 - single batched sync per verify step
            hosts = jax.device_get(slices)
            for sc, h in zip(scheds, hosts):
                sc.request.additional_information.setdefault(
                    "_hidden_chunks", []).append(np.asarray(h))
        # re-draft from the last accepted position
        last_hidden = hidden[jnp.arange(len(scheds)),
                             jnp.asarray(accepted_idx)]
        self._maybe_draft(scheds, last_hidden, out)

    def _batched_verify_probs(self, scheds, logits) -> dict:
        """{batch_row: [S, vocab] filtered target probs} for every
        sampled (temperature > 0) request — ONE filtered_probs dispatch
        + ONE device_get for the whole verify batch."""
        from vllm_omni_tpu.sample.sampler import filtered_probs

        rows = [(i, sc.request.sampling_params) for i, sc in
                enumerate(scheds)
                if sc.request.sampling_params.temperature != 0.0]
        if not rows:
            return {}
        s_len = logits.shape[1]
        idx = jnp.asarray([i for i, _ in rows])
        sub = logits[idx].reshape(len(rows) * s_len, logits.shape[-1])
        rep = lambda vals: np.repeat(  # noqa: E731
            np.asarray(vals, np.float32), s_len)
        flat = filtered_probs(
            sub,
            jnp.asarray(rep([sp.temperature for _, sp in rows])),
            jnp.asarray(rep([sp.top_k for _, sp in rows]).astype(np.int32)),
            jnp.asarray(rep([sp.top_p for _, sp in rows])),
        )
        probs = np.asarray(jax.device_get(flat)).reshape(
            len(rows), s_len, -1)
        return {i: probs[r] for r, (i, _) in enumerate(rows)}

    def _rejection_accept(self, req, probs, drafts: list[int]
                          ) -> list[int]:
        """Rejection-sampling verify for a sampled request (reference:
        gpu_ar_model_runner.py:466-497).  ``probs`` are the request's
        precomputed [S, vocab] filtered target distributions
        (_batched_verify_probs).  The MTP draft proposes
        deterministically (greedy head), so the accept probability for
        draft d at position j is the TARGET probability p_j(d); on
        rejection the replacement is drawn from p_j with d excluded and
        renormalized — the emitted stream is exactly p-distributed.
        Randomness is a deterministic per-(request, step) stream, like
        the main sampler."""
        sp = req.sampling_params
        seed = sp.seed if sp.seed is not None else self._base_seed
        # plain crc32 (not _salt_of): this method is driven standalone
        # in tests with a bare namespace, and it runs once per sampled
        # request per verify step — not the per-step hot loop the salt
        # cache exists for
        salt = zlib.crc32(req.request_id.encode())
        rng = np.random.default_rng((seed, salt, self._step))
        acc: list[int] = []
        for j, d in enumerate(drafts):
            p_d = float(probs[j, d])
            if rng.uniform() < p_d:
                acc.append(int(d))
                continue
            # rejected: sample the replacement from p_j \ {d}
            p = probs[j].astype(np.float64)
            p[d] = 0.0
            total = p.sum()
            if total <= 0.0:
                acc.append(int(np.argmax(probs[j])))
            else:
                acc.append(int(rng.choice(len(p), p=p / total)))
            return acc
        # every draft accepted: bonus token from the last position
        p = probs[len(drafts)].astype(np.float64)
        p = p / p.sum()
        acc.append(int(rng.choice(len(p), p=p)))
        return acc

    @staticmethod
    def _truncate_at_stop(req, acc: list[int]) -> list[int]:
        """Trim an accepted spec run at the first stop condition (eos /
        stop token / max_tokens), keeping the stopping token.  The
        scheduler re-checks per appended token; trimming here keeps the
        collect_hidden payload aligned with the tokens actually emitted
        (hidden rows past the stop would otherwise ship downstream)."""
        sp = req.sampling_params
        eos = req.eos_token_id
        n_out = len(req.output_token_ids)
        for idx, t in enumerate(acc):
            n = n_out + idx + 1
            if n >= sp.min_tokens:
                eos_hit = (t in eos if isinstance(eos, (list, tuple))
                           else t == eos) if eos is not None else False
                if (not sp.ignore_eos and eos_hit) \
                        or t in sp.stop_token_ids:
                    return acc[: idx + 1]
            if n >= sp.max_tokens:
                return acc[: idx + 1]
        return acc

    def _maybe_draft(self, scheds: list[ScheduledRequest],
                     last_hidden, out: RunnerOutput):
        """Propose the next k tokens for every greedy request that sampled
        this step (spec decode draft phase)."""
        if self.draft_fn is None or self.num_draft_tokens <= 0:
            return
        rows, toks, poss, reqs, ctxs = [], [], [], [], []
        for i, sc in enumerate(scheds):
            req = sc.request
            s = out.sampled.get(req.request_id)
            if s is None:
                continue
            if req.sampling_params.logprobs is not None:
                # multi-token verify accepts have no per-token sampling
                # distribution to report — logprobs requests stay on the
                # one-token-per-step path so entries align 1:1
                continue
            # greedy requests verify by argmax match; sampled requests by
            # rejection sampling (_rejection_accept) — both draft
            new = s if isinstance(s, list) else [s]
            # position where the just-sampled token will be computed: the
            # per-token advance for spec lists, the full chunk width for
            # int samples (a prefill covers num_new_tokens positions, not
            # one); mrope models shift generated positions by delta
            adv = len(new) if isinstance(s, list) else sc.num_new_tokens
            pos = sc.start_pos + adv
            if self.use_mrope:
                pos += req.mrope_delta
            rows.append(i)
            toks.append(new[-1])
            poss.append(pos)
            reqs.append(req)
            if self._draft_takes_contexts:
                # full post-step history (the just-sampled tokens are not
                # yet appended to the request at draft time); built only
                # for drafters that want it — it is an O(n) copy
                ctxs.append(req.all_token_ids + list(new))
        if not rows:
            return
        m = len(rows)
        mb = _bucket(m, self._batch_buckets)
        hh = jnp.zeros((mb,) + last_hidden.shape[1:], last_hidden.dtype)
        hh = hh.at[:m].set(last_hidden[jnp.asarray(rows)])
        tt = np.zeros((mb,), np.int32)
        tt[:m] = toks
        pp = np.zeros((mb,), np.int32)
        pp[:m] = poss
        kwargs = {"contexts": ctxs} if self._draft_takes_contexts else {}
        # omnilint: disable=OL2 - batch boundary: drafts feed next schedule
        drafts = np.asarray(jax.device_get(
            self.draft_fn(hh, jnp.asarray(tt), jnp.asarray(pp), **kwargs)
        ))
        for r, req in enumerate(reqs):
            req.spec_draft_tokens = [int(x) for x in drafts[r]]

    # ------------------------------------------------------------ sampling
    def _sample_and_record(
        self,
        scheds: list[ScheduledRequest],
        logits: jax.Array,       # [B_padded, vocab]
        last_hidden: jax.Array,  # [B_padded, H]
        out: RunnerOutput,
        full_hidden: Optional[jax.Array] = None,
    ):
        # Requests sample only when the forward covered their last token —
        # num_tokens, not num_prompt_tokens, so a preempted request that
        # recomputes prompt+generated KV resumes without double-sampling
        # (samples_final: the predicate shared with the scheduler's
        # async accounting and the unified path).
        sampling = [
            (i, sc) for i, sc in enumerate(scheds) if sc.samples_final
        ]
        if sampling:
            # Sample the full padded batch (one compile per bucket shape);
            # non-sampling rows compute discarded tokens.
            b_padded = logits.shape[0]
            params = [_PAD_SAMPLING] * b_padded
            salts = [0] * b_padded
            for i, sc in sampling:
                params[i] = sc.request.sampling_params
                salts[i] = self._salt_of(sc.request.request_id)
            key = ("single", b_padded) + tuple(
                (i, sc.request.request_id)
                + _params_key(sc.request.sampling_params)
                for i, sc in sampling)
            tensors = self._sampling_tensors(key, params, salts)
            tokens = sample_tokens(
                logits, tensors.temperature, tensors.top_k,
                tensors.top_p, tensors.keys,
            )
            # omnilint: disable=OL2 - batch boundary: scheduler needs tokens
            tokens = np.asarray(jax.device_get(tokens))
            for i, sc in sampling:
                out.sampled[sc.request.request_id] = int(tokens[i])
            want_lp = [(i, sc) for i, sc in sampling
                       if sc.request.sampling_params.logprobs is not None]
            if want_lp:
                from vllm_omni_tpu.sample.sampler import compute_logprobs

                k = min(20, max(int(sc.request.sampling_params.logprobs
                                    or 0) for _, sc in want_lp))
                chosen, top_v, top_i = compute_logprobs(
                    logits, jnp.asarray(tokens), k)
                # one transfer for all three arrays, not three round
                # trips (first omnilint OL2 harvest)
                # omnilint: disable=OL2
                chosen, top_v, top_i = jax.device_get(
                    (chosen, top_v, top_i))
                chosen, top_v, top_i = (np.asarray(chosen),
                                        np.asarray(top_v),
                                        np.asarray(top_i))
                for i, sc in want_lp:
                    kk = min(k, int(sc.request.sampling_params.logprobs
                                    or 0))
                    sc.request.output_logprobs.append({
                        "logprob": float(chosen[i]),
                        "top_ids": top_i[i, :kk].tolist(),
                        "top_logprobs": top_v[i, :kk].tolist(),
                    })
        if self.collect_hidden:
            # per-request hidden payloads for the next stage (reference
            # pooler_output slicing, gpu_ar_model_runner.py:525-568).
            # Device-side slicing + ONE batched transfer: a device_get
            # per request in the loop was a sync per request per step
            # (first omnilint OL2 harvest)
            if full_hidden is not None:
                slices = [full_hidden[i, : sc.num_new_tokens]
                          for i, sc in enumerate(scheds)]
            else:
                slices = [last_hidden[i: i + 1]
                          for i in range(len(scheds))]
            # omnilint: disable=OL2 - single batched sync per step
            hosts = [np.asarray(h) for h in jax.device_get(slices)]
            for sc, h in zip(scheds, hosts):
                req = sc.request
                prev = req.additional_information.get("_hidden_chunks")
                if prev is None:
                    req.additional_information["_hidden_chunks"] = [h]
                else:
                    prev.append(h)

    # -------------------------------------------------------- kv injection
    def inject_kv(self, block_ids: list[int], payload: list) -> int:
        """Scatter per-layer dense [Hkv, seq_len, D] KV into the given
        pages — the receive half of the transfer manager (reference:
        omni_connectors/kv_transfer_manager.py:100+ receive path, which r1
        lacked: extracted KV had nowhere to land) and of the kvcache
        tier-restore path (docs/kv_cache.md).  The whole payload ships
        host->device as ONE pytree transfer — a per-layer asarray walk
        was 2 transfers per layer on the ~0.15 GB/s tunnel.  Returns
        seq_len."""
        if len(payload) != len(self.kv_caches):
            raise ValueError(
                f"KV payload has {len(payload)} layers, cache has "
                f"{len(self.kv_caches)}"
            )
        seq_len = int(payload[0][0].shape[1])
        pos = np.arange(seq_len)
        slots = jnp.asarray(
            np.asarray(block_ids, np.int64)[pos // self.page_size]
            * self.page_size + pos % self.page_size,
            jnp.int32,
        )
        device_payload = jax.device_put(
            [(np.asarray(k), np.asarray(v)) for k, v in payload])
        new_caches = []
        for (k_cache, v_cache), (k, v) in zip(self.kv_caches,
                                              device_payload):
            kt = jnp.moveaxis(k, 0, 1)  # [seq, Hkv, D]
            vt = jnp.moveaxis(v, 0, 1)
            k_cache, v_cache = write_kv_cache(k_cache, v_cache, kt, vt, slots)
            new_caches.append((k_cache, v_cache))
        self.kv_caches = new_caches
        return seq_len

    # -------------------------------------------------------- kv extraction
    def extract_kv(self, block_ids: list[int], seq_len: int) -> list:
        """Gather the pages holding ``seq_len`` tokens into dense per-layer
        [Hkv, seq_len, D] arrays (device half of OmniKVTransferManager)."""
        ids = jnp.asarray(block_ids, jnp.int32)
        slices = []
        for k_cache, v_cache in self.kv_caches:
            k = k_cache[:, ids].reshape(k_cache.shape[0], -1, k_cache.shape[-1])
            v = v_cache[:, ids].reshape(v_cache.shape[0], -1, v_cache.shape[-1])
            slices.append((k[:, :seq_len], v[:, :seq_len]))
        # ONE transfer for the whole payload — 2 syncs per LAYER before
        # the first omnilint OL2 harvest (a 28-layer model paid 56
        # host round trips per extraction)
        # omnilint: disable=OL2
        payload = jax.device_get(slices)
        return [(np.asarray(k), np.asarray(v)) for k, v in payload]

    def extract_kv_batch(self, specs: list[tuple[list[int], int]]
                         ) -> list[list]:
        """``extract_kv`` for SEVERAL page runs in one device round
        trip: [(block_ids, seq_len)] -> one payload each.  The kvcache
        tier drain uses this so a step that evicts/park-extracts many
        payloads still costs ONE host sync (docs/kv_cache.md) — the
        bytes-moved discipline the ~0.15 GB/s tunnel demands."""
        all_slices = []
        for block_ids, seq_len in specs:
            ids = jnp.asarray(block_ids, jnp.int32)
            slices = []
            for k_cache, v_cache in self.kv_caches:
                k = k_cache[:, ids].reshape(
                    k_cache.shape[0], -1, k_cache.shape[-1])
                v = v_cache[:, ids].reshape(
                    v_cache.shape[0], -1, v_cache.shape[-1])
                slices.append((k[:, :seq_len], v[:, :seq_len]))
            all_slices.append(slices)
        # omnilint: disable=OL2 - ONE batched transfer for every
        # payload this step parks (the whole point of the batch API)
        payloads = jax.device_get(all_slices)
        return [[(np.asarray(k), np.asarray(v)) for k, v in sl]
                for sl in payloads]
