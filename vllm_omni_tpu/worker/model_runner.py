"""AR model runner: every step is ONE ragged dispatch.

TPU-native counterpart of the reference's GPUARModelRunner (reference:
worker/gpu_ar_model_runner.py:59).  Where the CUDA runner manages
CUDA-graph capture + padded dispatch (:180-205), this runner packs every
scheduled batch onto a flat token axis and launches ONE token-packed
executable per step (ops/ragged_paged_attention.py) — the split
bucketed-jit executor (fresh prefill / chunked continuation / decode /
spec verify as separately padded launches, deleted in PR 11) survives
only as the dedicated [B]-row executable for pure single-token decode
batches, where one row per sequence beats token-block alignment.

Everything the split path used to drain the async pipeline for now
rides the unified dispatch ON DEVICE:

- speculative verify: a k+1-token ragged row; accept-mask + rejection
  sampling run in the executable (sample/sampler.py
  ``spec_verify_tokens``) — no per-verify-step ``device_get``
- logprobs: chosen + top-k log-softmax computed in the step and carried
  on the in-flight handle to the one lagged retire
- collect_hidden: the packed hidden state rides the handle; per-request
  rows are sliced host-side after the single retire transfer
- embeds/deepstack inputs: scattered onto the packed token axis and fed
  through ``forward_unified``

Responsibilities (mirroring :90-396 / :398-588):
- assemble packed device inputs from ``SchedulerOutput``
- run the jitted unified / decode steps with donated KV caches
- sample next tokens ON DEVICE (sample/sampler.py)
- extract KV pages for cross-stage transfer and ACK them
  (device half of OmniKVTransferManager, reference:
  distributed/omni_connectors/kv_transfer_manager.py:47)
"""

from __future__ import annotations

import functools
import secrets
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.core.scheduler import ScheduledRequest, SchedulerOutput
from vllm_omni_tpu.kvcache.quant import (
    dequantize_payload,
    is_quant_payload,
    payload_seq_len,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.ops.autotune import auto_ragged_blocks
from vllm_omni_tpu.ops.paged_attention import init_kv_cache, write_kv_cache
from vllm_omni_tpu.ops.ragged_paged_attention import (
    DEFAULT_TOKEN_BLOCK,
    align_to_block,
)
from vllm_omni_tpu.sample.sampler import (
    SamplingTensors,
    compute_logprobs,
    sample_tokens,
    spec_verify_tokens,
)
from vllm_omni_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)

#: top-k width of the on-device logprob computation — the OpenAI API
#: caps requests at 20, so one static width serves every request and
#: the host trims per-request (a per-k executable would be a shape per
#: distinct logprobs value)
LOGPROBS_K = 20


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def _bucketed_prefill_shapes(prefill_shapes, batch_buckets,
                             seq_buckets) -> list[tuple[int, int]]:
    """Expand declared (batch, seq_len) traffic shapes into the bucketed
    (b, s) set to warm: every batch bucket up to the declared batch (the
    scheduler admits whatever arrived, so smaller waves bucket lower),
    seq clamped to its bucket.  Kept for the generation runner's padded
    precompile; the AR runner's unified warmup walks token buckets."""
    todo = set()
    for raw_b, raw_s in prefill_shapes:
        b_top = _bucket(min(raw_b, batch_buckets[-1]), batch_buckets)
        s = _bucket(min(raw_s, seq_buckets[-1]), seq_buckets)
        todo.update((b, s) for b in batch_buckets if b <= b_top)
    return sorted(todo)


def _make_buckets(start: int, limit: int) -> tuple[int, ...]:
    """Powers of two from ``start`` up to (and covering) ``limit``."""
    buckets = []
    b = start
    while b < limit:
        buckets.append(b)
        b *= 2
    buckets.append(limit)
    return tuple(buckets)


@dataclass
class RunnerOutput:
    # request_id -> sampled token (only for requests that reached
    # sampling); a spec-decode verify step stores the LIST of accepted
    # tokens instead of a single int
    sampled: dict[str, "int | list[int]"] = field(default_factory=dict)
    # request_id -> extracted KV payload (per-layer (k, v) numpy arrays)
    extracted_kv: dict[str, list] = field(default_factory=dict)
    kv_extracted_req_ids: set[str] = field(default_factory=set)


class UnifiedBatch(NamedTuple):
    """Host-assembled device inputs for one token-packed unified step
    (the layout contract of ops/ragged_paged_attention.py)."""

    token_ids: np.ndarray   # [T_pad]
    positions: np.ndarray   # [T_pad] ([3, T_pad] under mrope)
    slots: np.ndarray       # [T_pad] flat KV slots (-1 padding)
    tables: np.ndarray      # [S_max, max_pages]
    seq_lens: np.ndarray    # [S_max]
    cu_q_lens: np.ndarray   # [S_max + 1] aligned segment starts
    q_lens: np.ndarray      # [S_max]
    last_idx: np.ndarray    # [S_max] packed row of each seq's last token
    t_pad: int              # token bucket the batch padded to
    total: int              # aligned rows actually occupied
    verify_idx: np.ndarray  # [S_max, V] packed rows of candidate logits
    n_cand: np.ndarray      # [S_max] candidates per row (1 = plain)
    drafts: np.ndarray      # [S_max, V-1] draft token ids (0-padded)
    embeds: Optional[np.ndarray] = None       # [T_pad, W]
    embeds_mask: Optional[np.ndarray] = None  # [T_pad]
    deepstack: Optional[np.ndarray] = None    # [n_deep, T_pad, H]


@dataclass
class InflightDecode:
    """Handle for a dispatched-but-not-retired step (decode or unified).

    ``tokens`` stays DEVICE-resident: the next dispatch gathers its
    input tokens straight from it (no host round trip) — for a unified
    handle it is each row's LAST ACCEPTED token, so a spec verify row
    feeds the following step exactly like a plain decode row.  The
    engine retires the handle one step later with the single lagged
    ``device_get`` of ``outs`` (the async pipeline's whole point — host
    readback leaves the critical path)."""

    tokens: jax.Array                 # [rows] i32, on device
    rows: dict[str, int]              # request_id -> row index
    outs: Any = None                  # device output pytree of the step
    kind: str = "decode"              # "decode" | "unified"
    scheds: list = field(default_factory=list)  # row-ordered scheds
    # per-row (async_generation at dispatch) — retire skips side
    # effects (logprobs/hidden appends, spec stats) for rows whose
    # request finished or was preempted-and-readmitted mid-flight
    gens: list = field(default_factory=list)
    asm: Optional[UnifiedBatch] = None
    # indices of rows ASSEMBLED as spec verify rows.  Retire must key
    # on this, not on a (width, is_prefill) predicate: a preempt-resume
    # recompute chunk can start past the prompt with width > 1 and
    # would otherwise be mistaken for a verify row, rewinding its
    # multi-token advance to 1
    spec_rows: set = field(default_factory=set)


def _params_key(sp: SamplingParams) -> tuple:
    """The fields SamplingTensors actually consumes, by VALUE — cache
    keys must not use id(sp): CPython reuses freed addresses, so a
    recycled request_id could silently hit a stale entry built from a
    dead request's params."""
    return (sp.temperature, sp.top_k, sp.top_p, sp.seed)


# Bucket-padding rows must be GREEDY: sample_tokens skips its
# full-vocab-sort sampling branch only when no row has temperature > 0,
# and default-temperature padding would defeat that fast path for every
# batch that doesn't exactly fill its bucket (padding tokens are
# discarded either way).
_PAD_SAMPLING = SamplingParams(temperature=0.0)


class ARModelRunner:
    def __init__(
        self,
        params,
        cfg: tfm.TransformerConfig,
        num_pages: int,
        page_size: int,
        max_model_len: int = 4096,
        dtype=jnp.bfloat16,
        collect_hidden: bool = False,
        seed: Optional[int] = None,
        max_num_seqs: int = 64,
        mesh=None,  # 1-axis "tp" Mesh => tensor-parallel execution
        multi_step_decode: int = 1,  # retired knob: accepted, ignored
        async_scheduling: bool = False,
        unified_batching: bool = True,  # retired knob: always unified
        max_num_batched_tokens: int = 2048,  # sizes the token buckets
        deterministic_decode: bool = False,  # pin decode batches to one bucket
        kv_cache_dtype: str = "auto",  # auto | bf16 | int8 resident layout
    ):
        if kv_cache_dtype not in ("auto", "bf16", "int8"):
            raise ValueError(
                f"unknown kv_cache_dtype {kv_cache_dtype!r} "
                "(expected auto, bf16, or int8)")
        # int8 = the quantized resident layout (per-(head, page) absmax
        # scales, ops/paged_attention.py); auto/bf16 keep the dense
        # layout in the runner ``dtype``.  The flag is part of every
        # dispatch cache key: the quantized executables are a distinct
        # jit variant and warmup must prove it compiled (OL11).
        self._kv_quant = kv_cache_dtype == "int8"
        self.kv_cache_dtype = ("int8" if self._kv_quant
                               else str(jnp.dtype(dtype)))
        self.async_scheduling = bool(async_scheduling)
        self.deterministic_decode = bool(deterministic_decode)
        self.mesh = mesh
        if mesh is not None:
            # Megatron-style TP inside shard_map: heads and MLP columns
            # divide across the tp axis; the per-layer code runs on LOCAL
            # shapes and cfg.tp_axis inserts the psum/all_gather
            # collectives (reference: tensor_parallel_size,
            # stage_configs/qwen3_omni_moe.yaml:27).
            import dataclasses as _dc

            from vllm_omni_tpu.parallel.mesh import AXIS_TP
            from vllm_omni_tpu.parallel.sharding import shard_ar_params

            tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
                AXIS_TP, 1)
            if cfg.num_heads % tp or cfg.num_kv_heads % tp:
                raise ValueError(
                    f"tp={tp} must divide num_heads={cfg.num_heads} and "
                    f"num_kv_heads={cfg.num_kv_heads}")
            cfg = _dc.replace(cfg, tp_axis=AXIS_TP)
            params = shard_ar_params(params, mesh)
        self.params = params
        self.cfg = cfg
        self.params_dtype = jax.tree_util.tree_leaves(params)[0].dtype
        self.page_size = page_size
        self.max_pages_per_seq = -(-max_model_len // page_size)
        # bucket tables sized to the engine limits — the scheduler never
        # emits a batch beyond them, so _bucket cannot overflow
        self._batch_buckets = _make_buckets(1, max(max_num_seqs, 1))
        self._seq_buckets = _make_buckets(16, max(max_model_len, 16))
        # ragged block choice (ops/autotune.py): the per-sequence
        # q block doubles as the packer's segment alignment, so it is
        # fixed here and honored by BOTH the assembler and the kernel;
        # the DMA pipeline depth is the kernel's own knob.  Serving is
        # decode-heavy, which pins the q block at the minimum tile.
        _, dma_slots = auto_ragged_blocks(
            head_dim=cfg.head_dim, page_size=page_size,
            group=max(cfg.num_heads // max(cfg.num_kv_heads, 1), 1),
            kv_itemsize=1 if self._kv_quant else jnp.dtype(dtype).itemsize,
            q_itemsize=jnp.dtype(dtype).itemsize,
            quantized=self._kv_quant,
            num_pages=num_pages if self._kv_quant else 0)
        # the packer's segment alignment is pinned to the kernel's
        # packing contract (decode-heavy serving keeps the autotuner at
        # the same minimum tile; plumb the block through forward_unified
        # before honoring a larger choice here).  dma_slots is recorded
        # for the warmup log — the kernel re-derives the identical value
        # through the same lru-cached helper at dispatch.
        self._token_block = DEFAULT_TOKEN_BLOCK
        self._dma_slots = dma_slots
        # unified ragged batching pads to TOKEN-count buckets: a 1-D
        # bucket line replacing the (batch, seq) grid of the split path.
        # Worst packed size under the AR scheduler = the step token
        # budget plus per-sequence q-block alignment; the one-shot
        # generation scheduler ignores the token budget, so the line
        # extends to max_model_len for CAPACITY — but warmup only walks
        # the budget-reachable prefix (the AR scheduler can never emit
        # the larger buckets, and each compile costs 20-40 s on a
        # remote chip; a generation deployment takes the one-time
        # first-hit compile instead)
        budget_cap = align_to_block(
            max_num_batched_tokens
            + max(max_num_seqs, 1) * (align_to_block(1) - 1),
            self._token_block)
        t_cap = align_to_block(max(budget_cap, max_model_len),
                               self._token_block)
        self._token_buckets = _make_buckets(16, max(t_cap, 16))
        self._warm_token_cap = max(budget_cap, 16)
        self.collect_hidden = collect_hidden
        # --- telemetry (metrics/stats.py pulls these per step) ---
        # device dispatches: one jitted-executable launch each; tests
        # assert a mixed unified step is exactly ONE of these
        self.dispatch_count = 0
        # padding efficiency: real tokens vs. padded device rows
        self.useful_tokens = 0
        self.padded_tokens = 0
        # jit shape-cache telemetry: fresh compiles vs. cache hits and
        # cumulative first-call (compile-dominated) seconds, keyed by
        # this runner's own (kind, shape) signatures
        # "in_flight" is the stall watchdog's compile-stall signal: set
        # around the fresh-compile branch of _run_jit so a mid-traffic
        # XLA compile reads as "compiling", never as a hung engine
        self.compile_stats = {"compiles": 0, "cache_hits": 0,
                              "compile_s": 0.0, "in_flight": 0}
        self._jit_seen: set[tuple] = set()
        self.kv_caches = init_kv_cache(
            cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
            cfg.head_dim, dtype, quantized=self._kv_quant,
        )
        # device-memory ledger components (introspection/memory_ledger):
        # static buffer sizes, summed ONCE from array metadata — .nbytes
        # never syncs the device.  Spec-decode verify buffers are added
        # by set_draft_fn.  The tree walk counts int8 page bodies AND
        # their scale arrays, so kv_pages is exact under either layout.
        self._weights_bytes = sum(
            getattr(x, "nbytes", 0)
            for x in jax.tree_util.tree_leaves(params))
        self._kv_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(self.kv_caches))
        self._spec_bytes = 0
        if mesh is not None:
            from jax.sharding import NamedSharding

            from vllm_omni_tpu.parallel.sharding import ar_kv_cache_spec

            k_spec, v_spec = ar_kv_cache_spec(quantized=self._kv_quant)

            def _put(half, spec):
                if isinstance(half, tuple):
                    return tuple(
                        jax.device_put(a, NamedSharding(mesh, s))
                        for a, s in zip(half, spec))
                return jax.device_put(half, NamedSharding(mesh, spec))

            self.kv_caches = [
                (_put(k, k_spec), _put(v, v_spec))
                for k, v in self.kv_caches
            ]
        self._step = 0
        # engine-level entropy for unseeded requests (fresh per process
        # unless a seed is pinned for reproducibility)
        self._base_seed = seed if seed is not None else secrets.randbits(31)
        # host-side hot-path caches: crc32 sampling salts per request_id
        # and assembled SamplingTensors per batch composition — a
        # pure-decode batch keeps the same (requests, params) for
        # hundreds of steps, and rebuilding both every step was
        # measurable in the step-phase breakdown
        self._salt_cache: dict[str, int] = {}
        self._st_cache: dict[tuple, tuple] = {}
        # multimodal 3D-RoPE: positions carry 3 streams ([3, T] packed)
        self.use_mrope = cfg.mrope_sections is not None

        cfg_ = cfg
        collect_ = collect_hidden

        def _decode_core(params, token_ids, kv_caches, positions,
                         slot_mapping, block_tables, context_lens,
                         temperature, top_k, top_p, keys,
                         want_lp: bool):
            # the [B]-row pure-decode step: forward + ON-DEVICE sampling
            # (the hoist that enables the async pipelined engine step —
            # sampled tokens stay device-resident and feed the NEXT
            # dispatch, so jax.device_get becomes a one-step-lagged
            # retire, engine/llm_engine.py).  The want_lp variant also
            # computes chosen/top-k logprobs in the step, so logprobs
            # decode batches pipeline instead of draining.
            hidden, new_caches = tfm.forward_decode(
                params, cfg_, token_ids, positions, kv_caches,
                slot_mapping, block_tables, context_lens,
            )
            logits = tfm.logits_from_hidden(params, cfg_, hidden)
            toks = sample_tokens(logits, temperature, top_k, top_p, keys)
            out = {"tokens": toks}
            if want_lp:
                chosen, top_v, top_i = compute_logprobs(
                    logits, toks, LOGPROBS_K)
                out.update(lp_chosen=chosen, lp_topv=top_v, lp_topi=top_i)
            if collect_:
                out["hidden"] = hidden
            return out, new_caches

        def _decode_step(*args):
            return _decode_core(*args, want_lp=False)

        def _decode_step_lp(*args):
            return _decode_core(*args, want_lp=True)

        def _unified_core(params, token_ids, kv_caches, positions,
                          slot_mapping, page_tables, seq_lens, cu_q_lens,
                          q_lens, num_seqs, verify_idx, n_cand, drafts,
                          temperature, top_k, top_p, keys,
                          inputs_embeds=None, embeds_mask=None,
                          deepstack=None):
            # ONE executable for every non-pure-decode step: the
            # token-packed ragged forward serves prefill chunks,
            # decode rows, and k+1-token spec verify rows in the same
            # flat [T] axis; candidate logits are gathered at
            # ``verify_idx`` (all rows point at the sampling position
            # for plain sequences), verify/accept + sampling run on
            # device, and logprobs ride the output pytree.  Shapes vary
            # only in the token axis, so the jit cache is a 1-D
            # token-bucket line instead of a (batch, seq) grid.
            hidden, new_caches = tfm.forward_unified(
                params, cfg_, token_ids, positions, kv_caches,
                slot_mapping, page_tables, seq_lens, cu_q_lens, q_lens,
                num_seqs, inputs_embeds=inputs_embeds,
                embeds_mask=embeds_mask, deepstack=deepstack,
            )
            cand_hidden = hidden[verify_idx]          # [S, V, H]
            logits = tfm.logits_from_hidden(params, cfg_, cand_hidden)
            toks, counts = spec_verify_tokens(
                logits, drafts, n_cand, temperature, top_k, top_p, keys)
            ar = jnp.arange(toks.shape[0])
            last = jnp.maximum(counts - 1, 0)
            last_tok = toks[ar, last]
            chosen, top_v, top_i = compute_logprobs(
                logits[:, 0], toks[:, 0], LOGPROBS_K)
            out = {"tokens": toks, "counts": counts,
                   "last_tok": last_tok,
                   "lp_chosen": chosen, "lp_topv": top_v,
                   "lp_topi": top_i}
            if drafts.shape[1] > 0:
                # the accept-position hidden rows feed the next draft
                # proposal — only a drafted runner (V > 1, a STATIC
                # shape) needs them; without a draft head the [S, H]
                # array would be dead weight on every lagged retire
                # transfer
                out["accept_hidden"] = hidden[verify_idx[ar, last]]
            if collect_:
                out["hidden"] = hidden
            return out, new_caches

        if mesh is None:
            jit2 = functools.partial(jax.jit, donate_argnums=(2,))
            self._decode_sample_fn = jit2(_decode_step)
            self._decode_lp_fn = jit2(_decode_step_lp)
            self._unified_fn = jit2(_unified_core)
        else:
            # TP: shard_map over the tp axis — params/KV are the only
            # sharded operands; token inputs replicate, and the psums in
            # _layer_step make activations (logits/hidden) replicated
            # outputs. shard_map (not GSPMD) because the Pallas attention
            # kernels cannot be auto-partitioned by XLA.  Sampling is
            # deterministic in (logits, keys) and logits replicate, so
            # every shard samples the same token.
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            from vllm_omni_tpu.parallel.sharding import (
                ar_kv_cache_spec,
                ar_param_specs_tree,
            )

            pspecs = ar_param_specs_tree(params)
            kv_specs = ([ar_kv_cache_spec(quantized=True)] * cfg.num_layers
                        if self._kv_quant
                        else [ar_kv_cache_spec()] * cfg.num_layers)
            rep = P()

            def wrap(f, n_rest, out_keys):
                out_spec = ({k: rep for k in out_keys}, kv_specs)
                sm = shard_map(
                    f, mesh=mesh,
                    in_specs=(pspecs, rep, kv_specs) + (rep,) * n_rest,
                    out_specs=out_spec,
                    check_vma=False,
                )
                return jax.jit(sm, donate_argnums=(2,))

            dec_keys = ("tokens",) + (("hidden",) if collect_ else ())
            self._decode_sample_fn = wrap(_decode_step, 8, dec_keys)
            self._decode_lp_fn = wrap(
                _decode_step_lp, 8,
                dec_keys + ("lp_chosen", "lp_topv", "lp_topi"))
            # the unified step's embeds/deepstack tail is optional and
            # accept_hidden exists only for drafted runners (drafts
            # width > 0), and shard_map needs a fixed arity + output
            # tree — build one wrap per variant on first use (same
            # shape-cache stance as jit itself)
            uni_wraps: dict[tuple, Any] = {}

            def unified_dispatch(*args, inputs_embeds=None,
                                 embeds_mask=None, deepstack=None):
                has_e = inputs_embeds is not None
                has_d = deepstack is not None
                has_dr = args[12].shape[1] > 0  # drafts operand
                uni_keys = ("tokens", "counts", "last_tok",
                            "lp_chosen", "lp_topv", "lp_topi")
                if has_dr:
                    uni_keys += ("accept_hidden",)
                if collect_:
                    uni_keys += ("hidden",)
                fn = uni_wraps.get((has_e, has_d, has_dr))
                if fn is None:
                    extra = (2 if has_e else 0) + (1 if has_d else 0)

                    def make_core(he: bool, hd: bool):
                        # he/hd are CLOSED-OVER python bools fixed per
                        # wrap arity — never traced values
                        def core(p, t, k, *rest):
                            base, tail = rest[:14], rest[14:]
                            emb = tail[0] if he else None
                            mask = tail[1] if he else None
                            deep = tail[2 if he else 0] if hd else None
                            return _unified_core(
                                p, t, k, *base, inputs_embeds=emb,
                                embeds_mask=mask, deepstack=deep)

                        return core

                    fn = uni_wraps[(has_e, has_d, has_dr)] = wrap(
                        make_core(has_e, has_d), 14 + extra, uni_keys)
                extras = tuple(x for x in (inputs_embeds, embeds_mask,
                                           deepstack) if x is not None)
                return fn(*args, *extras)

            self._unified_fn = unified_dispatch
        # speculative decoding (MTP draft head): draft_fn(last_hidden [M,H],
        # last_token [M], positions [M]) -> [M, k] proposals
        self.draft_fn = None
        self.num_draft_tokens = 0
        self.spec_stats = {"verify_steps": 0, "proposed": 0, "accepted": 0}
        # width of upstream embeds accepted by this model: the embed_proj
        # input dim when present (thinker width for the talker), else the
        # model's own hidden size
        self.embeds_width = (
            params["embed_proj"]["w"].shape[0]
            if "embed_proj" in params else cfg.hidden_size
        )

    @property
    def _spec_v(self) -> int:
        """Candidate rows per sequence in the unified executable: the
        regular sample plus every possible draft.  1 without a draft
        head — the verify machinery degenerates to plain sampling in
        the same executable."""
        return 1 + self.num_draft_tokens

    def set_draft_fn(self, draft_fn, num_draft_tokens: int) -> None:
        """Install the MTP draft head (talker spec decode, reference:
        gpu_ar_model_runner.py:466-497 EAGLE propose).  A draft_fn taking
        a ``contexts`` kwarg also receives each drafted request's full
        post-step token history (oracle/tree drafters).  Install BEFORE
        warmup: the candidate width V = 1 + k is part of the unified
        executable's input shapes."""
        import inspect

        self.draft_fn = draft_fn
        self.num_draft_tokens = num_draft_tokens
        # memory-ledger estimate of the verify-path buffers: the widest
        # batch's (k+1)-row logits at float32 (deterministic — the
        # ledger's CPU fallback must not depend on allocator probes)
        self._spec_bytes = (self._batch_buckets[-1]
                            * (num_draft_tokens + 1)
                            * self.cfg.vocab_size * 4)
        try:
            sig = inspect.signature(draft_fn)
            self._draft_takes_contexts = "contexts" in sig.parameters
        except (TypeError, ValueError):
            self._draft_takes_contexts = False

    # -------------------------------------------------- dispatch telemetry
    def _run_jit(self, kind: str, shape_key: tuple, thunk):
        """Invoke one jitted executable through the telemetry choke
        point: counts the device dispatch (mixed-step tests assert ONE
        per unified step) and classifies it fresh-compile vs cache-hit
        by this runner's own (kind, shape) signature.  A fresh signature
        is timed TO COMPLETION (block_until_ready) so compile_s measures
        the real compile+first-run stall — warmup prepopulates the
        signatures, so steady-state traffic takes the unsynced branch."""
        self.dispatch_count += 1
        key = (kind,) + tuple(shape_key)
        if key in self._jit_seen:
            self.compile_stats["cache_hits"] += 1
            return thunk()
        self._jit_seen.add(key)
        t0 = time.perf_counter()
        self.compile_stats["in_flight"] = 1
        try:
            result = thunk()
            jax.block_until_ready(result)
        finally:
            self.compile_stats["in_flight"] = 0
        self.compile_stats["compiles"] += 1
        self.compile_stats["compile_s"] += time.perf_counter() - t0
        return result

    def memory_components(self) -> dict:
        """Attributable device-memory components for the engine's
        ledger (introspection/memory_ledger.py): static buffer sizes
        from array metadata — never a device sync."""
        comps = {"weights": self._weights_bytes,
                 "kv_pages": self._kv_bytes}
        if self._spec_bytes:
            comps["spec_buffers"] = self._spec_bytes
        return comps

    def _note_padding(self, useful: int, padded: int) -> None:
        self.useful_tokens += int(useful)
        self.padded_tokens += int(padded)

    def _decode_bucket(self, n: int) -> int:
        """Batch bucket for the single-token decode family.  With
        ``deterministic_decode`` every decode step pads to the TOP
        bucket: XLA fuses the [B]-leading decode matmuls differently
        per bucket shape, so the same row decoded in a bucket-4 batch
        and a bucket-8 batch can differ in the last bf16 bit — enough
        to flip a greedy argmax on near-flat logits.  One fixed bucket
        makes a request's stream invariant to co-batch occupancy
        (preemptions and arrivals stop perturbing OTHER requests'
        tokens) at the cost of padded rows when the batch runs small."""
        if self.deterministic_decode:
            return self._batch_buckets[-1]
        return _bucket(n, self._batch_buckets)

    # ---------------------------------------------------------- precompile
    def precompile(self, prefill_shapes=(), decode: bool = True,
                   progress_fn=None) -> int:
        """Build the executables BEFORE serving traffic.

        XLA compiles one executable per input-shape signature, and a
        cache miss mid-traffic stalls every in-flight request for the
        full compile — measured 20-40 s per shape on a remote-attached
        chip.  The unified refactor shrank the warmup surface from the
        (batch, seq) grid × {prefill, chunk, decode, verify, multi} to:

        - the 1-D token-bucket line of the unified executable (one
          shape per bucket; the candidate width V = 1 + draft k is
          fixed per runner — install the draft head first), and
        - the decode batch buckets × {plain, logprobs} of the dedicated
          pure-decode step.

        ``prefill_shapes`` is accepted for API compatibility; every
        packed size a prefill can produce already lands on a token
        bucket.  Embeds/deepstack batches add an argument-tree variant
        that compiles on first hit.  Dummy inputs write to KV slot -1,
        which the paged cache update drops, so the live KV pool is
        untouched.  Returns the number of executables requested."""
        del prefill_shapes  # the token-bucket line covers prefills
        built = 0

        def note(msg):
            if progress_fn is not None:
                progress_fn(msg)

        def warm(kind, key, thunk):
            nonlocal built
            res = self._run_jit(kind, key, thunk)
            built += 1
            return res

        logger.info(
            "ragged blocks: token_block=%d dma_slots=%d (head_dim=%d "
            "page_size=%d kv_cache_dtype=%s) — ops/autotune.py picks "
            "per layout", self._token_block, self._dma_slots,
            self.cfg.head_dim, self.page_size, self.kv_cache_dtype)

        def pos_shape(b):
            return (b, 3) if self.use_mrope else (b,)

        if decode and self.draft_fn is None:
            # deterministic decode runs every step at the top bucket —
            # the smaller executables can never be dispatched.  A
            # runner with a draft head never dispatches the [B]-row
            # decode path at all (_plain_decode_only routes every
            # decode batch unified), so its buckets would be pure
            # warmup waste.
            decode_buckets = (self._batch_buckets[-1:]
                              if self.deterministic_decode
                              else self._batch_buckets)
            for b in decode_buckets:
                tables = jnp.zeros((b, self.max_pages_per_seq), jnp.int32)
                zeros_b = jnp.zeros((b,), jnp.int32)
                t = SamplingTensors.build(
                    [_PAD_SAMPLING] * b, step=0,
                    base_seed=self._base_seed)
                for kind, fn in (("dispatch", self._decode_sample_fn),
                                 ("dispatch_lp", self._decode_lp_fn)):
                    note(f"precompile {kind} b={b}")
                    _, self.kv_caches = warm(
                        kind, (b, self._kv_quant), lambda fn=fn: fn(
                            self.params, zeros_b, self.kv_caches,
                            jnp.zeros(pos_shape(b), jnp.int32),
                            jnp.full((b,), -1, jnp.int32), tables,
                            jnp.ones((b,), jnp.int32),
                            t.temperature, t.top_k, t.top_p, t.keys))
        # ONE executable per token bucket — the 1-D shape-cache line
        # that replaces the (batch, seq) grid
        s_max = self._batch_buckets[-1]
        v = self._spec_v
        t = SamplingTensors.build(
            [_PAD_SAMPLING] * s_max, step=0, base_seed=self._base_seed)
        for t_pad in self._token_buckets:
            if t_pad > self._warm_token_cap:
                # reachable only by the one-shot generation scheduler's
                # whole-prompt packs — first-hit compile there, never
                # under the budget-capped AR scheduler
                continue
            note(f"precompile unified t={t_pad} v={v}")
            pos = (jnp.zeros((3, t_pad), jnp.int32) if self.use_mrope
                   else jnp.zeros((t_pad,), jnp.int32))
            _, self.kv_caches = warm(
                "unified", (t_pad, v, False, False, self._kv_quant),
                lambda: self._unified_fn(
                    self.params, jnp.zeros((t_pad,), jnp.int32),
                    self.kv_caches, pos,
                    jnp.full((t_pad,), -1, jnp.int32),
                    jnp.zeros((s_max, self.max_pages_per_seq),
                              jnp.int32),
                    jnp.zeros((s_max,), jnp.int32),
                    jnp.zeros((s_max + 1,), jnp.int32),
                    jnp.zeros((s_max,), jnp.int32),
                    jnp.zeros((1,), jnp.int32),
                    jnp.zeros((s_max, v), jnp.int32),
                    jnp.ones((s_max,), jnp.int32),
                    jnp.zeros((s_max, v - 1), jnp.int32),
                    t.temperature, t.top_k, t.top_p, t.keys))
        return built

    # ---------------------------------------------------------------- step
    def execute(
        self, sched_out: SchedulerOutput, extract_kv: bool = True
    ) -> RunnerOutput:
        """Synchronous step: dispatch + immediate retire of the SAME
        handles the async pipeline uses — one executable family, one
        numerics contract, so sync and pipelined streams cannot drift."""
        out = RunnerOutput()
        decodes, prefills = sched_out.decodes, sched_out.prefills
        if self._plain_decode_only(sched_out):
            handle = self.dispatch_decode(decodes)
            out.sampled.update(self.retire_step(handle))
        elif decodes or prefills:
            for g_decodes, g_prefills in self._pack_groups(decodes,
                                                           prefills):
                handle = self._dispatch_unified(g_decodes, g_prefills,
                                                None)
                out.sampled.update(self.retire_step(handle))
        for req, block_ids, seq_len in sched_out.kv_transfer_requests:
            # skip the device→host gather when no sink consumes it, but
            # still ACK so the scheduler releases the pinned pages
            if extract_kv:
                out.extracted_kv[req.request_id] = self.extract_kv(
                    block_ids, seq_len
                )
            out.kv_extracted_req_ids.add(req.request_id)
        return out

    # ------------------------------------------------------------ routing
    def _plain_decode_only(self, sched_out: SchedulerOutput) -> bool:
        """Pure single-token decode batches keep the dedicated [B]
        executable — 1 row per sequence beats token-block alignment.
        Anything else (prefill chunks, spec verify rows) packs onto
        the unified token axis.  This is a ROUTING choice between two
        always-available single-dispatch paths, not a fallback: both
        ride the async handle, and logprobs/collect_hidden are served
        by either.  A runner with a draft head routes every decode
        batch unified — the step's ``accept_hidden`` is what the draft
        proposal reads, and a drafted request's rows are verify rows
        (num_new_tokens > 1) on the very next step anyway."""
        if self.draft_fn is not None:
            return False
        return (bool(sched_out.decodes) and not sched_out.prefills
                and all(s.num_new_tokens == 1 for s in sched_out.decodes))

    def fits_unified(self, sched_out: SchedulerOutput) -> bool:
        """One packed group?  The engine pipelines single-group steps;
        a multi-group step (possible only under the one-shot generation
        scheduler, which ignores the token budget) runs synchronously
        as several dispatches."""
        scheds = sched_out.decodes + sched_out.prefills
        if len(scheds) > self._batch_buckets[-1]:
            return False
        total = sum(align_to_block(s.num_new_tokens, self._token_block)
                    for s in scheds)
        return total <= self._token_buckets[-1]

    def _pack_groups(self, decodes, prefills):
        """Split an oversized step into sequential unified dispatches
        (decodes first, arrival order preserved — the same admission
        order the scheduler emitted)."""
        s_cap = self._batch_buckets[-1]
        t_cap = self._token_buckets[-1]
        groups: list[tuple[list, list]] = []
        cur_d: list[ScheduledRequest] = []
        cur_p: list[ScheduledRequest] = []
        tot = 0
        for sched, is_decode in ([(s, True) for s in decodes]
                                 + [(s, False) for s in prefills]):
            need = align_to_block(sched.num_new_tokens, self._token_block)
            if (cur_d or cur_p) and (
                    len(cur_d) + len(cur_p) + 1 > s_cap
                    or tot + need > t_cap):
                groups.append((cur_d, cur_p))
                cur_d, cur_p, tot = [], [], 0
            (cur_d if is_decode else cur_p).append(sched)
            tot += need
        if cur_d or cur_p:
            groups.append((cur_d, cur_p))
        return groups

    # ---------------------------------------------------- unified ragged
    def _assemble_unified(self, scheds: list[ScheduledRequest],
                          spec_rows: set[int]) -> UnifiedBatch:
        """Token-packed device inputs for a mixed batch: each sequence's
        chunk occupies a token-block-aligned segment of the flat token
        axis (the layout contract of ops/ragged_paged_attention.py);
        metadata arrays are fixed [S_max] width so shapes vary only in
        the token bucket.  ``spec_rows``: indices of verify rows, whose
        segment is [last_sampled, draft_1..draft_k] and whose candidate
        logits cover every position."""
        s_max = self._batch_buckets[-1]
        v = self._spec_v
        tb = self._token_block
        n = len(scheds)
        cu = np.zeros((s_max + 1,), np.int32)
        q_lens = np.zeros((s_max,), np.int32)
        seq_lens = np.zeros((s_max,), np.int32)
        tables = np.zeros((s_max, self.max_pages_per_seq), np.int32)
        total = 0
        for i, sc in enumerate(scheds):
            cu[i] = total
            q_lens[i] = sc.num_new_tokens
            seq_lens[i] = sc.start_pos + sc.num_new_tokens
            t = sc.block_table[: self.max_pages_per_seq]
            tables[i, : len(t)] = t
            total += align_to_block(sc.num_new_tokens, tb)
        cu[n:] = total
        t_pad = _bucket(max(total, self._token_buckets[0]),
                        self._token_buckets)
        token_ids = np.zeros((t_pad,), np.int32)
        positions = (np.zeros((3, t_pad), np.int32) if self.use_mrope
                     else np.zeros((t_pad,), np.int32))
        slots = np.full((t_pad,), -1, np.int32)
        last_idx = np.zeros((s_max,), np.int32)
        verify_idx = np.zeros((s_max, v), np.int32)
        n_cand = np.ones((s_max,), np.int32)
        drafts = np.zeros((s_max, max(v - 1, 0)), np.int32)
        use_embeds = any(s.request.prompt_embeds is not None
                         for s in scheds)
        embeds = (np.zeros((t_pad, self.embeds_width), np.float32)
                  if use_embeds else None)
        embeds_mask = np.zeros((t_pad,), bool) if use_embeds else None
        # deepstack multiscale visual features, shipped as sparse
        # (offset, [n_deep, T_item, hidden]) spans on the request and
        # scattered here (zeros at non-visual rows): level i adds to the
        # residual stream after decoder layer i
        n_deep = max((arr.shape[0]
                      for s in scheds
                      for off, arr in (s.request.deepstack_embeds or ())
                      if off < s.start_pos + s.num_new_tokens
                      and off + arr.shape[1] > s.start_pos),
                     default=0)
        deep = (np.zeros((n_deep, t_pad, self.cfg.hidden_size),
                         np.float32) if n_deep else None)
        for i, sc in enumerate(scheds):
            req = sc.request
            m = sc.num_new_tokens
            lo = int(cu[i])
            if i in spec_rows:
                # verify row: [last_sampled, drafts...] — drafts are
                # inputs from the previous step's proposal, verified by
                # this step's candidate logits.  A pipelined verify
                # whose first input token is still in flight leaves a
                # placeholder; _dispatch_unified scatters the real
                # token device-side from the previous handle
                first = (req.all_token_ids[sc.start_pos]
                         if sc.start_pos < req.num_tokens else 0)
                row = ([first]
                       + [int(x) for x in
                          req.spec_draft_tokens[: m - 1]])
                token_ids[lo: lo + m] = row
                drafts[i, : m - 1] = row[1:]
                n_cand[i] = m
                verify_idx[i] = lo + np.minimum(np.arange(v), m - 1)
            else:
                # an async-fed decode row's input token is still in
                # flight (all_token_ids slice comes back empty):
                # _dispatch_unified scatters it device-side from the
                # previous handle
                toks = req.all_token_ids[sc.start_pos: sc.start_pos + m]
                token_ids[lo: lo + len(toks)] = toks
                # plain rows: every candidate slot points at the
                # sampling position (the segment's last token)
                verify_idx[i] = lo + m - 1
            p = np.arange(sc.start_pos, sc.start_pos + m)
            if self.use_mrope:
                positions[:, lo: lo + m] = self._mrope_cols(req, p)
            else:
                positions[lo: lo + m] = p
            slots[lo: lo + m] = sc.slot_mapping
            last_idx[i] = lo + m - 1
            if use_embeds and req.prompt_embeds is not None:
                # embeds cover prompt rows only; a recompute-resumed
                # request also re-prefills its generated tokens, which
                # embed from the table (mask False)
                pe = np.asarray(req.prompt_embeds)
                elo = min(sc.start_pos, pe.shape[0])
                ehi = min(sc.start_pos + m, pe.shape[0])
                if ehi > elo:
                    embeds[lo: lo + ehi - elo] = pe[elo:ehi]
                    embeds_mask[lo: lo + ehi - elo] = True
            if deep is not None:
                # intersect each visual span with this chunk's window
                # [start_pos, start_pos+m); rows outside any span (text,
                # re-prefilled generated tokens) stay zero
                for off, arr in req.deepstack_embeds or ():
                    dlo = max(off, sc.start_pos)
                    dhi = min(off + arr.shape[1], sc.start_pos + m)
                    if dlo < dhi:
                        deep[: arr.shape[0],
                             lo + dlo - sc.start_pos:
                             lo + dhi - sc.start_pos] = (
                            arr[:, dlo - off: dhi - off])
        return UnifiedBatch(token_ids, positions, slots, tables,
                            seq_lens, cu, q_lens, last_idx, t_pad, total,
                            verify_idx, n_cand, drafts, embeds,
                            embeds_mask, deep)

    def _unified_sampling(self, scheds, key_tag: str, t_pad: int):
        """[S_max]-wide SamplingTensors: real params on rows whose chunk
        reaches the sequence's last token (the sequence-final flag —
        verify rows included), greedy padding elsewhere (keeps
        sample_tokens' fast path)."""
        s_max = self._batch_buckets[-1]
        params_list = [_PAD_SAMPLING] * s_max
        salts = [0] * s_max
        final = []
        for i, sc in enumerate(scheds):
            req = sc.request
            if sc.samples_final:
                final.append((i, sc))
                params_list[i] = req.sampling_params
                salts[i] = self._salt_of(req.request_id)
        key = (key_tag, t_pad) + tuple(
            (i, sc.request.request_id)
            + _params_key(sc.request.sampling_params) for i, sc in final)
        return self._sampling_tensors(key, params_list, salts), final

    def dispatch_unified(
        self, sched_out: SchedulerOutput,
        prev: Optional[InflightDecode] = None,
    ) -> InflightDecode:
        """Dispatch a unified step on the async handle: prefill chunks,
        spec verify rows, logprobs, collect_hidden, and embeds inputs
        all ride the two-slot pipeline (engine/llm_engine.py).  Decode
        rows whose input token is still in flight gather it device-side
        from ``prev.tokens`` — each row's last ACCEPTED token, so the
        feed works across decode and unified handles alike."""
        return self._dispatch_unified(sched_out.decodes,
                                      sched_out.prefills, prev)

    def _dispatch_unified(self, decodes, prefills,
                          prev: Optional[InflightDecode]
                          ) -> InflightDecode:
        self._step += 1
        scheds = decodes + prefills
        spec_rows = {i for i, s in enumerate(decodes)
                     if s.num_new_tokens > 1}
        asm = self._assemble_unified(scheds, spec_rows)
        tensors, final = self._unified_sampling(scheds, "unified",
                                                asm.t_pad)
        feed_dst: list[int] = []
        feed_src: list[int] = []
        for i, sc in enumerate(scheds):
            if sc.start_pos >= sc.request.num_tokens and (
                    prev is not None
                    and sc.request.request_id in prev.rows):
                # input token sampled by the previous dispatch, still
                # device-resident
                feed_dst.append(int(asm.cu_q_lens[i]))
                feed_src.append(prev.rows[sc.request.request_id])
        token_ids = jnp.asarray(asm.token_ids)
        if feed_dst:
            token_ids = token_ids.at[jnp.asarray(feed_dst)].set(
                prev.tokens[jnp.asarray(feed_src)])
        # verify tokens are USEFUL work (each is a candidate position
        # the model scores); only block-alignment slack pads
        self._note_padding(int(asm.q_lens.sum()), asm.t_pad)
        if spec_rows:
            self.spec_stats["verify_steps"] += 1
        kwargs = {}
        if asm.embeds is not None:
            kwargs["inputs_embeds"] = jnp.asarray(
                asm.embeds, dtype=self.params_dtype)
            kwargs["embeds_mask"] = jnp.asarray(asm.embeds_mask)
        if asm.deepstack is not None:
            kwargs["deepstack"] = jnp.asarray(
                asm.deepstack, dtype=self.params_dtype)
        outs, self.kv_caches = self._run_jit(
            "unified",
            # the deepstack LEVEL COUNT is part of the operand shape —
            # omitting it would misclassify a real mid-traffic compile
            # as a cache hit and blind the compile-stall introspection;
            # the KV layout flag keeps the int8 executables a distinct
            # signature family (quantized caches are a different pytree)
            (asm.t_pad, self._spec_v, asm.embeds is not None,
             asm.deepstack.shape[0] if asm.deepstack is not None else 0,
             self._kv_quant),
            lambda: self._unified_fn(
                self.params, token_ids, self.kv_caches,
                jnp.asarray(asm.positions), jnp.asarray(asm.slots),
                jnp.asarray(asm.tables), jnp.asarray(asm.seq_lens),
                jnp.asarray(asm.cu_q_lens), jnp.asarray(asm.q_lens),
                jnp.asarray([len(scheds)], jnp.int32),
                jnp.asarray(asm.verify_idx), jnp.asarray(asm.n_cand),
                jnp.asarray(asm.drafts),
                tensors.temperature, tensors.top_k, tensors.top_p,
                tensors.keys, **kwargs))
        return InflightDecode(
            tokens=outs["last_tok"],
            rows={sc.request.request_id: i for i, sc in final},
            outs=outs, kind="unified", scheds=list(scheds),
            gens=[s.request.async_generation for s in scheds],
            asm=asm, spec_rows=spec_rows,
        )

    # ------------------------------------------------ pipelined dispatch
    def dispatch_decode(
        self, scheds: list[ScheduledRequest],
        prev: Optional[InflightDecode] = None,
    ) -> InflightDecode:
        """Dispatch half of the async pipelined step for a pure
        single-token decode batch: forward + on-device sampling (+
        logprobs when any row wants them), returning WITHOUT waiting.
        Input tokens that are not host-visible yet (sampled by ``prev``,
        still in flight) are gathered device-side from ``prev.tokens``.
        The engine retires the handle one step later (``retire_step``)."""
        self._step += 1
        b = self._decode_bucket(len(scheds))
        token_host = np.zeros((b,), np.int32)
        feed_rows: list[int] = []
        feed_src: list[int] = []
        params_list = [_PAD_SAMPLING] * b
        salts = [0] * b
        want_lp = False
        for i, sc in enumerate(scheds):
            req = sc.request
            if sc.start_pos < req.num_tokens:
                token_host[i] = req.all_token_ids[sc.start_pos]
            else:
                # input token still in flight from the previous dispatch
                feed_rows.append(i)
                feed_src.append(prev.rows[req.request_id])
            params_list[i] = req.sampling_params
            salts[i] = self._salt_of(req.request_id)
            if req.sampling_params.logprobs is not None:
                want_lp = True
        positions, slots, tables, ctx = self._assemble_decode_rows(
            scheds, b)
        token_ids = jnp.asarray(token_host)
        if feed_rows:
            token_ids = token_ids.at[jnp.asarray(feed_rows)].set(
                prev.tokens[jnp.asarray(feed_src)])
        kind = "dispatch_lp" if want_lp else "dispatch"
        fn = self._decode_lp_fn if want_lp else self._decode_sample_fn
        key = (kind, b) + tuple(
            (sc.request.request_id,) + _params_key(
                sc.request.sampling_params) for sc in scheds)
        tensors = self._sampling_tensors(key, params_list, salts)
        self._note_padding(len(scheds), b)
        outs, self.kv_caches = self._run_jit(
            kind, (b, self._kv_quant), lambda: fn(
                self.params, token_ids, self.kv_caches,
                jnp.asarray(positions), jnp.asarray(slots),
                jnp.asarray(tables), jnp.asarray(ctx),
                tensors.temperature, tensors.top_k, tensors.top_p,
                tensors.keys,
            )
        )
        return InflightDecode(
            tokens=outs["tokens"],
            rows={sc.request.request_id: i for i, sc in enumerate(scheds)},
            outs=outs, kind="decode", scheds=list(scheds),
            gens=[s.request.async_generation for s in scheds],
        )

    # ------------------------------------------------------------- retire
    def retire_step(self, handle: InflightDecode
                    ) -> dict[str, "int | list[int]"]:
        """Retire half: the ONE host readback of a step, lagged a full
        step behind dispatch in the async pipeline so it overlaps the
        next step's device compute.  Unpacks tokens (plain ints or
        spec-accepted lists), appends logprob entries and hidden
        chunks, and proposes the next drafts — every per-request side
        effect of the step happens here, behind the single transfer."""
        # omnilint: disable=OL2 - the single lagged retire sync of the
        # async pipeline: by the time the engine calls this, the NEXT
        # step is already dispatched, so this get overlaps its compute
        outs = jax.device_get(handle.outs)
        sampled: dict[str, "int | list[int]"] = {}
        if handle.kind == "decode":
            toks = np.asarray(outs["tokens"])
            for rid, i in handle.rows.items():
                sampled[rid] = int(toks[i])
            self._retire_side_effects(handle, outs, sampled)
            return sampled
        toks = np.asarray(outs["tokens"])      # [S, V]
        counts = np.asarray(outs["counts"])    # [S]
        for rid, i in handle.rows.items():
            sc = handle.scheds[i]
            if i in handle.spec_rows:
                # spec verify row: the accepted run, trimmed at the
                # first stop condition so downstream payloads align
                acc = [int(x) for x in toks[i, : max(int(counts[i]), 1)]]
                acc = self._truncate_at_stop(sc.request, acc)
                sampled[rid] = acc
                if not sc.request.is_finished \
                        and handle.gens[i] == sc.request.async_generation:
                    # overshoot / preempt-readmit rows are discarded by
                    # the scheduler — keep them out of the acceptance
                    # telemetry the flight-recorder honesty rides on
                    self.spec_stats["proposed"] += sc.num_new_tokens - 1
                    self.spec_stats["accepted"] += len(acc) - 1
            else:
                sampled[rid] = int(toks[i, 0])
        self._retire_side_effects(handle, outs, sampled)
        return sampled

    # engine compatibility alias (the PR 4 pipeline called the pure
    # decode retire by this name)
    retire_decode = retire_step

    def _retire_side_effects(self, handle: InflightDecode, outs: dict,
                             sampled: dict) -> None:
        """Logprob entries, hidden chunks, and draft proposals for the
        retired step.  Rows whose request finished or was
        preempted-and-readmitted while the step was in flight are
        SKIPPED — the scheduler discards their token (the overshoot
        contract), so appending their side effects would misalign the
        per-token streams."""
        live: list[tuple[int, ScheduledRequest]] = []
        for i, sc in enumerate(handle.scheds):
            req = sc.request
            if req.is_finished or handle.gens[i] != req.async_generation:
                # overshoot (finished at a previous retire) or
                # preempt-and-readmit mid-flight: the scheduler discards
                # the token; discard its side effects with it
                sampled.pop(req.request_id, None)
                continue
            live.append((i, sc))
        # logprobs: trim the static top-K to each request's ask
        if "lp_chosen" in outs:
            chosen = np.asarray(outs["lp_chosen"])
            top_v = np.asarray(outs["lp_topv"])
            top_i = np.asarray(outs["lp_topi"])
            for i, sc in live:
                req = sc.request
                if req.sampling_params.logprobs is None:
                    continue
                if sc.request.request_id not in sampled:
                    continue
                kk = min(LOGPROBS_K,
                         int(req.sampling_params.logprobs or 0))
                req.output_logprobs.append({
                    "logprob": float(chosen[i]),
                    "top_ids": top_i[i, :kk].tolist(),
                    "top_logprobs": top_v[i, :kk].tolist(),
                })
        if self.collect_hidden and "hidden" in outs:
            hidden = np.asarray(outs["hidden"])
            for i, sc in live:
                req = sc.request
                if handle.kind == "decode":
                    rows = hidden[i: i + 1]
                else:
                    lo = int(handle.asm.cu_q_lens[i])
                    s = sampled.get(req.request_id)
                    if isinstance(s, list):
                        # verify row: only accepted positions shipped
                        rows = hidden[lo: lo + len(s)]
                    else:
                        rows = hidden[lo: lo + sc.num_new_tokens]
                prev = req.additional_information.get("_hidden_chunks")
                if prev is None:
                    req.additional_information["_hidden_chunks"] = [
                        np.asarray(rows)]
                else:
                    prev.append(np.asarray(rows))
        self._maybe_draft(handle, outs, sampled, live)

    # ------------------------------------------------- speculative drafts
    def _maybe_draft(self, handle: InflightDecode, outs: dict,
                     sampled: dict, live) -> None:
        """Propose the next k tokens for every request that sampled this
        step (spec decode draft phase).  The hidden rows at each row's
        last ACCEPTED position were gathered ON DEVICE by the step
        (``accept_hidden``); one draft-head dispatch serves the whole
        batch.

        Known pipelined transient: on ENTRY into spec mode (the step
        after a prefill or pipeline bubble), the next schedule may pair
        these drafts with an input token that was still in flight when
        they were proposed — that one verify tests the drafts one
        position late, so its acceptance is ~0 and it degrades to
        plain-decode progress for a step.  Steady-state verifies (the
        hold-then-retire cadence) always pair fresh drafts with a
        host-visible input; correctness is unaffected either way (the
        accept mask only ever admits true target tokens)."""
        if self.draft_fn is None or self.num_draft_tokens <= 0:
            return
        ah = outs.get("accept_hidden")
        if ah is None:
            # pure-decode handle: the row's hidden IS the accept hidden
            ah = outs.get("hidden")
        rows, toks, poss, reqs, ctxs = [], [], [], [], []
        for i, sc in live:
            req = sc.request
            s = sampled.get(req.request_id)
            if s is None:
                continue
            if req.sampling_params.logprobs is not None:
                # multi-token verify accepts have no per-token sampling
                # distribution to report — logprobs requests stay on the
                # one-token-per-step path so entries align 1:1
                continue
            if req.is_finished:
                continue
            new = s if isinstance(s, list) else [s]
            # position where the just-sampled token will be computed:
            # the per-token advance for spec lists, the full chunk
            # width for int samples (a prefill covers num_new_tokens
            # positions, not one); mrope models shift generated
            # positions by delta
            adv = len(new) if isinstance(s, list) else sc.num_new_tokens
            pos = sc.start_pos + adv
            if self.use_mrope:
                pos += req.mrope_delta
            rows.append(i)
            toks.append(new[-1])
            poss.append(pos)
            reqs.append(req)
            if self._draft_takes_contexts:
                # full post-step history (the just-sampled tokens are
                # not yet appended to the request at draft time); built
                # only for drafters that want it — it is an O(n) copy
                ctxs.append(req.all_token_ids + list(new))
        if not rows:
            return
        if ah is None:
            # a decode handle built without hidden output (no
            # collect_hidden): decode batches cannot draft — the
            # engine routes drafted requests through the unified
            # dispatch (their verify rows have num_new_tokens > 1), so
            # this only skips the very first post-prefill proposal of
            # a request that landed in a pure-decode batch; it drafts
            # at its next unified step
            return
        ah = np.asarray(ah)
        m = len(rows)
        mb = _bucket(m, self._batch_buckets)
        hh = np.zeros((mb,) + ah.shape[1:], ah.dtype)
        hh[:m] = ah[np.asarray(rows)]
        tt = np.zeros((mb,), np.int32)
        tt[:m] = toks
        pp = np.zeros((mb,), np.int32)
        pp[:m] = poss
        kwargs = {"contexts": ctxs} if self._draft_takes_contexts else {}
        # omnilint: disable=OL2 - batch boundary: drafts feed next schedule
        drafts = np.asarray(jax.device_get(
            self.draft_fn(jnp.asarray(hh), jnp.asarray(tt),
                          jnp.asarray(pp), **kwargs)
        ))
        for r, req in enumerate(reqs):
            req.spec_draft_tokens = [int(x) for x in drafts[r]]

    # ---------------------------------------------------- mrope positions
    def _mrope_cols(self, req, p: np.ndarray) -> np.ndarray:
        """[3, len(p)] position columns for global token indices ``p``:
        prompt rows come from the request's precomputed table, generated
        rows sit at p + delta on all three streams."""
        mp = req.mrope_positions
        if mp is None:
            return np.broadcast_to(p, (3, len(p)))
        mp = np.asarray(mp)
        out = np.empty((3, len(p)), np.int32)
        in_prompt = p < mp.shape[1]
        out[:, in_prompt] = mp[:, p[in_prompt]]
        out[:, ~in_prompt] = p[~in_prompt][None, :] + req.mrope_delta
        return out

    # -------------------------------------------------------------- decode
    def _assemble_decode_rows(self, scheds: list[ScheduledRequest], b: int):
        """Padded (positions, slots, tables, ctx) rows for a
        single-token decode batch — ONE assembly shared by the
        synchronous decode and the pipelined dispatch, so their input
        semantics (mrope columns, ctx = start_pos + 1, table
        truncation) cannot drift apart."""
        positions = (np.zeros((b, 3), np.int32) if self.use_mrope
                     else np.zeros((b,), np.int32))
        slots = np.full((b,), -1, np.int32)
        tables = np.zeros((b, self.max_pages_per_seq), np.int32)
        ctx = np.zeros((b,), np.int32)
        for i, sc in enumerate(scheds):
            if self.use_mrope:
                positions[i] = self._mrope_cols(
                    sc.request, np.asarray([sc.start_pos]))[:, 0]
            else:
                positions[i] = sc.start_pos
            slots[i] = sc.slot_mapping[0]
            t = sc.block_table[: self.max_pages_per_seq]
            tables[i, : len(t)] = t
            ctx[i] = sc.start_pos + 1
        return positions, slots, tables, ctx

    # ----------------------------------------------- sampling host caches
    def _salt_of(self, request_id: str) -> int:
        """Cached zlib.crc32 sampling salt (recomputing it for every
        request every step was measurable in the step-phase breakdown)."""
        s = self._salt_cache.get(request_id)
        if s is None:
            if len(self._salt_cache) > 8192:
                self._salt_cache.clear()
            s = self._salt_cache[request_id] = zlib.crc32(
                request_id.encode())
        return s

    def _sampling_tensors(self, key: tuple, params_list, salts
                          ) -> SamplingTensors:
        """SamplingTensors for this batch, reused across steps while the
        (request set, params) composition is unchanged.  Only the PRNG
        keys fold the step index, so a cache hit re-keys in one tiny
        dispatch — and an all-greedy batch (keys unused by argmax) skips
        even that."""
        hit = self._st_cache.get(key)
        if hit is not None:
            tensors, any_sampling = hit
            return tensors.rekey(self._step) if any_sampling else tensors
        tensors = SamplingTensors.build(
            params_list, step=self._step, base_seed=self._base_seed,
            salts=salts,
        )
        if len(self._st_cache) > 8:
            self._st_cache.clear()
        self._st_cache[key] = (
            tensors, any(p.temperature > 0.0 for p in params_list))
        return tensors

    # ----------------------------------------------------------- stopping
    @staticmethod
    def _truncate_at_stop(req, acc: list[int]) -> list[int]:
        """Trim an accepted spec run at the first stop condition (eos /
        stop token / max_tokens), keeping the stopping token.  The
        scheduler re-checks per appended token; trimming here keeps the
        collect_hidden payload aligned with the tokens actually emitted
        (hidden rows past the stop would otherwise ship downstream)."""
        sp = req.sampling_params
        eos = req.eos_token_id
        n_out = len(req.output_token_ids)
        for idx, t in enumerate(acc):
            n = n_out + idx + 1
            if n >= sp.min_tokens:
                eos_hit = (t in eos if isinstance(eos, (list, tuple))
                           else t == eos) if eos is not None else False
                if (not sp.ignore_eos and eos_hit) \
                        or t in sp.stop_token_ids:
                    return acc[: idx + 1]
            if n >= sp.max_tokens:
                return acc[: idx + 1]
        return acc

    # -------------------------------------------------------- kv injection
    def inject_kv(self, block_ids: list[int], payload: list) -> int:
        """Scatter a per-layer KV payload into the given pages — the
        receive half of the transfer manager (reference:
        omni_connectors/kv_transfer_manager.py:100+ receive path, which r1
        lacked: extracted KV had nowhere to land) and of the kvcache
        tier-restore path (docs/kv_cache.md).  The whole payload ships
        host->device as ONE pytree transfer — a per-layer asarray walk
        was 2 transfers per layer on the ~0.15 GB/s tunnel.  Returns
        seq_len.

        Payloads arrive dense ([Hkv, seq, D]) or quantized (the
        kvcache/quant.py wire layout).  Quantized into an int8 pool is
        an EXACT page set (data bytes + per-page scales land verbatim —
        the cross-path no-double-quantize contract); quantized into a
        dense pool dequantizes first; dense into an int8 pool quantizes
        through the write op's shared rounding."""
        if len(payload) != len(self.kv_caches):
            raise ValueError(
                f"KV payload has {len(payload)} layers, cache has "
                f"{len(self.kv_caches)}"
            )
        quant_in = is_quant_payload(payload)
        if quant_in and not self._kv_quant:
            payload = dequantize_payload(payload, self.page_size)
            quant_in = False
        seq_len = payload_seq_len(payload)
        if quant_in:
            return self._inject_kv_exact(block_ids, payload, seq_len)
        pos = np.arange(seq_len)
        slots = jnp.asarray(
            np.asarray(block_ids, np.int64)[pos // self.page_size]
            * self.page_size + pos % self.page_size,
            jnp.int32,
        )
        device_payload = jax.device_put(
            [(np.asarray(k), np.asarray(v)) for k, v in payload])
        new_caches = []
        for (k_cache, v_cache), (k, v) in zip(self.kv_caches,
                                              device_payload):
            kt = jnp.moveaxis(k, 0, 1)  # [seq, Hkv, D]
            vt = jnp.moveaxis(v, 0, 1)
            k_cache, v_cache = write_kv_cache(k_cache, v_cache, kt, vt, slots)
            new_caches.append((k_cache, v_cache))
        self.kv_caches = new_caches
        return seq_len

    def _inject_kv_exact(self, block_ids: list[int], payload: list,
                         seq_len: int) -> int:
        """int8 wire payload -> int8 pool: page-granular set of data
        bytes and scales, bit-exact (no re-quantization).  The run's
        trailing partial page pads with zeros — those rows sit past
        every context length, and the settled page scale stays valid
        for later decode appends into the same page."""
        ps = self.page_size
        n_pages = min(len(block_ids), -(-seq_len // ps))
        ids = jnp.asarray(block_ids[:n_pages], jnp.int32)
        pad = n_pages * ps - seq_len

        def to_pages(q):
            a = np.asarray(q)[:, : n_pages * ps]
            if pad:
                a = np.pad(a, ((0, 0), (0, pad), (0, 0)))
            return a.reshape(a.shape[0], n_pages, ps, a.shape[-1])

        host = [((to_pages(kq), np.asarray(ks)[:, :n_pages]),
                 (to_pages(vq), np.asarray(vs)[:, :n_pages]))
                for (kq, ks), (vq, vs) in payload]
        dev = jax.device_put(host)
        new_caches = []
        for (k_half, v_half), ((kp, ks), (vp, vs)) in zip(
                self.kv_caches, dev):
            kd, ksc = k_half
            vd, vsc = v_half
            new_caches.append((
                (kd.at[:, ids].set(kp), ksc.at[:, ids].set(ks)),
                (vd.at[:, ids].set(vp), vsc.at[:, ids].set(vs)),
            ))
        self.kv_caches = new_caches
        return seq_len

    # -------------------------------------------------------- kv extraction
    def _extract_layer_slices(self, ids, seq_len: int) -> list:
        """Per-layer device slices for one page run.  Dense pools emit
        [Hkv, seq_len, D] halves; int8 pools emit the quantized wire
        layout ((data[:, :seq_len], page scales)) — the bytes leave the
        device as stored, so a later inject restores them bit-exact."""
        slices = []
        for k_cache, v_cache in self.kv_caches:
            if isinstance(k_cache, tuple):
                layer = []
                for data, scale in (k_cache, v_cache):
                    q = data[:, ids].reshape(
                        data.shape[0], -1, data.shape[-1])
                    layer.append((q[:, :seq_len], scale[:, ids]))
                slices.append(tuple(layer))
            else:
                k = k_cache[:, ids].reshape(
                    k_cache.shape[0], -1, k_cache.shape[-1])
                v = v_cache[:, ids].reshape(
                    v_cache.shape[0], -1, v_cache.shape[-1])
                slices.append((k[:, :seq_len], v[:, :seq_len]))
        return slices

    @staticmethod
    def _host_payload(slices: list) -> list:
        return [tuple(
            tuple(np.asarray(a) for a in half)
            if isinstance(half, tuple) else np.asarray(half)
            for half in layer) for layer in slices]

    def extract_kv(self, block_ids: list[int], seq_len: int) -> list:
        """Gather the pages holding ``seq_len`` tokens into a per-layer
        payload (device half of OmniKVTransferManager): dense
        [Hkv, seq_len, D] halves, or the kvcache/quant.py wire layout
        when the pool is int8."""
        ids = jnp.asarray(block_ids, jnp.int32)
        slices = self._extract_layer_slices(ids, seq_len)
        # ONE transfer for the whole payload — 2 syncs per LAYER before
        # the first omnilint OL2 harvest (a 28-layer model paid 56
        # host round trips per extraction)
        # omnilint: disable=OL2
        payload = jax.device_get(slices)
        return self._host_payload(payload)

    def extract_kv_batch(self, specs: list[tuple[list[int], int]]
                         ) -> list[list]:
        """``extract_kv`` for SEVERAL page runs in one device round
        trip: [(block_ids, seq_len)] -> one payload each.  The kvcache
        tier drain uses this so a step that evicts/park-extracts many
        payloads still costs ONE host sync (docs/kv_cache.md) — the
        bytes-moved discipline the ~0.15 GB/s tunnel demands."""
        all_slices = []
        for block_ids, seq_len in specs:
            ids = jnp.asarray(block_ids, jnp.int32)
            all_slices.append(self._extract_layer_slices(ids, seq_len))
        # omnilint: disable=OL2 - ONE batched transfer for every
        # payload this step parks (the whole point of the batch API)
        payloads = jax.device_get(all_slices)
        return [self._host_payload(sl) for sl in payloads]
