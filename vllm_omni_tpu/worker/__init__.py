from vllm_omni_tpu.worker.model_runner import ARModelRunner, RunnerOutput

__all__ = ["ARModelRunner", "RunnerOutput"]
