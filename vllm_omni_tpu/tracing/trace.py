"""Per-request distributed tracing across pipeline stages.

The gap VERDICT.md:116 names: the repo had jax.profiler fan-out and
aggregate stats jsonl but no request-trace propagation — once stages run
in separate processes nobody can answer "where did request X spend its
900 ms".  This module is the span layer underneath:

- a ``trace context`` is a plain dict ``{"trace_id", "request_id"}``
  created at ``Omni``/``AsyncOmni`` arrival.  Plain dicts (not a class)
  so the context survives every transport the pipeline already has —
  ``StageRequest.trace`` rides the stage_proc command sockets and the
  connector edges through OmniSerializer unchanged.
- each process owns one global ``TraceRecorder``; engines and stages
  record finished spans into it (recording is a no-op for requests
  without a context, so an untraced server pays one dict lookup).
- cross-process stage workers drain their recorder into the ``outputs``
  message (entrypoints/stage_proc.py); the orchestrator merges the
  shipped spans, so one request's trace id carries spans from every
  stage regardless of process placement.
- ``TraceWriter`` streams spans as JSONL next to the ``*.stats.jsonl``
  files and exports the whole trace as Chrome trace-event JSON
  (Perfetto / chrome://tracing loadable).

Span timestamps are wall-clock (``time.time``) so spans recorded in
different processes land on one timeline; durations come from the
caller's monotonic clock.
"""

from __future__ import annotations

import json
import threading
import uuid
from collections import deque
from typing import Any, Optional

from vllm_omni_tpu.analysis.runtime import traced


def new_trace_context(request_id: str) -> dict:
    """Fresh per-request trace context (created once, at arrival)."""
    return {"trace_id": uuid.uuid4().hex, "request_id": request_id}


class TraceRecorder:
    """Process-global span sink.  Bounded: a recorder nobody drains (a
    stage worker between output batches, a server without tracing
    enabled) must not grow memory forever.

    Eviction is COUNTED, never silent: ``spans_dropped`` is the
    lifetime number of spans the ring pushed out before anyone drained
    them, surfaced as ``trace_spans_dropped_total`` on /metrics — a
    growing counter means the drain cadence (or the capacity) is wrong
    and the traces being analyzed have holes."""

    def __init__(self, capacity: int = 65536):
        self._capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        self._lock = traced(threading.Lock(), "TraceRecorder._lock")
        self._dropped = 0

    def record(
        self,
        ctx: Optional[dict],
        name: str,
        start_ts: float,
        dur_s: float,
        *,
        stage_id: int = -1,
        cat: str = "engine",
        args: Optional[dict] = None,
    ) -> None:
        """Record one finished span.  ``ctx`` None means the request is
        untraced — the call is a no-op (this is the enablement switch:
        no trace context, no spans)."""
        if not ctx:
            return
        span = {
            "trace_id": ctx.get("trace_id", ""),
            "request_id": ctx.get("request_id", ""),
            "name": name,
            "cat": cat,
            "stage_id": stage_id,
            "ts_us": start_ts * 1e6,
            "dur_us": max(dur_s, 0.0) * 1e6,
        }
        if args:
            span["args"] = args
        with self._lock:
            if len(self._spans) == self._capacity:
                self._dropped += 1
            self._spans.append(span)

    def extend(self, spans: list[dict]) -> None:
        """Merge spans recorded by another process (shipped over the
        stage worker's outputs message)."""
        with self._lock:
            overflow = (len(self._spans) + len(spans)) - self._capacity
            if overflow > 0:
                # a batch larger than the whole ring also drops its own
                # head, not just the resident spans it pushes out
                self._dropped += overflow
            self._spans.extend(spans)

    def drain(self) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
            self._spans.clear()
        return spans

    @property
    def spans_dropped(self) -> int:
        """Lifetime spans evicted undrained (trace_spans_dropped_total)."""
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_global_recorder = TraceRecorder()


def get_recorder() -> TraceRecorder:
    """The process-global recorder (one per process; stage workers own
    their own and ship spans back over the command channel)."""
    return _global_recorder


# ------------------------------------------------------------- exporters
def to_chrome_trace(spans: list[dict]) -> dict:
    """Spans -> Chrome trace-event JSON (Perfetto loadable).

    pid = stage_id + 1 (pid 0 is the orchestrator, whose spans carry
    stage_id -1); tid = one lane per (pid, request_id) so concurrent
    requests don't overlap in the track view.  Metadata events name the
    processes/threads."""
    events: list[dict] = []
    tids: dict[tuple, int] = {}
    pids: set[int] = set()
    for s in spans:
        pid = int(s.get("stage_id", -1)) + 1
        pids.add(pid)
        key = (pid, s.get("request_id", ""))
        tid = tids.setdefault(key, len(tids) + 1)
        args = {"trace_id": s.get("trace_id", ""),
                "request_id": s.get("request_id", "")}
        args.update(s.get("args") or {})
        events.append({
            "name": s.get("name", ""),
            "cat": s.get("cat", ""),
            "ph": "X",
            "ts": s.get("ts_us", 0.0),
            "dur": s.get("dur_us", 0.0),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    for pid in sorted(pids):
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": ("orchestrator" if pid == 0
                              else f"stage_{pid - 1}")},
        })
    for (pid, rid), tid in tids.items():
        events.append({
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": rid or "-"},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class TraceWriter:
    """Sink for drained spans: streams ``{prefix}.trace.jsonl`` (one
    span per line, append-only — same convention as the stats jsonl
    files) and rewrites ``{prefix}.trace.json`` as a complete Chrome
    trace on every ``export_chrome``.  The in-memory accumulation for the
    Chrome export is bounded so a long-running server doesn't hold a
    lifetime of spans (the JSONL keeps the full history)."""

    def __init__(self, path_prefix: str, chrome_capacity: int = 200_000):
        self._prefix = path_prefix
        self._spans: deque = deque(maxlen=chrome_capacity)
        self._lock = traced(threading.Lock(), "TraceWriter._lock")

    @property
    def jsonl_path(self) -> str:
        return f"{self._prefix}.trace.jsonl"

    @property
    def chrome_path(self) -> str:
        return f"{self._prefix}.trace.json"

    def write(self, spans: list[dict]) -> None:
        if not spans:
            return
        with self._lock:
            self._spans.extend(spans)
            # omnilint: disable=OL9 - the jsonl append must stay
            # ordered with the chrome buffer extend above; writers are
            # rare (drain cadence) and the file is local append-only
            with open(self.jsonl_path, "a") as f:
                for s in spans:
                    f.write(json.dumps(s) + "\n")

    def export_chrome(self) -> str:
        with self._lock:
            doc = to_chrome_trace(list(self._spans))
        with open(self.chrome_path, "w") as f:
            json.dump(doc, f)
        return self.chrome_path
