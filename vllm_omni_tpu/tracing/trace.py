"""Per-request distributed tracing across pipeline stages.

The gap VERDICT.md:116 names: the repo had jax.profiler fan-out and
aggregate stats jsonl but no request-trace propagation — once stages run
in separate processes nobody can answer "where did request X spend its
900 ms".  This module is the span layer underneath:

- a ``trace context`` is a plain dict ``{"trace_id", "request_id"}``
  created at ``Omni``/``AsyncOmni`` arrival.  Plain dicts (not a class)
  so the context survives every transport the pipeline already has —
  ``StageRequest.trace`` rides the stage_proc command sockets and the
  connector edges through OmniSerializer unchanged.
- each process owns one global ``TraceRecorder``; engines and stages
  record finished spans into it (recording is a no-op for requests
  without a context, so an untraced server pays one dict lookup).
- cross-process stage workers drain their recorder into the ``outputs``
  message (entrypoints/stage_proc.py); the orchestrator merges the
  shipped spans, so one request's trace id carries spans from every
  stage regardless of process placement.
- ``TraceWriter`` streams spans as JSONL next to the ``*.stats.jsonl``
  files and exports the whole trace as Chrome trace-event JSON
  (Perfetto / chrome://tracing loadable).

Span timestamps are wall-clock (``time.time``) so spans recorded in
different processes land on one timeline; durations come from the
caller's monotonic clock.
"""

from __future__ import annotations

import json
import threading
import uuid
from collections import deque
from typing import Any, Optional

from vllm_omni_tpu.analysis.runtime import traced


def new_trace_context(request_id: str) -> dict:
    """Fresh per-request trace context (created once, at arrival)."""
    return {"trace_id": uuid.uuid4().hex, "request_id": request_id}


class TraceRecorder:
    """Process-global span sink.  Bounded: a recorder nobody drains (a
    stage worker between output batches, a server without tracing
    enabled) must not grow memory forever.

    Eviction is COUNTED, never silent: ``spans_dropped`` is the
    lifetime number of spans the ring pushed out before anyone drained
    them, surfaced as ``trace_spans_dropped_total`` on /metrics — a
    growing counter means the drain cadence (or the capacity) is wrong
    and the traces being analyzed have holes."""

    def __init__(self, capacity: int = 65536):
        self._capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        self._lock = traced(threading.Lock(), "TraceRecorder._lock")
        self._dropped = 0

    def record(
        self,
        ctx: Optional[dict],
        name: str,
        start_ts: float,
        dur_s: float,
        *,
        stage_id: int = -1,
        cat: str = "engine",
        args: Optional[dict] = None,
        replica_id: Optional[str] = None,
        role: Optional[str] = None,
    ) -> None:
        """Record one finished span.  ``ctx`` None means the request is
        untraced — the call is a no-op (this is the enablement switch:
        no trace context, no spans).

        ``replica_id``/``role``: fleet identity (docs/observability.md
        journey traces).  Spans carrying a replica id render on their
        own Perfetto process track — N same-process engine replicas
        stepped by one router must not collide on one pid row the way
        same-process pipeline stages deliberately do."""
        if not ctx:
            return
        span = {
            "trace_id": ctx.get("trace_id", ""),
            "request_id": ctx.get("request_id", ""),
            "name": name,
            "cat": cat,
            "stage_id": stage_id,
            "ts_us": start_ts * 1e6,
            "dur_us": max(dur_s, 0.0) * 1e6,
        }
        if replica_id is not None:
            span["replica_id"] = replica_id
        if role is not None:
            span["role"] = role
        if args:
            span["args"] = args
        with self._lock:
            if len(self._spans) == self._capacity:
                self._dropped += 1
            self._spans.append(span)

    def extend(self, spans: list[dict]) -> None:
        """Merge spans recorded by another process (shipped over the
        stage worker's outputs message)."""
        with self._lock:
            overflow = (len(self._spans) + len(spans)) - self._capacity
            if overflow > 0:
                # a batch larger than the whole ring also drops its own
                # head, not just the resident spans it pushes out
                self._dropped += overflow
            self._spans.extend(spans)

    def drain(self) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
            self._spans.clear()
        return spans

    def tail(self, n: int) -> list[dict]:
        """The ``n`` most recent undrained spans, NON-destructively —
        the journey slice an alert evidence bundle captures
        (metrics/alerts.py) must never steal spans from the writer's
        next drain."""
        with self._lock:
            spans = list(self._spans)
        return spans[-n:] if n > 0 else []

    @property
    def spans_dropped(self) -> int:
        """Lifetime spans evicted undrained (trace_spans_dropped_total)."""
        with self._lock:
            return self._dropped

    @property
    def capacity(self) -> int:
        return self._capacity

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_global_recorder = TraceRecorder()


def get_recorder() -> TraceRecorder:
    """The process-global recorder (one per process; stage workers own
    their own and ship spans back over the command channel)."""
    return _global_recorder


# ------------------------------------------------------------- exporters
#: pid base for per-replica process tracks — far above any plausible
#: stage_id+1 pid so the two namespaces can never collide
_REPLICA_PID_BASE = 1000


def iter_chrome_events(spans):
    """Spans -> Chrome trace events, one at a time (the streaming core
    shared by ``to_chrome_trace`` and ``TraceWriter.export_chrome`` —
    the writer must never materialize a second full copy of the span
    buffer just to serialize it).

    Track layout (docs/observability.md journey-trace tour):

    - spans WITHOUT a replica id: pid = stage_id + 1 (pid 0 is the
      orchestrator, whose spans carry stage_id -1) — the classic
      pipeline-stage layout;
    - spans WITH a replica id (fleet spans: engine replicas behind a
      DisaggRouter, the router itself, control-plane operations): one
      pid per distinct replica id, allocated in first-seen order from
      ``_REPLICA_PID_BASE`` — N same-process replicas get N tracks
      instead of colliding on one stage row;
    - tid = one lane per (pid, request_id) so concurrent requests don't
      overlap in the track view.  Metadata events name every process
      and thread, emitted after the X events."""
    tids: dict[tuple, int] = {}
    stage_pids: set[int] = set()
    replica_pids: dict[str, int] = {}
    replica_roles: dict[str, str] = {}
    for s in spans:
        rid = s.get("replica_id")
        if rid is not None:
            pid = replica_pids.setdefault(
                rid, _REPLICA_PID_BASE + len(replica_pids))
            if s.get("role"):
                replica_roles[rid] = s["role"]  # last role wins
        else:
            pid = int(s.get("stage_id", -1)) + 1
            stage_pids.add(pid)
        key = (pid, s.get("request_id", ""))
        tid = tids.setdefault(key, len(tids) + 1)
        args = {"trace_id": s.get("trace_id", ""),
                "request_id": s.get("request_id", "")}
        if rid is not None:
            args["replica_id"] = rid
            if s.get("role"):
                args["role"] = s["role"]
        args.update(s.get("args") or {})
        yield {
            "name": s.get("name", ""),
            "cat": s.get("cat", ""),
            "ph": "X",
            "ts": s.get("ts_us", 0.0),
            "dur": s.get("dur_us", 0.0),
            "pid": pid,
            "tid": tid,
            "args": args,
        }
    for pid in sorted(stage_pids):
        yield {
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": ("orchestrator" if pid == 0
                              else f"stage_{pid - 1}")},
        }
    for rid, pid in replica_pids.items():
        role = replica_roles.get(rid)
        yield {
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": (f"replica:{rid} ({role})" if role
                              else f"replica:{rid}")},
        }
    for (pid, req_id), tid in tids.items():
        yield {
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": req_id or "-"},
        }


def to_chrome_trace(spans: list[dict]) -> dict:
    """Spans -> a complete Chrome trace-event document (Perfetto
    loadable).  Convenience face of ``iter_chrome_events`` for bounded
    span lists; long-running writers stream instead
    (``TraceWriter.export_chrome``)."""
    return {"traceEvents": list(iter_chrome_events(spans)),
            "displayTimeUnit": "ms"}


class TraceWriter:
    """Sink for drained spans: streams ``{prefix}.trace.jsonl`` (one
    span per line, append-only — same convention as the stats jsonl
    files) and rewrites ``{prefix}.trace.json`` as a complete Chrome
    trace on every ``export_chrome``.  The in-memory accumulation for the
    Chrome export is bounded so a long-running server doesn't hold a
    lifetime of spans (the JSONL keeps the full history), spans the cap
    pushed out are COUNTED (``chrome_spans_dropped``), and the export
    declares its own truncation in ``otherData`` instead of silently
    presenting a tail as the whole story.  The export itself streams
    event-by-event — serializing 200k spans must not build a second
    full copy of the buffer in memory."""

    def __init__(self, path_prefix: str, chrome_capacity: int = 200_000):
        self._prefix = path_prefix
        self._spans: deque = deque(maxlen=chrome_capacity)
        self._lock = traced(threading.Lock(), "TraceWriter._lock")
        # spans the bounded chrome buffer evicted before any export
        # (lifetime) — the truncation note in the export metadata; the
        # JSONL still has them
        self._chrome_dropped = 0
        self._last_export_ts: Optional[float] = None
        # serializes whole exports (heartbeat vs shutdown flush) so two
        # concurrent export_chrome calls never interleave on the same
        # file; distinct from _lock so recording threads don't convoy
        # behind export IO
        self._export_lock = traced(threading.Lock(),
                                   "TraceWriter._export_lock")

    @property
    def jsonl_path(self) -> str:
        return f"{self._prefix}.trace.jsonl"

    @property
    def chrome_path(self) -> str:
        return f"{self._prefix}.trace.json"

    def write(self, spans: list[dict]) -> None:
        if not spans:
            return
        with self._lock:
            cap = self._spans.maxlen or 0
            overflow = (len(self._spans) + len(spans)) - cap
            if cap and overflow > 0:
                self._chrome_dropped += overflow
            self._spans.extend(spans)
            # omnilint: disable=OL9 - the jsonl append must stay
            # ordered with the chrome buffer extend above; writers are
            # rare (drain cadence) and the file is local append-only
            with open(self.jsonl_path, "a") as f:
                for s in spans:
                    f.write(json.dumps(s) + "\n")

    def export_chrome(self) -> str:
        import os
        import time as _time

        with self._export_lock:
            with self._lock:
                spans = list(self._spans)
                dropped = self._chrome_dropped
            # serialize OUTSIDE the span lock (recording threads must
            # not convoy behind file IO), streaming one event at a
            # time into a temp file swapped in atomically — a reader
            # (or a crashed export) never sees a half-written document
            tmp = f"{self.chrome_path}.tmp"
            # omnilint: disable=OL9 - file IO under the EXPORT lock is
            # the point: it serializes rare whole-document exports
            # against each other; span recording rides _lock only and
            # never waits here
            with open(tmp, "w") as f:
                f.write('{"traceEvents":[')
                first = True
                for ev in iter_chrome_events(spans):
                    if not first:
                        f.write(",")
                    first = False
                    f.write(json.dumps(ev))
                meta = {
                    "spans": len(spans),
                    "spans_dropped": dropped,
                    "truncated": dropped > 0,
                    "note": ("chrome buffer capped; the .trace.jsonl "
                             "keeps the full span history"
                             if dropped > 0 else "complete"),
                }
                f.write('],"displayTimeUnit":"ms","otherData":'
                        + json.dumps(meta) + "}")
            os.replace(tmp, self.chrome_path)
            with self._lock:
                self._last_export_ts = _time.time()
        return self.chrome_path

    @property
    def chrome_spans_dropped(self) -> int:
        with self._lock:
            return self._chrome_dropped

    def debug_snapshot(self) -> dict:
        """/debug/trace: writer paths + chrome-buffer bookkeeping."""
        with self._lock:
            return {
                "jsonl_path": self.jsonl_path,
                "chrome_path": self.chrome_path,
                "buffered_spans": len(self._spans),
                "chrome_capacity": self._spans.maxlen,
                "chrome_spans_dropped": self._chrome_dropped,
                "last_export_ts": self._last_export_ts,
            }
