"""omnijourney: fleet-wide request-journey tracing helpers.

The PR 1 span layer (tracing/trace.py) stops at the single-engine
boundary: engines record prefill/decode/dispatch/retire spans, stages
ship them across process boundaries, and one request id yields one
timeline — as long as exactly one engine served it.  PR 9/12 made the
FLEET the unit of serving (router dispatch, KV handoff, failover,
re-roling, WFQ), and the exact minute the control plane exists to
explain — a drain→flip→re-admit under failover — was invisible.

This module is the producing side of the journey layer:

- **span vocabulary** for the fleet edges: router dispatch/failover/
  shed, the prefill→decode KV handoff (ship/recv/adopt), degradation
  transitions, and control-plane operations.  Every journey span
  carries ``(trace_id, replica_id, role)`` so the exporter
  (``iter_chrome_events``) lays each replica out on its own Perfetto
  process track — the router and N same-process replicas must not
  collide on one pid row.
- **external trace joining**: ``inbound_trace_id`` parses the W3C
  ``traceparent`` header (or the simpler ``x-omni-trace-id``) so a
  request arriving from an already-traced caller continues the
  caller's trace id instead of minting a fresh one.  Both are CLIENT
  input: parsed defensively, length/charset bounded, never raised on.

Recording remains enablement-by-context: no trace context on the
request, no spans (one dict lookup per would-be span).  Control-plane
operations are the one exception — they are fleet-scoped, not
request-scoped, and rare (a handful per minute at most), so they ride
a long-lived synthetic context and the bounded recorder ring absorbs
them on untraced deployments.

No jax imports, no device syncs — this module is on the router/engine
hot path (omnilint HOT_PATHS) and must stay host-only.
"""

from __future__ import annotations

import re
import time
from typing import Optional

from vllm_omni_tpu.tracing.trace import get_recorder

# ---------------------------------------------------------------- names
#: router-edge spans (cat="router")
SPAN_DISPATCH = "router_dispatch"
SPAN_FAILOVER = "failover"
SPAN_SHED = "shed"
SPAN_DEGRADED = "degraded_dispatch"
#: expected-vs-actual prefix hit marker (cat="router"): emitted at
#: first prefill output with the dispatch-time expectation joined to
#: the engine's actual match — the per-request cache-economics receipt
SPAN_PREFIX_HIT = "prefix_hit"
#: KV handoff spans (cat="handoff")
SPAN_HANDOFF_SHIP = "kv_handoff_ship"
SPAN_HANDOFF_RECV = "kv_handoff_recv"
SPAN_ADOPT = "decode_adopt"
#: cluster-KV-fabric pull leg (cat="handoff"): the router fetched a
#: shared-prefix payload from the connector store instead of letting
#: the chosen replica re-prefill it — args carry key/tokens/bytes/src
SPAN_PREFIX_PULL = "prefix_pull"
#: control-plane operation spans (cat="controlplane"): "cp:" + kind —
#: kinds are the controller's action/operation names (drain, undrain,
#: rerole, scale_up, remove_replica, scale_down)
CP_PREFIX = "cp:"

#: the router's own pseudo-replica identity: router-scoped spans
#: (dispatch decisions, sheds, handoff transport) get one track of
#: their own instead of landing on whichever replica was involved
ROUTER_TRACK = "router"


def record_journey(ctx: Optional[dict], name: str, start_wall: float,
                   dur_s: float, *, replica_id: str = ROUTER_TRACK,
                   role: str = "router", cat: str = "router",
                   args: Optional[dict] = None) -> None:
    """Record one fleet span.  No-op without a trace context — the same
    enablement switch every engine span uses."""
    if not ctx:
        return
    get_recorder().record(ctx, name, start_wall, dur_s, cat=cat,
                          args=args, replica_id=replica_id, role=role)


def journey_instant(ctx: Optional[dict], name: str, *,
                    replica_id: str = ROUTER_TRACK, role: str = "router",
                    cat: str = "router",
                    args: Optional[dict] = None) -> None:
    """Zero-duration marker span (failover decisions, sheds, ladder
    transitions — events, not intervals)."""
    record_journey(ctx, name, time.time(), 0.0, replica_id=replica_id,
                   role=role, cat=cat, args=args)


# ------------------------------------------------------ external joins
# W3C traceparent: version "-" 32 lowercase hex trace-id "-" 16 hex
# parent-id "-" 2 hex flags.  An all-zero trace id is the spec's
# "invalid" sentinel and must not be joined.
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$")
# x-omni-trace-id: our own lighter header — hex/word chars, bounded
_OMNI_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_\-]{1,64}$")


def parse_traceparent(value) -> Optional[str]:
    """W3C ``traceparent`` header -> trace id, or None when malformed
    (client input: never raises)."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    tid = m.group(1)
    if tid == "0" * 32:
        return None
    return tid


def inbound_trace_id(headers) -> Optional[str]:
    """Join an external trace: ``x-omni-trace-id`` wins (explicit
    opt-in to OUR tracing), then ``traceparent`` (ambient W3C context).
    ``headers`` is any mapping with ``.get`` (http.server's message
    object is case-insensitive).  Returns a validated trace id or
    None."""
    try:
        raw = headers.get("x-omni-trace-id")
    except Exception:
        return None
    if raw and _OMNI_TRACE_ID_RE.match(str(raw).strip()):
        return str(raw).strip()
    try:
        tp = headers.get("traceparent")
    except Exception:
        return None
    if tp:
        return parse_traceparent(tp)
    return None


__all__ = [
    "SPAN_DISPATCH", "SPAN_FAILOVER", "SPAN_SHED", "SPAN_DEGRADED",
    "SPAN_PREFIX_HIT",
    "SPAN_HANDOFF_SHIP", "SPAN_HANDOFF_RECV", "SPAN_ADOPT",
    "SPAN_PREFIX_PULL", "CP_PREFIX",
    "ROUTER_TRACK", "record_journey", "journey_instant",
    "parse_traceparent", "inbound_trace_id",
]
