"""Per-request distributed tracing: span recorder + trace-event export.

See ``trace.py`` for the design; ``docs/observability.md`` for usage.
"""

from vllm_omni_tpu.tracing.trace import (
    TraceRecorder,
    TraceWriter,
    get_recorder,
    new_trace_context,
    to_chrome_trace,
)

__all__ = [
    "TraceRecorder",
    "TraceWriter",
    "get_recorder",
    "new_trace_context",
    "to_chrome_trace",
]
