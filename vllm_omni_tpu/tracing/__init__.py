"""Per-request distributed tracing: span recorder + trace-event export.

See ``trace.py`` for the single-engine span layer, ``journey.py`` for
the fleet-wide journey layer (router/handoff/control-plane spans,
external trace joining); ``docs/observability.md`` for usage.
"""

from vllm_omni_tpu.tracing.journey import (
    inbound_trace_id,
    journey_instant,
    parse_traceparent,
    record_journey,
)
from vllm_omni_tpu.tracing.trace import (
    TraceRecorder,
    TraceWriter,
    get_recorder,
    iter_chrome_events,
    new_trace_context,
    to_chrome_trace,
)

__all__ = [
    "TraceRecorder",
    "TraceWriter",
    "get_recorder",
    "iter_chrome_events",
    "new_trace_context",
    "to_chrome_trace",
    "inbound_trace_id",
    "journey_instant",
    "parse_traceparent",
    "record_journey",
]
