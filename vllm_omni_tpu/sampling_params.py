"""Per-request sampling parameters.

TPU-native analogue of vLLM's ``SamplingParams`` as consumed by the
reference's stage workers (reference: vllm_omni/entrypoints/omni_stage.py
batches only requests with identical sampling params, omni_stage.py:797-843;
default params come from stage YAML ``default_sampling_params``).

Kept deliberately flat: the engine vectorizes these into device arrays per
scheduled batch (see worker/model_runner.py), so every field must be a
scalar that can ride a jnp array — no callables, no logits processors v1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass
class SamplingParams:
    max_tokens: int = 16
    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0
    min_tokens: int = 0
    seed: Optional[int] = None
    stop_token_ids: Sequence[int] = field(default_factory=tuple)
    ignore_eos: bool = False
    # repetition penalties (applied host-side pre-softmax when != defaults)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    # OpenAI-style logprobs: None = off; 0..20 returns the sampled
    # token's logprob plus that many top alternatives per step
    logprobs: "Optional[int]" = None

    def __post_init__(self):
        if self.logprobs is not None and not 0 <= int(self.logprobs) <= 20:
            raise ValueError("logprobs must be within [0, 20]")
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.stop_token_ids = tuple(self.stop_token_ids)

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0
