"""Stall watchdog: trip when step progress stalls past a deadline.

The supervisor's heartbeats (resilience/supervisor.py) catch a *worker
process* that stopped answering; nothing before this module caught the
in-process failure mode — an engine thread alive but wedged (deadlocked
lock, runaway device wait, scheduler livelock) while requests age out.
The watchdog is a monitor thread that polls registered *sources* and
declares a stall when a source is busy (has unfinished work) but its
progress counter has not advanced within the deadline.

Two kinds of "no progress" look identical from outside and must not be
conflated (docs/debugging.md):

- **XLA compile stalls** — a shape-cache miss mid-traffic blocks every
  in-flight request for a full compile (20-40 s on a remote-attached
  chip).  The runner's PR 5 compile telemetry distinguishes them: a
  fresh compile in flight (``compile_stats["in_flight"]``) or the
  ``jit_compiles_total`` counter advancing since the stall began means
  the device is compiling, not hung.  Those windows EXTEND the deadline
  (counted in ``compile_stalls`` so a pathological compile loop is
  still visible) instead of tripping.
- **true hangs** — busy, no steps, no compile activity.  On trip the
  watchdog captures the full incident context: all-thread stacks
  (``sys._current_frames``), every registered engine's in-flight
  request table (age, phase, token accounting, deadline remaining,
  tenant), the flight-recorder tails, and the per-source stall ages —
  and writes one dump document (``dump_to_file``) before notifying
  ``on_trip`` callbacks.  ``/health`` turns 503 once tripped so a load
  balancer ejects the wedged replica.

Cross-process stages feed the same machinery: the supervisor's
heartbeat state (last-pong age) registers as a source, so a trip dump
covers remote workers the in-proc probes cannot see.

Clock and sleep are injectable (same stance as StageSupervisor) so the
unit tests drive the whole state machine with a fake clock — no real
threads, no sleeps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.introspection.flight_recorder import (
    build_dump,
    dump_to_file,
)
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

# a probe returns this shape; every field optional but "busy"
#   busy:              the source has unfinished work
#   progress:          any monotone int that advances when work advances
#   compiles:          cumulative fresh-compile count (jit_compiles_total)
#   compile_in_flight: a fresh XLA compile is running right now
#   detail:            JSON-ready context included in trip dumps
Probe = Callable[[], dict]


@dataclass
class _SourceState:
    name: str
    probe: Probe
    last_progress: Optional[int] = None
    last_compiles: int = 0
    # when the current no-progress window began (None = progressing)
    stalled_since: Optional[float] = None
    compile_stalls: int = 0
    # whether the previous poll already saw this compile in flight —
    # compile_stalls counts compile EVENTS, not poll intervals
    was_compiling: bool = False
    detail: dict = field(default_factory=dict)


class StallWatchdog:
    """Monitor for in-process engine liveness.

    ``check_once()`` is the whole state machine (the thread just calls
    it on an interval), so tests — and operators poking a live process
    — can drive it synchronously.
    """

    def __init__(
        self,
        deadline_s: float = 60.0,
        *,
        poll_interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        on_trip: Optional[Callable[[dict], None]] = None,
        dump_path: Optional[str] = None,
    ):
        self.deadline_s = float(deadline_s)
        self._poll = (poll_interval_s if poll_interval_s is not None
                      else max(self.deadline_s / 4.0, 0.05))
        self._clock = clock
        self._sleep = sleep
        self._dump_path = dump_path
        self._on_trip: list[Callable[[dict], None]] = (
            [on_trip] if on_trip else [])
        self._lock = traced(threading.Lock(), "StallWatchdog._lock")
        self._sources: dict[str, _SourceState] = {}
        # weak handles to engines for the trip dump's request tables +
        # flight-recorder tails (the introspection registry owns the
        # weakrefs; the watchdog just asks at dump time)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # trip latch: /health flips 503 off this (one trip is enough to
        # eject the replica; un-tripping is a restart's job)
        self.tripped: Optional[dict] = None
        self.trips = 0

    # ------------------------------------------------------------- sources
    def add_source(self, name: str, probe: Probe) -> None:
        with self._lock:
            self._sources[name] = _SourceState(name=name, probe=probe)

    def add_engine(self, name: str, engine) -> None:
        """Register an LLMEngine-shaped object (anything exposing
        ``introspect_progress``)."""
        self.add_source(name, engine.introspect_progress)

    def add_supervisor(self, name: str, supervisor) -> None:
        """Register a StageSupervisor: progress is the worker's last
        pong stamp, so a remote worker that stops answering heartbeats
        stalls this source and lands in the trip dump alongside the
        in-proc engines (the supervisor still owns restart policy)."""

        def probe() -> dict:
            stage = getattr(supervisor, "_stage", None)
            last_pong = float(getattr(stage, "last_pong", 0.0) or 0.0)
            return {
                "busy": bool(getattr(supervisor, "has_unfinished", False)),
                # ms resolution keeps the counter integral and monotone
                "progress": int(last_pong * 1e3),
                "detail": {
                    "kind": "supervised_stage",
                    "restarts": getattr(supervisor, "_restarts", 0),
                    "dead": getattr(supervisor, "_dead", False),
                },
            }

        self.add_source(name, probe)

    def on_trip(self, fn: Callable[[dict], None]) -> None:
        self._on_trip.append(fn)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "StallWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="stall-watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._closed = True

    def _loop(self) -> None:
        while not self._closed:
            self._sleep(self._poll)
            if self._closed:
                return
            try:
                self.check_once()
            except Exception:  # the monitor must never kill the process
                logger.exception("watchdog check failed")

    # ---------------------------------------------------------- the check
    def check_once(self) -> Optional[dict]:
        """Poll every source once; returns the trip document if this
        check tripped, else None.  Idempotent after a trip (the latch
        stays set; further stalls don't re-dump)."""
        now = self._clock()
        with self._lock:
            sources = list(self._sources.values())
        stalled: list[tuple[_SourceState, float]] = []
        for st in sources:
            try:
                p = st.probe() or {}
            except Exception as e:
                # a probe that raises is itself a liveness signal worth
                # surfacing, but never a reason to trip
                st.detail = {"probe_error": repr(e)}
                continue
            st.detail = dict(p.get("detail") or {})
            progress = p.get("progress")
            compiles = int(p.get("compiles") or 0)
            in_flight = bool(p.get("compile_in_flight"))
            if not p.get("busy"):
                st.stalled_since = None
                st.last_progress = progress
                st.last_compiles = compiles
                st.was_compiling = False
                continue
            if st.last_progress is None or progress != st.last_progress:
                # progress observed NOW: the next stall window is
                # measured from this observation, so one poll interval
                # of queueing never inflates the stall age.
                # was_compiling resets too: compile-event accounting
                # belongs to stall windows only
                st.last_progress = progress
                st.last_compiles = compiles
                st.stalled_since = now
                st.was_compiling = False
                continue
            # busy + no progress: the stall window is open
            if st.stalled_since is None:
                st.stalled_since = now
            if in_flight or compiles != st.last_compiles:
                # the device is compiling, not hung: restart the window.
                # compile_stalls counts compile EVENTS — a completion
                # (counter advanced) or a NEW in-flight compile — not
                # every poll that re-observes the same long compile
                if compiles != st.last_compiles or not st.was_compiling:
                    st.compile_stalls += 1
                st.last_compiles = compiles
                st.was_compiling = in_flight
                st.stalled_since = now
                continue
            st.was_compiling = False
            stalled_for = now - st.stalled_since
            if stalled_for >= self.deadline_s:
                stalled.append((st, stalled_for))
        if not stalled or self.tripped is not None:
            return None
        return self._trip(stalled)

    # -------------------------------------------------------------- tripping
    def _trip(self, stalled: list[tuple[_SourceState, float]]) -> dict:
        from vllm_omni_tpu import introspection

        worst = max(s for _, s in stalled)
        names = [st.name for st, _ in stalled]
        logger.error(
            "stall watchdog TRIPPED: %s made no progress for %.1fs "
            "(deadline %.1fs)", ", ".join(names), worst, self.deadline_s)
        engines = introspection.iter_engines()
        # registry read under the lock: add_source from another thread
        # mid-trip must not race the dump's source inventory (OL7)
        with self._lock:
            registered = sorted(self._sources)
        extra: dict[str, Any] = {
            "watchdog": {
                "deadline_s": self.deadline_s,
                "stalled_sources": [
                    {"name": st.name, "stalled_s": round(s, 3),
                     "compile_stalls": st.compile_stalls,
                     "detail": st.detail}
                    for st, s in stalled
                ],
                "sources": registered,
            },
            "requests": [
                {"engine": getattr(e, "stage_id", i),
                 "table": introspection.request_table(e)}
                for i, e in enumerate(engines)
            ],
        }
        doc = build_dump(
            "watchdog_trip",
            recorders=[e.flight for e in engines
                       if getattr(e, "flight", None) is not None],
            extra=extra)
        dump_to_file(doc, self._dump_path)
        self.trips += 1
        self.tripped = {
            "reason": "stall",
            "sources": names,
            "stalled_s": round(worst, 3),
            "ts": doc["ts"],
        }
        for fn in list(self._on_trip):
            try:
                fn(doc)
            except Exception:
                logger.exception("watchdog on_trip callback failed")
        return doc

    # ------------------------------------------------------------- reading
    def state(self) -> dict:
        """JSON-ready view for /debug + /health: per-source stall ages
        and the trip latch."""
        now = self._clock()
        with self._lock:
            sources = list(self._sources.values())
        return {
            "deadline_s": self.deadline_s,
            "running": self._thread is not None and not self._closed,
            "tripped": self.tripped,
            "trips": self.trips,
            "sources": {
                st.name: {
                    "stalled_s": (round(now - st.stalled_since, 3)
                                  if st.stalled_since is not None else 0.0),
                    "compile_stalls": st.compile_stalls,
                    "last_progress": st.last_progress,
                }
                for st in sources
            },
        }
