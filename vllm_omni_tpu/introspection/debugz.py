"""/debug/z builders: JSON-ready views of a live serving process.

Everything /metrics can't answer during an incident — *which* request
is stuck, what the pipeline slot holds, which radix nodes pin which
pages — renders here.  Pure read-side introspection over duck-typed
engine/stage objects (``getattr`` throughout): AR engines report
everything, diffusion/generation engines and process-disaggregated
stages degrade to whatever they expose, and a half-built pipeline
mid-crash still produces a document instead of a second traceback.

Served by the OpenAI server (entrypoints/openai/api_server.py):

- ``/debug/z``              — index of the family
- ``/debug/engine``         — per-stage engine state (pipeline slot,
                              last step record, warmup/bucket state,
                              compile + fallback telemetry)
- ``/debug/requests``       — in-flight request table
- ``/debug/kv``             — pages/pins/radix/tier occupancy
- ``/debug/flightrecorder`` — the step-record ring (?n= tail size)
- ``/debug/stacks``         — all-thread stacks
- ``/debug/watchdog``       — stall-watchdog state

None of these mutate anything, and none sync the device.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from vllm_omni_tpu.introspection.flight_recorder import capture_stacks

ENDPOINTS = ("/debug/engine", "/debug/requests", "/debug/kv",
             "/debug/flightrecorder", "/debug/stacks", "/debug/watchdog",
             "/debug/disagg", "/debug/controlplane", "/debug/trace",
             "/debug/alerts", "/debug/tenants", "/debug/cache")


# -------------------------------------------------------- request table
def request_table(engine) -> list[dict]:
    """In-flight request table for one engine: the incident-response
    answer to "which request is stuck".  Age/deadline are monotonic
    durations; absent fields degrade to None."""
    from vllm_omni_tpu.resilience.deadline import remaining_s

    sched = getattr(engine, "scheduler", None)
    if sched is None:
        return []
    now = time.monotonic()
    rows: list[dict] = []
    for phase, queue in (("waiting", getattr(sched, "waiting", ())),
                         ("running", getattr(sched, "running", ()))):
        for req in list(queue):
            info = getattr(req, "additional_information", {}) or {}
            remaining = remaining_s(getattr(req, "deadline_ts", None))
            arrival = getattr(req, "arrival_mono", 0.0)
            rows.append({
                "request_id": getattr(req, "request_id", "?"),
                "phase": phase,
                "status": getattr(getattr(req, "status", None),
                                  "name", str(getattr(req, "status", ""))),
                "tenant": getattr(req, "tenant", "default"),
                "age_s": round(now - arrival, 3) if arrival else None,
                "prompt_tokens": getattr(req, "num_prompt_tokens", None),
                "output_tokens": len(getattr(req, "output_token_ids", ())),
                "computed_tokens": getattr(req, "num_computed_tokens",
                                           None),
                "inflight_tokens": getattr(req, "num_inflight_tokens", 0),
                "deadline_remaining_s": (round(remaining, 3)
                                         if remaining is not None
                                         else None),
                "awaiting_chunks": bool(getattr(req, "awaiting_chunks",
                                                False)),
                "parked": bool(info.get("_parked_len")),
            })
    return rows


# --------------------------------------------------------- engine views
def _pipeline_slot(engine) -> dict:
    inflight = getattr(engine, "_inflight", None)
    if inflight is None:
        return {"occupied": False}
    sched_out = getattr(inflight, "sched_out", None)
    handle = getattr(inflight, "handle", None)
    rows = getattr(handle, "rows", None)
    return {
        "occupied": True,
        "prefills": len(getattr(sched_out, "prefills", ())),
        "decodes": len(getattr(sched_out, "decodes", ())),
        "rows": sorted(rows) if isinstance(rows, dict) else None,
    }


def engine_debug(engine) -> dict:
    """Pipeline slot + last step record + warmup/bucket/compile state
    for one engine (AR; other engine kinds report what they have)."""
    runner = getattr(engine, "runner", None)
    flight = getattr(engine, "flight", None)
    cfg = getattr(engine, "config", None)
    last = flight.tail(1) if flight is not None else []
    doc: dict[str, Any] = {
        "engine_type": type(engine).__name__,
        "stage_id": getattr(engine, "stage_id", None),
        "has_unfinished": bool(getattr(engine, "has_unfinished_requests",
                                       False)),
        "pipeline_slot": _pipeline_slot(engine),
        "last_step": last[0] if last else None,
        "last_step_age_s": (flight.last_step_age_s()
                            if flight is not None else None),
        "async_fallback": dict(getattr(engine, "async_fallback", {}) or {}),
    }
    if cfg is not None:
        doc["config"] = {
            "worker_type": getattr(cfg, "worker_type", None),
            "async_scheduling": getattr(cfg, "async_scheduling", None),
            "unified_batching": getattr(cfg, "unified_batching", None),
            "kv_offload": getattr(cfg, "kv_offload", None),
            "max_num_seqs": getattr(cfg, "max_num_seqs", None),
            "max_num_batched_tokens": getattr(cfg,
                                              "max_num_batched_tokens",
                                              None),
        }
    if runner is not None:
        doc["warmup"] = {
            "batch_buckets": list(getattr(runner, "_batch_buckets", ())),
            "seq_buckets": list(getattr(runner, "_seq_buckets", ())),
            "token_buckets": list(getattr(runner, "_token_buckets", ())),
            "shapes_seen": len(getattr(runner, "_jit_seen", ()) or ()),
        }
        doc["compile"] = dict(getattr(runner, "compile_stats", {}) or {})
    ledger = getattr(engine, "memory", None)
    if ledger is not None:
        doc["device_memory"] = ledger.snapshot()
    roofline = getattr(engine, "roofline", None)
    if roofline is not None:
        # rolling MFU/MBU window (metrics/roofline.py): the live
        # roofline view — window means + the last ~32 per-step readings
        doc["roofline"] = roofline.snapshot()
    return doc


def kv_debug(engine) -> dict:
    """Radix/page/pin/tier occupancy for one engine's KV manager."""
    sched = getattr(engine, "scheduler", None)
    kv = getattr(sched, "kv", None)
    if kv is None:
        return {}
    fn = getattr(kv, "debug_snapshot", None)
    doc = fn() if fn is not None else {
        "pages_total": getattr(kv, "num_pages", None),
        "pages_free": getattr(kv, "num_free_pages", None),
    }
    tiers = getattr(engine, "kv_tiers", None)
    if tiers is not None:
        doc["tiers"] = tiers.debug_snapshot()
    return doc


# ----------------------------------------------------- pipeline rollups
def _stage_engines(omni):
    """[(stage_id, engine-or-None, stage)] over the pipeline; proc
    stages carry engine None (their engine lives in the worker)."""
    out = []
    for stage in getattr(omni, "stages", ()):
        out.append((getattr(stage, "stage_id", None),
                    getattr(stage, "engine", None), stage))
    return out


def _per_stage(omni, fn, empty) -> dict:
    doc = {}
    for sid, engine, stage in _stage_engines(omni):
        if engine is None:
            doc[str(sid)] = {
                "process_stage": True,
                "note": "engine runs in a worker process; see the "
                        "worker's own dump / engine_metrics_snapshot",
                "metrics_snapshot": _safe_snapshot(stage),
            }
        else:
            try:
                doc[str(sid)] = fn(engine)
            except Exception as e:
                # the builders read live engine state without locks;
                # a torn read mid-mutation degrades to an error marker
                # instead of 500ing the one request an operator is
                # using to debug the engine — retry, don't crash
                doc[str(sid)] = {"error": repr(e), "retry": True}
    return doc if doc else empty


def _safe_snapshot(stage) -> dict:
    fn = getattr(stage, "engine_metrics_snapshot", None)
    try:
        return fn() if fn is not None else {}
    except Exception:
        return {}


def debug_engine(omni) -> dict:
    return {"stages": _per_stage(omni, engine_debug, {})}


def debug_requests(omni) -> dict:
    return {"stages": _per_stage(omni, request_table, {})}


def debug_kv(omni) -> dict:
    return {"stages": _per_stage(omni, kv_debug, {})}


def debug_flightrecorder(omni, tail: Optional[int] = None) -> dict:
    def one(engine):
        flight = getattr(engine, "flight", None)
        return (flight.snapshot(tail=tail) if flight is not None
                else {})

    return {"stages": _per_stage(omni, one, {})}


def debug_stacks() -> dict:
    return {"stacks": capture_stacks()}


def debug_watchdog(omni) -> dict:
    wd = getattr(omni, "watchdog", None)
    return wd.state() if wd is not None else {"enabled": False}


def debug_disagg(omni) -> dict:
    """Disagg-router state (docs/disaggregation.md): replica table
    (role/dead/ejected/drained/queue depth), in-flight request phases,
    and the failover/handoff ledgers.  ``{"enabled": False}`` on
    deployments without a router — the endpoint always answers."""
    router = getattr(omni, "router", None)
    if router is None:
        return {"enabled": False}
    try:
        return router.debug_snapshot()
    except Exception as e:
        # same stance as _per_stage: a torn concurrent read degrades
        # to a retry marker, never a 500 on the debugging request
        return {"enabled": True, "error": repr(e), "retry": True}


def debug_cache(omni) -> dict:
    """Fleet cache-economics board (docs/disaggregation.md): per-
    replica radix digest summaries, top cross-replica duplicated
    prefixes, the dispatch regret ledger, and the fleet hit-rate
    counters.  ``{"enabled": False}`` on deployments without a disagg
    router — the endpoint always answers; a torn concurrent read
    degrades to the retry marker, never a 500."""
    cache = getattr(getattr(omni, "router", None), "cache", None)
    if cache is None:
        return {"enabled": False}
    try:
        return cache.board()
    except Exception as e:
        return {"enabled": True, "error": repr(e), "retry": True}


def debug_controlplane(omni) -> dict:
    """Control-plane state (docs/control_plane.md): the sensor
    snapshot, the in-flight operation's stage, warming replicas, and
    the structured-action ring.  ``{"enabled": False}`` on deployments
    without a controller — the endpoint always answers."""
    cp = getattr(omni, "controlplane", None)
    if cp is None:
        return {"enabled": False}
    try:
        return cp.debug_snapshot()
    except Exception as e:
        # same stance as _per_stage: a torn concurrent read degrades
        # to a retry marker, never a 500 on the debugging request
        return {"enabled": True, "error": repr(e), "retry": True}


def debug_trace(omni) -> dict:
    """Trace-layer self-view (docs/observability.md): recorder
    occupancy + drop accounting, and — when a writer is configured —
    its file paths, chrome-buffer bookkeeping, and the last-export
    timestamp.  The one subsystem that had no /debug view of itself:
    "why does my trace have holes" is answered here, not by reading
    the jsonl backwards."""
    from vllm_omni_tpu.tracing import get_recorder

    rec = get_recorder()
    writer = getattr(omni, "_trace_writer", None)
    doc = {
        "enabled": writer is not None,
        "recorder": {
            "buffered_spans": len(rec),
            "capacity": rec.capacity,
            "spans_dropped": rec.spans_dropped,
        },
    }
    if writer is not None:
        try:
            doc["writer"] = writer.debug_snapshot()
        except Exception as e:
            # same stance as _per_stage: torn read -> retry marker
            doc["writer"] = {"error": repr(e), "retry": True}
    return doc


def debug_alerts(omni) -> dict:
    """Alert-engine state (docs/observability.md): every rule's
    declaration + lifecycle state, window values at the last
    evaluation, the transition-ring tail, and the dump-cooldown
    self-view evidence capture rides.  ``{"enabled": False}`` on
    deployments without an alert engine — the endpoint always
    answers."""
    alerts = getattr(omni, "alerts", None)
    if alerts is None:
        return {"enabled": False}
    try:
        return alerts.snapshot()
    except Exception as e:
        # same stance as _per_stage: a torn concurrent read degrades
        # to a retry marker, never a 500 on the debugging request
        return {"enabled": True, "error": repr(e), "retry": True}


def debug_tenants(omni) -> dict:
    """Per-stage tenant attribution boards (metrics/attribution.py):
    top-k heavy hitters per consumption meter with their proven error
    bounds — the incident answer to "which tenant is eating the
    fleet"."""

    def one(engine):
        attr = getattr(engine, "attribution", None)
        # claim_slots=False: a debugging poll must not burn lifetime
        # /metrics label slots on tenants the exposition never renders
        return (attr.snapshot(claim_slots=False)
                if attr is not None else {})

    return {"stages": _per_stage(omni, one, {})}


def debug_index() -> dict:
    return {"endpoints": list(ENDPOINTS),
            "hint": "see docs/debugging.md for the tour"}


# ---------------------------------------------------------------- health
def health_snapshot(omni, engine_thread_alive: Optional[bool] = None
                    ) -> tuple[int, dict]:
    """The honest /health: (status_code, body).  503 once the watchdog
    has tripped or the engine loop died — a load balancer must eject a
    wedged replica instead of feeding it traffic the static "ok" used
    to invite."""
    wd = getattr(omni, "watchdog", None)
    ages = []
    for _, engine, _ in _stage_engines(omni):
        flight = getattr(engine, "flight", None)
        if flight is not None:
            age = flight.last_step_age_s()
            if age is not None:
                ages.append(age)
    body: dict[str, Any] = {
        "status": "ok",
        # youngest engine step across stages; None before any step ran
        # (an idle engine's age GROWS — pair it with the busy flag)
        "last_step_age_s": (round(min(ages), 3) if ages else None),
        "busy": any(
            bool(getattr(e, "has_unfinished_requests", False))
            for _, e, _ in _stage_engines(omni) if e is not None),
        "watchdog": (wd.state() if wd is not None
                     else {"enabled": False}),
    }
    # read-only alert visibility: the count of firing alerts rides the
    # payload WITHOUT joining the 503 decision — ejection stays the
    # watchdog/engine-liveness contract (an overload alert means "shed
    # and scale", not "take the replica out back")
    alerts = getattr(omni, "alerts", None)
    if alerts is not None:
        try:
            body["alerts_firing"] = len(alerts.firing())
        except Exception:
            body["alerts_firing"] = None
    if engine_thread_alive is not None:
        body["engine_alive"] = bool(engine_thread_alive)
    code = 200
    if wd is not None and wd.tripped is not None:
        body["status"] = "stalled"
        code = 503
    if engine_thread_alive is False:
        body["status"] = "dead"
        code = 503
    return code, body
