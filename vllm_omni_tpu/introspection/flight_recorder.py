"""Flight recorder: a bounded ring of structured per-step records.

The black-box layer under the tracing/metrics spine (docs/debugging.md).
Counters and Perfetto traces are aggregate and after-the-fact; when an
engine wedges, the question an operator actually asks is *what were the
last 200 steps doing* — which path each step took, what the batch looked
like, which requests rode it, where the time went.  The recorder answers
that with a fixed-capacity deque of plain dicts that:

- costs one lock + one deque append per engine step.  Every field is a
  host-side int/str/float the step loop already computed — appending
  performs **zero device syncs** (the recorder lives in the omnilint
  OL2 HOT_PATHS manifest so a stray ``device_get`` can't creep in);
- survives and explains the bad minute: the ring is dumped as JSON on
  crash (``sys.excepthook`` / ``atexit``), on ``SIGUSR2``, on a stall-
  watchdog trip, and on demand (``/debug/flightrecorder``);
- is deterministic: records carry a monotone ``seq`` so tests (and
  humans diffing two dumps) can see exactly which records the ring
  evicted (``seq`` gaps at the head == ``dropped``).

Dump files land under ``OMNI_TPU_FLIGHT_DIR`` when set; the crash hooks
are silent no-ops without it (a test process exiting must not litter
the working directory).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

# bump when the dump/record schema changes shape (incident tooling
# parses these files long after the process that wrote them is gone).
# v2: step records are record-schema v3 — they gain live roofline
# attribution ("mfu"/"mbu"/"roofline_phase") and the capped
# "trace_ids" journey cross-link (docs/debugging.md) — additive, so
# v1 consumers keep parsing
SCHEMA_VERSION = 2


class FlightRecorder:
    """Bounded per-step record ring for one engine.

    Thread-safe: the engine thread appends while the /debug HTTP thread
    (or a crash hook on an arbitrary thread) snapshots.
    """

    def __init__(self, capacity: int = 256, name: str = "engine"):
        if capacity < 1:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.name = name
        self._ring: deque = deque(maxlen=capacity)
        self._lock = traced(threading.Lock(), "FlightRecorder._lock")
        self._seq = 0
        self._dropped = 0
        # monotonic stamp of the last append — /health reports this as
        # last_step_age_s and the watchdog keys progress off _seq
        self._last_mono = 0.0
        self._last_wall = 0.0

    # ------------------------------------------------------------- append
    def append(self, record: dict) -> None:
        """Append one step record (host values only — callers must never
        compute a field by syncing the device for the recorder's sake).
        Stamps ``seq`` (monotone) and ``ts`` (wall clock, for correlating
        dumps against logs/traces)."""
        now_m = time.monotonic()
        record["ts"] = time.time()
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(record)
            self._last_mono = now_m
            self._last_wall = record["ts"]

    # ------------------------------------------------------------ reading
    @property
    def total_steps(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Records evicted by the ring (lifetime).  Expected to grow on
        any long-running engine — the ring is a tail, not a history."""
        with self._lock:
            return self._dropped

    def last_step_age_s(self) -> Optional[float]:
        """Seconds since the last appended record (monotonic), or None
        when nothing was ever recorded."""
        with self._lock:
            if self._last_mono == 0.0:
                return None
            return max(time.monotonic() - self._last_mono, 0.0)

    def tail(self, n: Optional[int] = None) -> list[dict]:
        with self._lock:
            records = list(self._ring)
        if n is not None and n >= 0:
            records = records[-n:] if n else []
        return records

    def snapshot(self, tail: Optional[int] = None) -> dict:
        """JSON-ready view of the ring + its bookkeeping (the shape the
        dump files and /debug/flightrecorder serve)."""
        with self._lock:
            records = list(self._ring)
            seq, dropped = self._seq, self._dropped
            last_wall = self._last_wall
        if tail is not None and tail >= 0:
            records = records[-tail:] if tail else []
        return {
            "name": self.name,
            "capacity": self.capacity,
            "total_steps": seq,
            "dropped": dropped,
            "last_step_ts": last_wall or None,
            "records": records,
        }


# ---------------------------------------------------------------- dumping
# process-wide dump ordinal: filenames stay unique even when two dumps
# with the same reason land in the same second (e.g. repeated SIGUSR2)
_dump_seq = 0
_dump_seq_lock = threading.Lock()


class DumpCooldown:
    """Per-reason dump rate limit: a flapping alert, a held-down
    SIGUSR2, or a crash loop must not flood ``OMNI_TPU_FLIGHT_DIR``
    with near-identical documents.  Keys are ``reason@dir`` — distinct
    reasons never throttle each other (a crash dump lands even seconds
    after an alert bundle), and distinct directories are independent
    (test processes point each dump at a fresh tmpdir).

    Suppressions are COUNTED per key and visible in ``snapshot()``
    (served on /debug/alerts, the watchdog-state stance) so an
    operator can see that dumps were elided, not lost.  Clock is
    injectable for fake-clock tests; the window resolves through
    ``OMNI_TPU_DUMP_COOLDOWN_S`` unless pinned at construction."""

    def __init__(self, cooldown_s: Optional[float] = None,
                 clock=time.monotonic):
        self._cooldown_s = cooldown_s
        self._clock = clock
        self._lock = traced(threading.Lock(), "DumpCooldown._lock")
        self._last: dict[str, float] = {}
        self._prev: dict[str, Optional[float]] = {}
        self._suppressed: dict[str, int] = {}

    def window_s(self) -> float:
        if self._cooldown_s is not None:
            return float(self._cooldown_s)
        from vllm_omni_tpu import envs

        return float(envs.OMNI_TPU_DUMP_COOLDOWN_S)

    def ready(self, reason: str, where: str = "") -> bool:
        """True (and RESERVES the window atomically — two threads
        racing the same reason cannot both pass) when a dump for
        ``reason`` may write now; False counts a suppression.  A
        writer whose write then fails calls :meth:`release` so a full
        disk at the worst possible moment neither eats the window nor
        fakes a last-dump age for a bundle that was never written."""
        key = f"{reason}@{where}"
        window = self.window_s()
        now = self._clock()
        with self._lock:
            last = self._last.get(key)
            if window > 0 and last is not None and now - last < window:
                self._suppressed[key] = self._suppressed.get(key, 0) + 1
                return False
            self._prev[key] = last
            self._last[key] = now
            return True

    def release(self, reason: str, where: str = "") -> None:
        """Roll back a :meth:`ready` reservation whose write failed:
        the prior stamp (if any) is restored, so the next attempt is
        not suppressed by a dump that never landed."""
        key = f"{reason}@{where}"
        with self._lock:
            prev = self._prev.pop(key, None)
            if prev is None:
                self._last.pop(key, None)
            else:
                self._last[key] = prev

    def snapshot(self) -> dict:
        """JSON-ready self-view: the window plus, per reason key, the
        age of the last written dump and the suppressed count."""
        now = self._clock()
        with self._lock:
            last = dict(self._last)
            suppressed = dict(self._suppressed)
        return {
            "cooldown_s": self.window_s(),
            "reasons": {
                key: {
                    "last_dump_age_s": round(now - t, 3),
                    "suppressed": suppressed.get(key, 0),
                }
                for key, t in sorted(last.items())
            },
        }


#: the process-wide limiter ``dump_to_file`` consults for every
#: flight-dir-resolved write (explicit-path callers manage their own
#: files and bypass it)
dump_cooldown = DumpCooldown()


def capture_stacks() -> dict:
    """All-thread stack traces, keyed by thread name (falling back to
    the raw thread id).  Pure host introspection — safe from any thread,
    including a signal handler or a dying excepthook."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks: dict[str, list[str]] = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')}-{tid}"
        stacks[label] = [
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        ]
    return stacks


def build_dump(reason: str, *, recorders: list[FlightRecorder] = (),
               extra: Optional[dict] = None,
               include_stacks: bool = True) -> dict:
    """One self-contained incident document: every recorder's ring,
    all-thread stacks, and whatever context the tripper adds."""
    doc: dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "recorders": [r.snapshot() for r in recorders],
    }
    if include_stacks:
        doc["stacks"] = capture_stacks()
    if extra:
        doc.update(extra)
    return doc


def dump_to_file(doc: dict, path: Optional[str] = None) -> Optional[str]:
    """Write a dump document as JSON.  ``path`` None resolves through
    ``OMNI_TPU_FLIGHT_DIR``; unset means the dump is skipped (returns
    None) — crash hooks must not litter CWD in ordinary test runs.
    Flight-dir-resolved writes are rate-limited PER REASON through
    :data:`dump_cooldown` (suppressed writes return None and are
    counted); an explicit ``path`` bypasses the limiter — the caller
    chose the exact file, so flooding is its problem to solve."""
    cooldown_key = None
    written = None
    try:
        if path is None:
            from vllm_omni_tpu import envs

            flight_dir = envs.OMNI_TPU_FLIGHT_DIR
            if not flight_dir:
                return None
            reason = str(doc.get("reason", "dump")).replace("/", "_")
            if not dump_cooldown.ready(reason, flight_dir):
                logger.warning(
                    "flight-recorder dump (%s) suppressed by the %ss "
                    "per-reason cooldown", reason,
                    dump_cooldown.window_s())
                return None
            cooldown_key = (reason, flight_dir)
            try:
                os.makedirs(flight_dir, exist_ok=True)
            except OSError as e:  # a dying process must not die harder
                logger.error("flight-recorder dir %s unusable: %s",
                             flight_dir, e)
                return None
            global _dump_seq
            with _dump_seq_lock:
                _dump_seq += 1
                seq = _dump_seq
            path = os.path.join(
                flight_dir,
                f"flight-{os.getpid()}-{int(doc.get('ts', 0))}"
                f"-{seq:03d}-{reason}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        logger.warning("flight-recorder dump (%s) written to %s",
                       doc.get("reason"), path)
        written = path
    except OSError as e:  # a dying process must not die harder
        logger.error("flight-recorder dump to %s failed: %s", path, e)
        return None
    finally:
        # the cooldown window is held only by a bundle that actually
        # LANDED.  Releasing in the OSError handlers alone (the
        # original PR 15 shape) left every other failure — a
        # non-serializable doc raising TypeError out of json.dump, a
        # KeyboardInterrupt mid-write — consuming the window for the
        # whole cooldown period with nothing on disk, which OL12's
        # exception-edge pass flags as a leaked acquire.
        if cooldown_key is not None and written is None:
            dump_cooldown.release(*cooldown_key)
    return written


# ------------------------------------------------------------ crash hooks
def _dumping_enabled() -> bool:
    """Whether dump_to_file would actually write (OMNI_TPU_FLIGHT_DIR
    set).  The hooks check this FIRST — building a full dump (every
    ring + all-thread stacks) just to throw it away would tax every
    crash path of every undumped process."""
    from vllm_omni_tpu import envs

    return bool(envs.OMNI_TPU_FLIGHT_DIR)


_hooks_installed = False
_hooks_lock = threading.Lock()


def install_crash_hooks(recorders_fn) -> None:
    """Install the crash-dump hooks once per process: ``sys.excepthook``
    (unhandled exception), ``atexit`` (normal/abnormal interpreter
    exit), and ``SIGUSR2`` (operator-requested dump of a live process).
    ``recorders_fn`` returns the live recorders at dump time — hooks
    hold no strong references, so engines stay collectable.

    All three write through :func:`dump_to_file`, so without
    ``OMNI_TPU_FLIGHT_DIR`` every hook is a no-op.
    """
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return
        _hooks_installed = True

    prev_hook = sys.excepthook

    def _excepthook(exc_type, exc, tb):
        try:
            if _dumping_enabled():
                doc = build_dump(
                    "crash", recorders=recorders_fn(),
                    extra={"exception": "".join(
                        traceback.format_exception(exc_type, exc, tb))})
                dump_to_file(doc)
        except Exception:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _excepthook

    def _atexit():
        try:
            if not _dumping_enabled():
                return
            recs = recorders_fn()
            if any(r.total_steps for r in recs):
                dump_to_file(build_dump("exit", recorders=recs,
                                        include_stacks=False))
        except Exception:
            pass

    atexit.register(_atexit)

    def _on_sigusr2(signum, frame):
        try:
            if _dumping_enabled():
                dump_to_file(build_dump("sigusr2",
                                        recorders=recorders_fn()))
        except Exception:
            pass

    try:
        # only valid on the main thread (and not on every platform) —
        # an engine built from a worker thread simply skips the signal
        # face and keeps the other two hooks
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, AttributeError, OSError):
        pass
