"""Device-memory ledger: per-component HBM accounting with watermarks.

Serving TPUs live or die on exact HBM accounting (the Gemma-4-31B
serving report in PAPERS.md): the difference between "we can admit 40
more sessions" and a RESOURCE_EXHAUSTED abort mid-request is knowing
what actually occupies the device.  ``hbm_bytes`` (one gauge) and the
stage accountant's post-build snapshot say *how much* is used; this
ledger says *by what*:

- **weights** — model parameters (static after load; the runner sums
  leaf ``nbytes`` once, a metadata walk with no device sync);
- **kv_pages** — the paged KV cache arrays (static geometry: pages ×
  page_size × layers × heads × head_dim × itemsize);
- **spec_buffers** — speculative-decode verify buffers when a draft
  head is attached (deterministic estimate from the config);
- **workspace** — everything the components above can't name: compiled
  executables, XLA scratch, collective buffers.  On a real device it is
  the residual ``bytes_in_use − Σ(known components)``; on backends
  without allocator stats (CPU tier-1) it is 0.

Conservation is the ledger's contract either way: **components sum to
total**, and every per-component ``peak`` watermark is monotone.  The
CPU fallback defines total := Σ components, so the invariant is exact
and deterministic — which is what lets tier-1 exercise the same code
path the TPU fleet scrapes (``device_memory_bytes{component}`` /
``device_memory_peak_bytes{component}`` on /metrics).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from vllm_omni_tpu.analysis.runtime import traced

COMPONENT_WORKSPACE = "workspace"


class DeviceMemoryLedger:
    """Per-component live/peak device-memory accounting for one engine.

    ``components_fn`` returns {name: live_bytes} for everything the
    owner can attribute (the runner's static buffers); ``stats_fn``
    returns the platform allocator stats (``bytes_in_use`` /
    ``peak_bytes_in_use``) or None — the default probes the current
    platform, which reports None on CPU.
    """

    def __init__(self, components_fn: Callable[[], dict],
                 stats_fn: Optional[Callable[[], Optional[dict]]] = None):
        if stats_fn is None:
            from vllm_omni_tpu.platforms.memory import device_memory_stats

            stats_fn = device_memory_stats
        self._components_fn = components_fn
        self._stats_fn = stats_fn
        self._lock = traced(threading.Lock(),
                            "DeviceMemoryLedger._lock")
        self._peaks: dict[str, int] = {}
        self._peak_total = 0
        self._last: dict = {}

    def refresh(self) -> dict:
        """Re-read the components + allocator stats and return the
        JSON-ready snapshot.  Cold path only (called from
        ``metrics_snapshot`` / the /debug endpoints, never per step)."""
        comps = {str(k): max(int(v), 0)
                 for k, v in (self._components_fn() or {}).items()}
        known = sum(comps.values())
        stats = None
        try:
            stats = self._stats_fn()
        except Exception:  # a broken probe must not break /metrics
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            total = int(stats["bytes_in_use"])
            comps[COMPONENT_WORKSPACE] = max(total - known, 0)
            # allocator total can lag the components it doesn't know
            # about; conservation is re-established by definition
            total = sum(comps.values())
            source = "device"
            limit = stats.get("bytes_limit")
            device_peak = stats.get("peak_bytes_in_use")
        else:
            comps[COMPONENT_WORKSPACE] = 0
            total = known
            source = "fallback"
            limit = None
            device_peak = None
        with self._lock:
            for name, v in comps.items():
                if v > self._peaks.get(name, 0):
                    self._peaks[name] = v
            self._peak_total = max(self._peak_total, total)
            snap = {
                "source": source,
                "total_bytes": total,
                "peak_total_bytes": self._peak_total,
                "bytes_limit": limit,
                "device_peak_bytes_in_use": device_peak,
                "components": {
                    name: {"bytes": v,
                           "peak_bytes": self._peaks.get(name, v)}
                    for name, v in sorted(comps.items())
                },
            }
            self._last = snap
        return snap

    def snapshot(self) -> dict:
        """Last refreshed view (refreshes on first use)."""
        with self._lock:
            last = self._last
        return last if last else self.refresh()
