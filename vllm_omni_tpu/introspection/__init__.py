"""Introspection: the debugging spine of the serving stack.

Four faces (docs/debugging.md):

- **flight recorder** — bounded ring of per-step records appended from
  ``LLMEngine``'s step paths with zero device syncs, dumped as JSON on
  crash / SIGUSR2 / watchdog trip / demand (flight_recorder.py);
- **stall watchdog** — monitor thread that distinguishes XLA-compile
  stalls from true hangs and captures stacks + request tables + step
  tails on trip (watchdog.py);
- **/debug/z** — live JSON views served by the OpenAI server
  (debugz.py);
- **device-memory ledger** — per-component HBM accounting with peak
  watermarks, CPU-deterministic fallback (memory_ledger.py).

This module owns the process-global engine registry: engines register
at construction (weakly — registration must never extend an engine's
lifetime), and the crash hooks / watchdog / debug endpoints enumerate
the live ones at dump time.
"""

from __future__ import annotations

import threading
import weakref

from vllm_omni_tpu.introspection.debugz import request_table
from vllm_omni_tpu.introspection.flight_recorder import (
    FlightRecorder,
    build_dump,
    capture_stacks,
    dump_to_file,
    install_crash_hooks,
)
from vllm_omni_tpu.introspection.memory_ledger import DeviceMemoryLedger
from vllm_omni_tpu.introspection.watchdog import StallWatchdog

__all__ = [
    "FlightRecorder",
    "DeviceMemoryLedger",
    "StallWatchdog",
    "build_dump",
    "capture_stacks",
    "dump_to_file",
    "install_crash_hooks",
    "register_engine",
    "iter_engines",
    "request_table",
]

_engines: "weakref.WeakSet" = weakref.WeakSet()
_registry_lock = threading.Lock()


def register_engine(engine) -> None:
    """Track a live engine for crash dumps / watchdog trips / debugz.
    Also installs the process crash hooks on first use (they no-op
    without ``OMNI_TPU_FLIGHT_DIR``)."""
    with _registry_lock:
        _engines.add(engine)
    install_crash_hooks(_live_recorders)


def iter_engines() -> list:
    """The live registered engines, stage-ordered (stable for dumps)."""
    with _registry_lock:
        engines = list(_engines)
    return sorted(engines,
                  key=lambda e: (getattr(e, "stage_id", 0) or 0, id(e)))


def _live_recorders() -> list[FlightRecorder]:
    return [e.flight for e in iter_engines()
            if getattr(e, "flight", None) is not None]
