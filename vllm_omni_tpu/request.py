"""Engine request type + lifecycle status.

Mirrors the behavioral surface of the reference's ``OmniRequest``
(reference: vllm_omni/request.py:14 — adds prompt_embeds,
additional_information, external_req_id on top of vLLM's Request) and the
``RequestStatus`` extension with WAITING_FOR_CHUNK
(reference: vllm_omni/patch.py:21-41).

Host-side bookkeeping only — nothing here touches jax.  Device-side state
(KV pages, sampler state) is owned by the KV-cache manager and model runner.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.sampling_params import SamplingParams


class RequestStatus(enum.IntEnum):
    # mirrors the reference's extended enum; WAITING_FOR_CHUNK is the
    # async-chunk streaming state added by patch.py:26-41
    WAITING_FOR_CHUNK = -1
    WAITING = 0
    RUNNING = 1
    PREEMPTED = 2
    FINISHED_STOPPED = 3
    FINISHED_LENGTH = 4
    FINISHED_ABORTED = 5
    FINISHED_ERROR = 6

    @property
    def is_finished(self) -> bool:
        return self >= RequestStatus.FINISHED_STOPPED


FINISH_REASON = {
    RequestStatus.FINISHED_STOPPED: "stop",
    RequestStatus.FINISHED_LENGTH: "length",
    RequestStatus.FINISHED_ABORTED: "abort",
    RequestStatus.FINISHED_ERROR: "error",
}


class KVTransferState(enum.Enum):
    """Cross-stage KV-transfer lifecycle of one request (reference:
    core/sched/omni_ar_scheduler.py:84-136 trigger + :444-546 delayed free)."""

    NONE = "none"          # no transfer configured
    PENDING = "pending"    # trigger criteria not yet met
    ACTIVE = "active"      # triggered; blocks pinned until extraction ACK
    DONE = "done"          # runner ACKed extraction; blocks may be freed


@dataclass
class Request:
    request_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams = field(default_factory=SamplingParams)
    # single id or a list (multi-eos checkpoints stop on any)
    eos_token_id: Optional[int | list[int]] = None
    arrival_time: float = 0.0
    # duration clock twin of arrival_time: TTFT/queue-wait spans are
    # computed monotonic-to-monotonic (an NTP step mid-request must not
    # corrupt latency histograms); the wall-clock stamp above stays for
    # logs and trace timestamps only
    arrival_mono: float = 0.0
    # omni extensions (reference: request.py:14)
    prompt_embeds: Optional[np.ndarray] = None      # [S, hidden]
    additional_information: dict[str, Any] = field(default_factory=dict)
    external_req_id: Optional[str] = None
    # multimodal 3D-RoPE positions for the prompt ([3, S_prompt]) and the
    # generated-token delta (position of token p = p + delta); computed by
    # models/common/mrope.compute_mrope_positions (reference: mrope.py:25)
    mrope_positions: Optional[np.ndarray] = None
    mrope_delta: int = 0
    # deepstack multiscale visual features as sparse spans:
    # [(offset, [n_deep, T_item, hidden])] covering each visual item's
    # prompt positions; level i is added to the hidden states after
    # decoder layer i (reference: Qwen3-Omni thinker deepstack,
    # qwen3_omni_moe_thinker.py:177-178)
    deepstack_embeds: Optional[list] = None

    # end-to-end deadline as a monotonic expiry on THIS process's clock
    # (resilience/deadline.py: the orchestrator ships REMAINING budget
    # across process boundaries; each engine converts it back to its own
    # clock).  None = no deadline.  Enforced at scheduler admission and
    # on every engine step.
    deadline_ts: Optional[float] = None

    # ----- mutable engine state -----
    status: RequestStatus = RequestStatus.WAITING
    output_token_ids: list[int] = field(default_factory=list)
    # async pipelined engine: tokens sampled by a dispatched-but-not-yet-
    # retired step (device-resident, not in output_token_ids yet).  The
    # scheduler counts them when computing the decode remainder so it can
    # schedule the NEXT step before the token value reaches the host;
    # retire decrements, preemption/abort resets (the in-flight token is
    # discarded and greedily re-derived on recompute).
    num_inflight_tokens: int = 0
    # bumped on every preemption: a lagged async retire consumes its
    # token only when the generation recorded at dispatch still matches,
    # so a preempt-and-readmit while a step was in flight (possible
    # under unified batching, where waiting requests join pipelined
    # steps) can never resurrect the discarded token
    async_generation: int = 0
    # per-output-token logprob entries when sampling_params.logprobs is
    # set: {"logprob": float, "top_ids": [...], "top_logprobs": [...]}
    # (spec-decode multi-accept steps skip entries — the verify path
    # has no per-token sampling distribution to report)
    output_logprobs: list = field(default_factory=list)
    num_computed_tokens: int = 0
    kv_transfer: KVTransferState = KVTransferState.NONE
    # block-id snapshot taken at transfer trigger, truncated to seq len
    # (reference: omni_ar_scheduler.py:553-594)
    kv_transfer_block_ids: Optional[list[int]] = None
    kv_transfer_seq_len: int = 0
    multimodal_output: dict[str, Any] = field(default_factory=dict)
    # speculative-decode draft tokens proposed by the MTP head after the
    # last verified step (reference: talker MTP code predictor,
    # models/qwen3_omni/qwen3_omni_moe_code_predictor_mtp.py); consumed by
    # the next decode step's verify forward
    spec_draft_tokens: list[int] = field(default_factory=list)
    # streaming (async_chunk) intake: the prompt may still GROW via
    # engine.append_prompt_chunk — prefill chunks run as they arrive and
    # sampling is held until the final chunk lands (reference:
    # WAITING_FOR_CHUNK + OmniChunkTransferAdapter,
    # transfer_adapter/chunk_transfer_adapter.py:19)
    awaiting_chunks: bool = False
    # hidden states destined for the next stage (pooler_output payloads,
    # reference: gpu_ar_model_runner.py:525-568)
    pooled_hidden: Optional[np.ndarray] = None

    @property
    def tenant(self) -> str:
        """Multi-tenant metrics label, plumbed from request metadata
        (OpenAI header ``x-omni-tenant`` ->
        additional_information["tenant"]); "default" when absent.
        CLIENT input: sanitized to a bounded safe charset before it
        can reach a metrics label or ledger key."""
        from vllm_omni_tpu.metrics.stats import sanitize_tenant

        return sanitize_tenant(self.additional_information.get("tenant"))

    # lazily cached sanitized priority (the WFQ scheduler reads it in
    # per-schedule loops; re-parsing the raw header per access would be
    # avoidable hot-path work)
    _priority_cache: Optional[int] = field(default=None, repr=False)

    @property
    def priority(self) -> int:
        """Weighted-fair-queueing weight, plumbed from request metadata
        (OpenAI header ``x-omni-priority`` ->
        additional_information["priority"]); the neutral weight when
        absent.  CLIENT input: clamped to the bounded priority range
        exactly like the tenant label is sanitized.  Cached on first
        read — metadata is fixed by the time scheduling reads it."""
        if self._priority_cache is None:
            from vllm_omni_tpu.metrics.stats import sanitize_priority

            self._priority_cache = sanitize_priority(
                self.additional_information.get("priority"))
        return self._priority_cache

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return list(self.prompt_token_ids) + list(self.output_token_ids)

    @property
    def is_finished(self) -> bool:
        return self.status.is_finished

    @property
    def finish_reason(self) -> Optional[str]:
        return FINISH_REASON.get(self.status)

    def append_output_token(self, token_id: int) -> None:
        self.output_token_ids.append(token_id)

    def check_stop(self) -> bool:
        """Apply finish criteria after a new token; returns True if the
        request just finished (reference finish logic lives in vLLM's
        scheduler update_from_output, extended at omni_ar_scheduler.py:193)."""
        sp = self.sampling_params
        n_out = len(self.output_token_ids)
        if n_out == 0:
            return False
        last = self.output_token_ids[-1]
        if n_out >= sp.min_tokens:
            eos = self.eos_token_id
            eos_hit = (last in eos if isinstance(eos, (list, tuple))
                       else last == eos) if eos is not None else False
            if not sp.ignore_eos and eos_hit:
                self.status = RequestStatus.FINISHED_STOPPED
                return True
            if last in sp.stop_token_ids:
                self.status = RequestStatus.FINISHED_STOPPED
                return True
        if n_out >= sp.max_tokens:
            self.status = RequestStatus.FINISHED_LENGTH
            return True
        return False
