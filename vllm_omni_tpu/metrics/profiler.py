"""Profiling: jax.profiler traces fanned out across pipeline stages.

The TPU counterpart of the reference's torch-profiler RPC chain
(reference: Omni.start_profile/stop_profile entrypoints/omni.py:398-497 →
stage PROFILER_START/STOP tasks omni_stage.py:740-777 →
DiffusionEngine.start_profile collective_rpc diffusion_engine.py:197-313 →
per-rank TorchProfiler, diffusion/profiler/torch_profiler.py:17).

Here each stage owns one ``StageProfiler`` that wraps
``jax.profiler.start_trace/stop_trace``: traces land under
``{trace_dir}/stage_{id}`` in XPlane format (TensorBoard / xprof
readable).  Cross-process stages receive the same start/stop over their
command socket (entrypoints/stage_proc.py).
"""

from __future__ import annotations

import os
from typing import Optional

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


# jax.profiler admits ONE active trace per process; in-proc pipelines run
# every stage in the same process, so the first stage's trace covers them
# all and later starts are no-ops (process-disaggregated stages each own
# a process and trace independently)
_process_owner: Optional[int] = None


class StageProfiler:
    """Per-stage jax.profiler session (one active trace at a time)."""

    def __init__(self, stage_id: int):
        self.stage_id = stage_id
        self._active_dir: Optional[str] = None

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    def start(self, trace_dir: str) -> Optional[str]:
        """Begin an XPlane trace under ``trace_dir/stage_{id}``; returns
        the stage's trace directory.  Idempotent while active; a no-op
        when another in-process stage already owns the process trace."""
        global _process_owner
        if self._active_dir is not None:
            logger.warning(
                "stage %d: profiler already tracing to %s",
                self.stage_id, self._active_dir,
            )
            return self._active_dir
        if _process_owner is not None:
            logger.info(
                "stage %d: stage %d's trace already covers this process",
                self.stage_id, _process_owner,
            )
            return None
        import jax

        path = os.path.join(trace_dir, f"stage_{self.stage_id}")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        _process_owner = self.stage_id
        self._active_dir = path
        logger.info("stage %d: profiling -> %s", self.stage_id, path)
        return path

    def stop(self) -> Optional[str]:
        """End the trace; returns the directory the trace landed in (None
        if this stage owned no trace)."""
        global _process_owner
        if self._active_dir is None:
            return None
        import jax

        jax.profiler.stop_trace()
        _process_owner = None
        path, self._active_dir = self._active_dir, None
        logger.info("stage %d: profile written to %s", self.stage_id, path)
        return path
