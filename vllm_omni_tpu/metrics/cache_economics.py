"""omniscope: fleet KV-cache economics — the sensor half of
prefix-affinity routing (ROADMAP item 3).

Every engine's ``RadixPrefixIndex`` already knows exactly which
prefixes it holds and where their bytes live; the fleet knows nothing.
The cache-blind router therefore re-prefills prompt prefixes that a
sibling replica (or the remote tier) already paid for — invisible
work, because each engine's local ``prefix_hits`` counter looks
perfectly healthy while the FLEET hit rate collapses with replica
count.  This module is the scoreboard that makes the waste visible
before the affinity router (the needle-mover) exists:

- **digest aggregation**: each replica's bounded radix digest
  (``RadixPrefixIndex.digest`` — top-of-tree chain-hash fingerprints
  with O(1) per-subtree HBM token counts, hard node cap) lands here on
  a router stride.  Chain hashing makes cross-replica comparison
  trivial: equal keys mean equal whole prefixes, no token shipping.
- **dispatch regret**: at dispatch time the router asks
  ``note_dispatch`` what the chosen replica holds versus the best
  in-rotation peer.  The gap — tokens the chosen replica is about to
  prefill that a peer already held — is the *wasted re-prefill*
  ledger, the exact signal an affinity router minimizes.  Reasons
  split hot-peer (``peer_replica``) from parked-cold
  (``peer_cold_tier``) so the fix (route-to-peer vs restore-from-tier)
  is attributable per event.
- **fleet counters**: per-replica cumulative hit/prefill token
  counters are folded into monotone fleet totals (delta-accumulated,
  reset-tolerant, retained across replica replacement) so
  ``fleet_prefix_hit_tokens_total`` and the fleet hit-rate gauge stay
  counter-safe on /metrics.

Thread contract: the router thread (the single engine-stepping thread,
router.py's contract) calls ``observe_digest`` / ``note_dispatch`` /
``resolve_dispatch``; /metrics and /debug/cache snapshot from HTTP
threads via ``exposition`` / ``board`` — the per-instance lock guards
every table (LOCK_GUARDS manifest).  Hot-path discipline: dispatch
accounting is dict/set arithmetic over already-exported digests, zero
device syncs (omnilint OL2, HOT_PATHS manifest).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.kvcache.tiers import TIER_HBM

#: wasted re-prefill reasons (the {reason} label on
#: fleet_duplicate_prefill_tokens_total)
REASON_PEER_REPLICA = "peer_replica"
REASON_PEER_COLD_TIER = "peer_cold_tier"
REASONS = (REASON_PEER_REPLICA, REASON_PEER_COLD_TIER)

#: regret-ledger ring bound: enough to explain "why is hit rate low
#: right now", small enough that /debug/cache stays a cheap read
LEDGER_SIZE = 128

#: duplicated-prefix rows exported on the board
TOP_DUPLICATES = 10

#: affinity decision ring bound (the /debug/cache "affinity" block):
#: enough recent decisions to explain "why did this land there", small
#: enough that the board stays a cheap copy under the lock
AFFINITY_RING = 64

#: affinity dispatch outcomes (the {outcome} label on
#: router_affinity_dispatch_total)
AFFINITY_HIT = "hit"                  # affinity score chose a warm owner
AFFINITY_MISS = "miss"                # cold prefix: load + tenant-hash owner
AFFINITY_LOAD_OVERRIDE = "load_override"  # a warm hit existed but load won
AFFINITY_OUTCOMES = (AFFINITY_HIT, AFFINITY_MISS, AFFINITY_LOAD_OVERRIDE)


class CacheEconomics:
    """Fleet-wide cache board: replica digests in, regret signal out."""

    def __init__(self, *, ledger_size: int = LEDGER_SIZE,
                 bytes_per_token: int = 0):
        self._lock = traced(threading.Lock(), "CacheEconomics._lock")
        # replica_id -> the replica's latest radix digest (stored as
        # exported — digest() builds fresh dicts, nothing aliases the
        # live tree)
        self._digests: dict[str, dict] = {}
        # replica_id -> {key -> (depth, tier)} — the coverage lookup
        # note_dispatch walks, precomputed once per digest refresh
        self._cover: dict[str, dict[str, tuple[int, str]]] = {}
        # replica_id -> last observed cumulative (hit, prefill) token
        # counters, for delta accumulation into the fleet totals
        self._last: dict[str, tuple[int, int]] = {}
        # monotone fleet totals: survive replica replacement and
        # engine counter resets (deltas clamp at zero; a reset counts
        # from zero again instead of subtracting)
        self._fleet_hit_tokens = 0
        self._fleet_prefill_tokens = 0
        self._dup_by_reason: dict[str, int] = {r: 0 for r in REASONS}
        # request_id -> open dispatch entry (expected side recorded at
        # dispatch, actual side joined at first prefill output)
        self._pending: dict[str, dict] = {}
        self._ledger: deque = deque(maxlen=ledger_size)
        self._dispatches = 0
        self.bytes_per_token = int(bytes_per_token)
        # affinity decision ring + per-outcome counters (PR 19): every
        # affinity-scored placement leaves a bounded explanation here
        self._affinity_ring: deque = deque(maxlen=AFFINITY_RING)
        self._affinity_outcomes: dict[str, int] = {
            o: 0 for o in AFFINITY_OUTCOMES}
        # cluster KV fabric ledgers: prefix pages published to the
        # shared store and pages pulled back instead of re-prefilled
        self._fabric_published_tokens = 0
        self._fabric_publishes = 0
        self._fabric_pulled_tokens = 0
        self._fabric_pulls = 0
        self._fabric_pull_failures = 0

    # ------------------------------------------------------- digest side
    def observe_digest(self, replica_id: str, digest: dict,
                       hit_tokens: int = 0,
                       prefill_tokens: int = 0) -> None:
        """Fold one replica's refreshed digest + cumulative hit/prefill
        token counters into the board (router thread, on a stride)."""
        cover: dict[str, tuple[int, str]] = {}
        for n in digest.get("nodes", ()):
            cover[n["key"]] = (int(n["depth"]), str(n["tier"]))
        with self._lock:
            self._digests[replica_id] = digest
            self._cover[replica_id] = cover
            last_hit, last_prefill = self._last.get(replica_id, (0, 0))
            hit = int(hit_tokens)
            prefill = int(prefill_tokens)
            # delta-accumulate; a counter that went backwards is a
            # replaced/reset engine — count its new value from zero
            self._fleet_hit_tokens += (
                hit - last_hit if hit >= last_hit else hit)
            self._fleet_prefill_tokens += (
                prefill - last_prefill if prefill >= last_prefill
                else prefill)
            self._last[replica_id] = (hit, prefill)

    def forget_replica(self, replica_id: str) -> None:
        """Drop a reaped replica's digest; its already-accumulated
        fleet deltas stay (totals are monotone by construction)."""
        with self._lock:
            self._digests.pop(replica_id, None)
            self._cover.pop(replica_id, None)
            self._last.pop(replica_id, None)

    def invalidate_digest(self, replica_id: str) -> None:
        """Drop a replica's digest WITHOUT dropping its counter
        baseline.  The ejection path: an ejected replica's coverage
        must stop steering affinity immediately (it may come back with
        a cold cache, or not at all), but its cumulative hit/prefill
        baseline must survive re-admission — ``forget_replica`` here
        would reset ``_last`` and double-count the replica's lifetime
        counters into the fleet totals on the next observe."""
        with self._lock:
            self._digests.pop(replica_id, None)
            self._cover.pop(replica_id, None)

    def expected_hits(self, replica_ids: Sequence[str],
                      keys: Sequence[str]) -> dict[str, tuple[int, int]]:
        """Affinity scoring probe: for each candidate replica, the
        (covered pages, covered tokens) its current digest promises for
        ``keys``.  One lock hold for the whole candidate set — the
        dispatch hot path calls this once per request.  Replicas with
        no digest (cold, ejected, never exported) score (0, 0)."""
        with self._lock:
            out: dict[str, tuple[int, int]] = {}
            for rid in replica_ids:
                cover = self._cover.get(rid)
                if not cover:
                    out[rid] = (0, 0)
                    continue
                pages, _ = self._coverage(cover, keys)
                out[rid] = (pages, pages * self._page_size_locked(rid))
            return out

    def key_src(self, key: str) -> str:
        """Provenance label for a fabric pull of ``key``: ``peer`` when
        some live replica's digest advertises it HBM-resident, ``cold``
        when only a parked tier (or no digest at all — the publisher
        may have evicted since) backs it."""
        with self._lock:
            for cover in self._cover.values():
                hit = cover.get(key)
                if hit is not None and hit[1] == TIER_HBM:
                    return "peer"
            return "cold"

    def replica_heat(self) -> dict[str, int]:
        """Per-replica cache heat: HBM-resident tokens promised by each
        live digest (``hbm_tokens`` summed over leaf-most nodes would
        double-count ancestors, so sum per-node own pages instead:
        every digest node is one page).  The control plane subtracts
        this from donor scores so scale-down/re-role stops evicting the
        fleet's hottest caches."""
        with self._lock:
            heat: dict[str, int] = {}
            for rid, cover in self._cover.items():
                page_size = self._page_size_locked(rid)
                heat[rid] = page_size * sum(
                    1 for _, tier in cover.values() if tier == TIER_HBM)
            return heat

    # ----------------------------------------------------- affinity side
    def note_affinity(self, doc: dict) -> None:
        """Record one affinity routing decision (bounded ring + outcome
        counter).  ``doc`` carries outcome/chosen/score breakdowns from
        the router; the ring is the /debug/cache explanation surface."""
        outcome = doc.get("outcome")
        with self._lock:
            self._affinity_ring.append(doc)
            if outcome in self._affinity_outcomes:
                self._affinity_outcomes[outcome] += 1

    def note_publish(self, tokens: int) -> None:
        """Meter one prefix-page publication into the cluster fabric."""
        with self._lock:
            self._fabric_publishes += 1
            self._fabric_published_tokens += int(tokens)

    def note_pull(self, tokens: int, ok: bool = True) -> None:
        """Meter one fabric pull attempt (tokens admitted on success;
        a failure degrades to recompute — the lost-payload contract).
        Pulled tokens count toward the fleet hit ledger: they were
        served from fleet cache instead of re-prefilled, which is
        exactly what the hit-rate gauge prices.  No double count — a
        pull injects pages the local radix did NOT hold, so the same
        tokens never also arrive through a replica digest delta."""
        with self._lock:
            if ok:
                self._fabric_pulls += 1
                self._fabric_pulled_tokens += int(tokens)
                self._fleet_hit_tokens += int(tokens)
            else:
                self._fabric_pull_failures += 1

    # ----------------------------------------------------- dispatch side
    @staticmethod
    def _coverage(cover: dict[str, tuple[int, str]],
                  keys: Sequence[str]) -> tuple[int, str]:
        """(pages covered, tier of the deepest covering node).  Chain
        hashing means key membership at position i implies the whole
        i+1-page prefix matches — the walk only has to find the
        deepest hit, and a miss at depth d ends the chain (a digest
        never holds a child without its parent)."""
        pages, tier = 0, TIER_HBM
        for i, key in enumerate(keys):
            hit = cover.get(key)
            if hit is None:
                break
            pages = i + 1
            tier = hit[1]
        return pages, tier

    def note_dispatch(self, replica_id: str, keys: Sequence[str],
                      tenant: Optional[str] = None,
                      request_id: Optional[str] = None) -> dict:
        """Score one routing decision against the current digests.

        ``keys`` are the request's chain-hash page keys
        (``kvcache.radix.chain_page_keys``).  Returns the expected-hit
        doc (journey span args + attribution amount for the caller):
        ``expected_hit_tokens`` on the chosen replica,
        ``peer_hit_tokens`` on the best in-rotation peer, and
        ``wasted_tokens`` — the re-prefill regret — with its reason.
        Digests are best-effort snapshots (stride-refreshed, node-
        capped), so coverage is a LOWER bound on what replicas hold;
        regret is correspondingly conservative."""
        with self._lock:
            self._dispatches += 1
            chosen = self._cover.get(replica_id, {})
            local_pages, _ = self._coverage(chosen, keys)
            peer_pages, peer_tier, best_peer = 0, TIER_HBM, None
            for rid, cover in self._cover.items():
                if rid == replica_id:
                    continue
                pages, tier = self._coverage(cover, keys)
                if pages > peer_pages:
                    peer_pages, peer_tier, best_peer = pages, tier, rid
            page_size = self._page_size_locked(replica_id, best_peer)
            wasted_pages = max(peer_pages - local_pages, 0)
            wasted = wasted_pages * page_size
            reason = (REASON_PEER_REPLICA if peer_tier == TIER_HBM
                      else REASON_PEER_COLD_TIER)
            if wasted > 0:
                self._dup_by_reason[reason] = (
                    self._dup_by_reason.get(reason, 0) + wasted)
            doc = {
                "request_id": request_id,
                "tenant": tenant,
                "replica": replica_id,
                "expected_hit_tokens": local_pages * page_size,
                "peer_hit_tokens": peer_pages * page_size,
                "best_peer": best_peer,
                "wasted_tokens": wasted,
                "reason": reason if wasted > 0 else None,
            }
            if request_id is not None:
                self._pending[request_id] = doc
            return doc

    def resolve_dispatch(self, request_id: Optional[str],
                         actual_hit_tokens: int) -> Optional[dict]:
        """Join the actual prefix hit (the engine's per-request count)
        onto the open dispatch entry and retire it into the regret
        ledger.  Returns the completed entry (journey annotation), or
        None when no entry is open for ``request_id``."""
        if request_id is None:
            return None
        with self._lock:
            doc = self._pending.pop(request_id, None)
            if doc is None:
                return None
            doc["actual_hit_tokens"] = int(actual_hit_tokens)
            self._ledger.append(doc)
            return doc

    def abandon_dispatch(self, request_id: Optional[str]) -> None:
        """Drop an open entry whose request died before prefill output
        (failover/shed) so the pending table stays bounded."""
        if request_id is None:
            return
        with self._lock:
            self._pending.pop(request_id, None)

    # --------------------------------------------------------- rendering
    def _page_size_locked(self, *replica_ids) -> int:
        """Best page size for token math (caller holds the lock):
        prefer the named replicas' digests, fall back to any."""
        for rid in replica_ids:
            d = self._digests.get(rid)
            if d is not None:
                return int(d.get("page_size", 1)) or 1
        for d in self._digests.values():
            return int(d.get("page_size", 1)) or 1
        return 1

    def _duplicates_locked(self) -> tuple[int, list[dict]]:
        """(duplicate tokens across replicas, top duplicated rows).
        A key held by k replicas means k-1 redundant page copies —
        summed over every duplicated key that is the cross-replica
        duplicate-prefix bill.  Rows sort most-replicated first, then
        shallowest (prefix heads), then key — deterministic for the
        hand-oracled fixture test."""
        seen: dict[str, dict] = {}
        for rid, cover in self._cover.items():
            page_size = self._page_size_locked(rid)
            for key, (depth, tier) in cover.items():
                row = seen.get(key)
                if row is None:
                    seen[key] = {"key": key, "depth": depth,
                                 "replicas": [rid], "tiers": {tier: 1},
                                 "page_size": page_size}
                else:
                    row["replicas"].append(rid)
                    row["tiers"][tier] = row["tiers"].get(tier, 0) + 1
        dup_tokens = 0
        rows = []
        for row in seen.values():
            k = len(row["replicas"])
            if k < 2:
                continue
            tokens = (k - 1) * row["page_size"]
            dup_tokens += tokens
            rows.append({
                "key": row["key"], "depth": row["depth"],
                "replicas": sorted(row["replicas"]),
                "tiers": dict(sorted(row["tiers"].items())),
                "duplicate_tokens": tokens,
                "duplicate_bytes": tokens * self.bytes_per_token,
            })
        rows.sort(key=lambda r: (-len(r["replicas"]), r["depth"],
                                 r["key"]))
        return dup_tokens, rows

    def _hit_rate_locked(self) -> float:
        total = self._fleet_hit_tokens + self._fleet_prefill_tokens
        return self._fleet_hit_tokens / total if total else 0.0

    def exposition(self) -> dict:
        """Compact block for the /metrics disagg render: fleet
        hit/prefill counters, hit-rate gauge, per-reason duplicate
        counters, per-replica digest node gauges."""
        with self._lock:
            dup_tokens, _ = self._duplicates_locked()
            return {
                "fleet_hit_tokens": self._fleet_hit_tokens,
                "fleet_prefill_tokens": self._fleet_prefill_tokens,
                "hit_rate": round(self._hit_rate_locked(), 6),
                "duplicate_by_reason": dict(self._dup_by_reason),
                "duplicate_prefix_tokens": dup_tokens,
                "digest_nodes": {
                    rid: len(d.get("nodes", ()))
                    for rid, d in sorted(self._digests.items())},
            }

    def board(self) -> dict:
        """The /debug/cache fleet board: per-replica digest summaries,
        top duplicated prefixes, the regret ledger, fleet totals.
        Copies out every mutable structure under the lock (C-level
        list/dict constructions — the debugz torn-read contract)."""
        with self._lock:
            dup_tokens, top = self._duplicates_locked()
            replicas = {}
            for rid in sorted(self._digests):
                d = self._digests[rid]
                hit, prefill = self._last.get(rid, (0, 0))
                replicas[rid] = {
                    "nodes": len(d.get("nodes", ())),
                    "node_cap": d.get("node_cap"),
                    "truncated": bool(d.get("truncated")),
                    "hbm_pages": d.get("hbm_pages"),
                    "page_size": d.get("page_size"),
                    "clock": d.get("clock"),
                    "hit_tokens": hit,
                    "prefill_tokens": prefill,
                }
            return {
                "enabled": True,
                "replicas": replicas,
                "fleet": {
                    "hit_tokens": self._fleet_hit_tokens,
                    "prefill_tokens": self._fleet_prefill_tokens,
                    "hit_rate": round(self._hit_rate_locked(), 6),
                    "dispatches": self._dispatches,
                    "duplicate_by_reason": dict(self._dup_by_reason),
                    "duplicate_prefix_tokens": dup_tokens,
                    "duplicate_prefix_bytes":
                        dup_tokens * self.bytes_per_token,
                    "bytes_per_token": self.bytes_per_token,
                },
                "top_duplicates": top[:TOP_DUPLICATES],
                "regret_ledger": list(self._ledger),
                "pending_dispatches": len(self._pending),
                "affinity": {
                    "ring": list(self._affinity_ring),
                    "outcomes": dict(self._affinity_outcomes),
                },
                "fabric": {
                    "publishes": self._fabric_publishes,
                    "published_tokens": self._fabric_published_tokens,
                    "pulls": self._fabric_pulls,
                    "pulled_tokens": self._fabric_pulled_tokens,
                    "pull_failures": self._fabric_pull_failures,
                },
            }


__all__ = [
    "CacheEconomics", "REASON_PEER_REPLICA", "REASON_PEER_COLD_TIER",
    "REASONS", "LEDGER_SIZE", "TOP_DUPLICATES", "AFFINITY_RING",
    "AFFINITY_HIT", "AFFINITY_MISS", "AFFINITY_LOAD_OVERRIDE",
    "AFFINITY_OUTCOMES",
]
