"""Pipeline metrics: per-stage / per-edge / end-to-end aggregation.

Behavioral port of the reference's metrics layer (reference:
vllm_omni/metrics/stats.py — StageRequestStats:28, StageStats:18,
TransferEdgeStats:59, RequestE2EStats:75, OrchestratorAggregator:115 with
per-stage TPS + E2E latency aggregation and optional ``*.stats.jsonl``
output wired in entrypoints/omni.py:692-697,759-791).
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Optional


@dataclass
class StageRequestStats:
    request_id: str
    stage_id: int
    tokens_in: int = 0
    tokens_out: int = 0
    gen_ms: float = 0.0
    rx_bytes: int = 0
    rx_decode_ms: float = 0.0
    in_flight_ms: float = 0.0


@dataclass
class StageStats:
    stage_id: int
    num_requests: int = 0
    tokens_in: int = 0
    tokens_out: int = 0
    gen_ms_total: float = 0.0

    @property
    def tps(self) -> float:
        return (self.tokens_out / (self.gen_ms_total / 1e3)
                if self.gen_ms_total else 0.0)


@dataclass
class TransferEdgeStats:
    from_stage: int
    to_stage: int
    num_transfers: int = 0
    bytes_total: int = 0
    transfer_ms_total: float = 0.0


@dataclass
class RequestE2EStats:
    request_id: str
    arrival_ts: float
    finish_ts: float = 0.0

    @property
    def e2e_ms(self) -> float:
        return max(0.0, (self.finish_ts - self.arrival_ts) * 1e3)


def nearest_rank_pct(xs: list, p: float) -> float:
    """Nearest-rank percentile over a sequence: index ceil(p*n)-1.
    (int(p*n) would bias toward the max — p50 of [10, 20] must be 10.)
    The int(p*100*n) form sidesteps float error in p itself (0.99*n)."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = max(0, -(-int(p * 100 * len(xs)) // 100) - 1)
    return xs[min(len(xs) - 1, idx)]


class OrchestratorAggregator:
    """``stats_path`` is a path *prefix*: per-stage request records stream
    to ``{prefix}.stage{N}.stats.jsonl`` and E2E records to
    ``{prefix}.e2e.stats.jsonl`` (reference: the per-stage ``*.stats.jsonl``
    files of metrics/stats.py:115, wired at omni.py:692-697)."""

    def __init__(self, num_stages: int, stats_path: Optional[str] = None,
                 window: int = 4096):
        self.stages = {i: StageStats(stage_id=i) for i in range(num_stages)}
        self.edges: dict[tuple[int, int], TransferEdgeStats] = {}
        # in-flight only: finished entries are EVICTED (a long-running
        # server harvests stats every heartbeat — unbounded history would
        # grow memory forever and make summary() sort a lifetime of
        # latencies on the engine thread)
        self.requests: dict[str, RequestE2EStats] = {}
        self.per_request: deque = deque(maxlen=window)
        self._recent_e2e_ms: deque = deque(maxlen=window)
        self.num_finished = 0
        self._stats_path = stats_path

    def _append_jsonl(self, suffix: str, record: dict) -> None:
        with open(f"{self._stats_path}.{suffix}.stats.jsonl", "a") as f:
            f.write(json.dumps(record) + "\n")

    # ------------------------------------------------------------ recording
    def record_arrival(self, request_id: str) -> None:
        self.requests[request_id] = RequestE2EStats(
            request_id=request_id, arrival_ts=time.time()
        )

    def record_finish(self, request_id: str) -> None:
        r = self.requests.pop(request_id, None)
        if r is None:
            return
        r.finish_ts = time.time()
        self.num_finished += 1
        self._recent_e2e_ms.append(r.e2e_ms)
        if self._stats_path:
            self._append_jsonl("e2e", {
                "request_id": r.request_id,
                "arrival_ts": r.arrival_ts,
                "finish_ts": r.finish_ts,
                "e2e_ms": round(r.e2e_ms, 3),
            })

    def record_stage_request(self, s: StageRequestStats) -> None:
        self.per_request.append(s)
        st = self.stages.setdefault(s.stage_id, StageStats(stage_id=s.stage_id))
        st.num_requests += 1
        st.tokens_in += s.tokens_in
        st.tokens_out += s.tokens_out
        st.gen_ms_total += s.gen_ms
        if self._stats_path:
            self._append_jsonl(f"stage{s.stage_id}", asdict(s))

    def record_transfer(self, from_stage: int, to_stage: int,
                        nbytes: int, ms: float) -> None:
        key = (from_stage, to_stage)
        edge = self.edges.setdefault(
            key, TransferEdgeStats(from_stage=from_stage, to_stage=to_stage)
        )
        edge.num_transfers += 1
        edge.bytes_total += nbytes
        edge.transfer_ms_total += ms

    # ------------------------------------------------------------- summary
    def summary(self) -> dict:
        e2e = list(self._recent_e2e_ms)

        def pct(p):
            return nearest_rank_pct(e2e, p)

        return {
            "stages": {
                i: {
                    "num_requests": st.num_requests,
                    "tokens_in": st.tokens_in,
                    "tokens_out": st.tokens_out,
                    "tps": round(st.tps, 2),
                }
                for i, st in self.stages.items()
            },
            "edges": {
                f"{k[0]}->{k[1]}": {
                    "transfers": e.num_transfers,
                    "bytes": e.bytes_total,
                    "ms": round(e.transfer_ms_total, 2),
                }
                for k, e in self.edges.items()
            },
            "e2e": {
                "num_finished": self.num_finished,
                # percentiles over the recent window, not lifetime
                "window": len(e2e),
                "p50_ms": round(pct(0.50), 2),
                "p90_ms": round(pct(0.90), 2),
                "p99_ms": round(pct(0.99), 2),
            },
        }
