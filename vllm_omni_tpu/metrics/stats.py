"""Pipeline metrics: per-stage / per-edge / end-to-end aggregation.

Behavioral port of the reference's metrics layer (reference:
vllm_omni/metrics/stats.py — StageRequestStats:28, StageStats:18,
TransferEdgeStats:59, RequestE2EStats:75, OrchestratorAggregator:115 with
per-stage TPS + E2E latency aggregation and optional ``*.stats.jsonl``
output wired in entrypoints/omni.py:692-697,759-791).
"""

from __future__ import annotations

import bisect
import json
import re
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Optional

from vllm_omni_tpu.analysis.runtime import traced


@dataclass
class StageRequestStats:
    request_id: str
    stage_id: int
    tokens_in: int = 0
    tokens_out: int = 0
    gen_ms: float = 0.0
    rx_bytes: int = 0
    rx_decode_ms: float = 0.0
    in_flight_ms: float = 0.0


@dataclass
class StageStats:
    stage_id: int
    num_requests: int = 0
    tokens_in: int = 0
    tokens_out: int = 0
    gen_ms_total: float = 0.0

    @property
    def tps(self) -> float:
        return (self.tokens_out / (self.gen_ms_total / 1e3)
                if self.gen_ms_total else 0.0)


@dataclass
class TransferEdgeStats:
    from_stage: int
    to_stage: int
    num_transfers: int = 0
    bytes_total: int = 0
    transfer_ms_total: float = 0.0


@dataclass
class RequestE2EStats:
    request_id: str
    # wall-clock arrival, kept for LOGS only (jsonl records, dashboards
    # correlating against external timestamps) — never for durations
    arrival_ts: float
    finish_ts: float = 0.0
    # duration clock: monotonic stamps.  An NTP step mid-request would
    # corrupt a wall-clock difference (negative or wildly inflated
    # latencies poisoning the histograms); time.monotonic() is immune.
    arrival_mono: float = 0.0
    finish_mono: float = 0.0

    @property
    def e2e_ms(self) -> float:
        return max(0.0, (self.finish_mono - self.arrival_mono) * 1e3)


# Prometheus-style latency buckets (ms).  Wide on purpose: one set serves
# TTFT (tens of ms on-chip, seconds under load) and ITL (single-digit ms)
# — per-metric tuning would make cross-deployment dashboards incomparable.
LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
                      60000.0)

# Token-count buckets (engine_step_batched_tokens): powers of two up to
# the largest plausible per-step token budget.
TOKEN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0, 4096.0, 8192.0)

# KV tier-restore latency buckets, in SECONDS: sub-ms for host-tier
# hits on fast tunnels up to tens of seconds for big runs over the
# measured ~0.15 GB/s host<->HBM path (docs/kv_cache.md).
KV_RESTORE_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Histogram:
    """Fixed-bucket histogram with a recent-value window for percentiles.

    Buckets follow Prometheus semantics (``snapshot()`` returns CUMULATIVE
    counts per upper bound, plus sum/count) so the exposition layer
    (metrics/prometheus.py) can render ``_bucket``/``_sum``/``_count``
    series directly.  Percentiles come from a bounded recent window (the
    same recency stance as OrchestratorAggregator — a lifetime of
    latencies would both grow memory and bury regressions under history).

    Thread-safe: the engine thread observes while the /metrics HTTP
    thread snapshots.
    """

    def __init__(self, buckets=LATENCY_BUCKETS_MS, window: int = 4096):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: deque = deque(maxlen=window)
        self._lock = traced(threading.Lock(), "Histogram._lock")

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``value`` ``n`` times (n>1 amortizes per-token metrics
        a multi-step decode window emits in one host round trip)."""
        if n <= 0:
            return
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += n
            self._sum += value * n
            self._count += n
            # the window weights repeated observations once per call —
            # enough for percentile math without O(n) appends
            self._window.append(value)

    def percentile(self, p: float) -> float:
        with self._lock:
            xs = list(self._window)
        return nearest_rank_pct(xs, p)

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
            xs = list(self._window)
        cum = 0
        cumulative = []
        for le, n in zip(self.buckets + (float("inf"),), counts):
            cum += n
            cumulative.append([le, cum])
        return {
            "buckets": cumulative,
            "sum": round(s, 3),
            "count": c,
            "p50": round(nearest_rank_pct(xs, 0.50), 3),
            "p90": round(nearest_rank_pct(xs, 0.90), 3),
            "p99": round(nearest_rank_pct(xs, 0.99), 3),
        }


# label value for requests that carry no tenant metadata (the OpenAI
# server stamps ``x-omni-tenant`` into additional_information["tenant"])
DEFAULT_TENANT = "default"
# tenant values past the cardinality cap collapse into this bucket —
# the tenant label is CLIENT input, and a client inventing a fresh
# tenant per request must not grow engine memory or /metrics series
# without bound
OVERFLOW_TENANT = "other"
MAX_TENANT_SERIES = 32

_TENANT_BAD_CHARS = re.compile(r"[^A-Za-z0-9_.:\-]")


def sanitize_tenant(raw) -> str:
    """Client tenant -> safe, bounded label value: charset restricted
    to [A-Za-z0-9_.:-] (anything else becomes "_"), capped at 64
    chars, empty/missing -> DEFAULT_TENANT.  Exposition-side escaping
    exists too; sanitizing at the source keeps ledger keys, JSON
    snapshots, and log lines clean as well."""
    if not raw:
        return DEFAULT_TENANT
    s = _TENANT_BAD_CHARS.sub("_", str(raw))[:64]
    return s or DEFAULT_TENANT


def cap_tenant(tenant: str, known: "set[str] | dict") -> str:
    """Collapse a NEW tenant into OVERFLOW_TENANT once ``known``
    already tracks MAX_TENANT_SERIES distinct tenants."""
    if tenant in known or len(known) < MAX_TENANT_SERIES:
        return tenant
    return OVERFLOW_TENANT


# weighted-fair-queueing priority (docs/control_plane.md): an integer
# weight in [MIN_PRIORITY, MAX_PRIORITY] — a priority-8 tenant gets 8x
# a priority-1 tenant's share of the admission quantum under overload.
# DEFAULT_PRIORITY is the neutral weight every request without explicit
# metadata gets, so deployments that never send x-omni-priority keep
# exact FCFS-equivalent behavior (equal weights degenerate DRR to
# round-robin over tenants).
MIN_PRIORITY = 1
MAX_PRIORITY = 8
DEFAULT_PRIORITY = 4


def sanitize_priority(raw) -> int:
    """Client priority -> bounded int weight: parsed leniently (ints,
    numeric strings, floats truncate), clamped to
    [MIN_PRIORITY, MAX_PRIORITY]; anything unparseable or missing ->
    DEFAULT_PRIORITY.  CLIENT input (the x-omni-priority header) — it
    must never raise and never exceed the clamp, exactly the
    hostile-input stance of ``sanitize_tenant``."""
    if raw is None:
        return DEFAULT_PRIORITY
    try:
        f = float(str(raw).strip())
    except (TypeError, ValueError):
        return DEFAULT_PRIORITY
    if f != f:  # NaN parses as a float but orders with nothing
        return DEFAULT_PRIORITY
    # clamp in FLOAT space before truncating: "inf"/"1e400" parse fine
    # and int() on an infinity raises OverflowError — an out-of-range
    # value must clamp, never raise (one hostile header would
    # otherwise crash schedule() for every tenant)
    return int(max(float(MIN_PRIORITY), min(float(MAX_PRIORITY), f)))


@dataclass
class TenantSLOStats:
    """Per-tenant SLO attainment + goodput accounting over finished
    requests.  "Met" means every CONFIGURED target held: TTFT <= target
    and TPOT <= target (a missing target always passes; a <=1-token
    request has no TPOT and passes that leg).  Exactly-at-target counts
    as met — the SLO is an upper bound, not a strict one."""

    finished: int = 0        # successfully finished requests
    met: int = 0             # finished requests inside every SLO target
    tokens: int = 0          # output tokens over all finished requests
    goodput_tokens: int = 0  # output tokens of SLO-met requests only

    @property
    def attainment(self) -> float:
        """met / finished; 0.0 with zero completions (an idle tenant
        reports no attainment rather than a fake-perfect 1.0)."""
        if self.finished <= 0:
            return 0.0
        return self.met / self.finished


class DeltaRing:
    """Bounded ring of (monotonic_t, cumulative-sample) pairs for
    windowed counter deltas — the substrate burn-rate alerting
    (metrics/alerts.py) computes real windows from.

    Lifetime-cumulative ratios hide incidents: after a week of uptime,
    a minute of 100% errors moves ``slo_attainment_ratio`` by noise.
    Sampling the cumulative counters on a cadence and differencing
    against the sample closest to ``now - window_s`` recovers the
    WINDOWED rate.  All stamps are ``time.monotonic()`` — the same
    NTP-immunity stance as the PR 7 duration clocks (an NTP step
    mid-window must never fabricate or swallow a burn).

    Samples are plain dicts of floats; ``window_delta`` returns both
    the delta and the actual span covered (early in a process's life a
    1h window is backed by whatever history exists — the caller
    normalizes rates by the REAL span, never the nominal one).
    Not thread-safe: one owner samples and reads (the alert engine's
    evaluation thread).
    """

    def __init__(self, horizon_s: float, max_samples: int = 720,
                 clock=time.monotonic):
        self.horizon_s = float(horizon_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._samples: deque = deque()

    def sample(self, values: dict) -> None:
        now = self._clock()
        self._samples.append((now, dict(values)))
        # keep ONE sample at-or-beyond the horizon so a full-window
        # delta always has a baseline to difference against
        while (len(self._samples) > 2
               and (now - self._samples[1][0] >= self.horizon_s
                    or len(self._samples) > self.max_samples)):
            self._samples.popleft()

    def window_delta(self, window_s: float, key: str
                     ) -> tuple[float, float]:
        """(delta, span_s) of ``key`` over the trailing ``window_s``:
        newest sample minus the newest sample at least ``window_s``
        old (falling back to the oldest available).  (0, 0) before two
        samples exist."""
        if len(self._samples) < 2:
            return 0.0, 0.0
        t_new, new = self._samples[-1]
        base_t, base = self._samples[0]
        for t, s in self._samples:
            if t_new - t >= window_s:
                base_t, base = t, s
            else:
                break
        return (float(new.get(key, 0.0)) - float(base.get(key, 0.0)),
                t_new - base_t)


def burn_rate(d_bad: float, d_total: float, budget: float) -> float:
    """Error-budget burn rate over one window: the window's bad
    fraction divided by the allowed bad fraction (``budget`` =
    1 - SLO objective).  1.0 = exactly on budget; 14.4 = burning a
    30-day budget in ~2 days (the classic fast-page threshold).  An
    empty window burns nothing — no traffic is not an SLO violation."""
    if d_total <= 0:
        return 0.0
    return (max(d_bad, 0.0) / d_total) / max(budget, 1e-9)


class EngineStepMetrics:
    """Step-level engine gauges/counters/histograms, sampled from
    ``LLMEngine.step()`` (the vLLM-core Stats/StatLogger analogue):
    scheduler depth gauges, token counters, and the request-latency
    histograms the serving SLOs are written against — TTFT (arrival to
    first output token), TPOT (per-output-token time over a finished
    request, excluding the first token), ITL (inter-token latency
    between consecutive host-visible emissions).
    """

    def __init__(self):
        self.ttft_ms = Histogram()
        self.tpot_ms = Histogram()
        self.itl_ms = Histogram()
        self.step_ms = Histogram()
        # step-phase breakdown: host-side work vs. device-bound wait per
        # step, plus how much of the host work ran while a dispatched
        # step was still computing (the async pipeline's win — see
        # docs/async_engine.md; sync steps overlap nothing)
        self.host_ms = Histogram()
        self.device_ms = Histogram()
        # tokens per step (REAL tokens computed, before padding) — with
        # the useful/padded counters below this makes the unified
        # ragged path's padding win measurable (docs/ragged_batching.md)
        self.batched_tokens = Histogram(buckets=TOKEN_BUCKETS)
        # per-request KV tier restore latency (fetch + inject), seconds
        # — the cold path must earn its transfers (docs/kv_cache.md)
        self.kv_restore_s = Histogram(buckets=KV_RESTORE_BUCKETS_S)
        # arrival -> FIRST time scheduled, per request (the queueing
        # component the serving curve bends on)
        self.queue_wait_ms = Histogram()
        # SLO targets (None = unconfigured leg always passes) + the
        # per-tenant attainment/goodput ledger they gate
        self.slo_ttft_ms: Optional[float] = None
        self.slo_tpot_ms: Optional[float] = None
        self.tenants: dict[str, TenantSLOStats] = {
            DEFAULT_TENANT: TenantSLOStats()}
        # per-phase saturation (last schedule's fractions): how close
        # each capacity axis ran to its ceiling — the knee of the
        # serving curve shows up here before latency explodes
        self.saturation: dict[str, float] = {
            "prefill": 0.0, "decode": 0.0, "seats": 0.0}
        # gauges (last sampled values)
        self.num_waiting = 0
        self.num_running = 0
        # counters
        self.num_steps = 0
        self.tokens_generated = 0
        self.prefill_tokens = 0
        self.host_ms_total = 0.0
        self.overlapped_host_ms_total = 0.0
        # padding efficiency: real tokens vs. padded device rows across
        # every dispatch (bucketed split path vs. token-packed unified)
        self.useful_tokens_total = 0
        self.padded_tokens_total = 0

    def on_schedule(self, waiting: int, running: int) -> None:
        self.num_waiting = waiting
        self.num_running = running

    def on_saturation(self, prefill: float, decode: float,
                      seats: float) -> None:
        """Fractions of this step's capacity ceilings actually used:
        prefill/decode tokens over the step token budget, running seats
        over max_num_seqs (sampled per schedule; last value wins)."""
        self.saturation["prefill"] = round(min(max(prefill, 0.0), 1.0), 4)
        self.saturation["decode"] = round(min(max(decode, 0.0), 1.0), 4)
        self.saturation["seats"] = round(min(max(seats, 0.0), 1.0), 4)

    def on_request_slo(self, tenant: Optional[str], ttft_ms: float,
                       tpot_ms: Optional[float], n_tokens: int) -> None:
        """Account one successfully finished request against the SLO
        targets.  ``tpot_ms`` is None for <=1-token requests (no
        per-output-token time exists); that leg passes.  Exactly at a
        target counts as met (<=)."""
        t = cap_tenant(sanitize_tenant(tenant), self.tenants)
        st = self.tenants.setdefault(t, TenantSLOStats())
        met = True
        if self.slo_ttft_ms is not None and ttft_ms > self.slo_ttft_ms:
            met = False
        if (met and self.slo_tpot_ms is not None and tpot_ms is not None
                and tpot_ms > self.slo_tpot_ms):
            met = False
        st.finished += 1
        st.tokens += n_tokens
        if met:
            st.met += 1
            st.goodput_tokens += n_tokens

    def on_step(self, step_ms: float, new_tokens: int,
                prefill_tokens: int, host_ms: Optional[float] = None,
                device_ms: Optional[float] = None,
                overlapped_host_ms: float = 0.0) -> None:
        self.num_steps += 1
        self.tokens_generated += new_tokens
        self.prefill_tokens += prefill_tokens
        self.step_ms.observe(step_ms)
        if host_ms is not None:
            self.host_ms.observe(host_ms)
            self.host_ms_total += host_ms
            self.overlapped_host_ms_total += min(overlapped_host_ms,
                                                 host_ms)
        if device_ms is not None:
            self.device_ms.observe(device_ms)

    def on_padding(self, useful: int, padded: int) -> None:
        """Per-step device-row accounting: ``useful`` real tokens rode
        ``padded`` padded rows (engine samples the runner's counters
        around each dispatch/execute)."""
        if padded <= 0:
            return
        self.useful_tokens_total += useful
        self.padded_tokens_total += padded
        self.batched_tokens.observe(float(useful))

    def slo_totals(self) -> dict:
        """Cumulative SLO counters summed over tenants — the shape the
        alert engine's :class:`DeltaRing` samples so burn rates come
        from real windows instead of the lifetime attainment ratio
        (which a week of uptime renders incident-blind)."""
        finished = met = tokens = goodput = 0
        for st in self.tenants.values():
            finished += st.finished
            met += st.met
            tokens += st.tokens
            goodput += st.goodput_tokens
        return {
            "finished": finished,
            "met": met,
            "bad": finished - met,
            "tokens": tokens,
            "goodput_tokens": goodput,
        }

    @property
    def padding_efficiency(self) -> float:
        """useful / padded over all dispatches (1.0 = zero padding)."""
        if self.padded_tokens_total <= 0:
            return 0.0
        return self.useful_tokens_total / self.padded_tokens_total

    @property
    def overlap_ratio(self) -> float:
        """Fraction of host-side step work performed while a dispatched
        device step was in flight (0 for purely synchronous serving)."""
        if self.host_ms_total <= 0.0:
            return 0.0
        return self.overlapped_host_ms_total / self.host_ms_total

    def snapshot(self) -> dict:
        return {
            "gauges": {
                "num_waiting": self.num_waiting,
                "num_running": self.num_running,
            },
            "counters": {
                "num_steps": self.num_steps,
                "tokens_generated": self.tokens_generated,
                "prefill_tokens": self.prefill_tokens,
            },
            "ttft_ms": self.ttft_ms.snapshot(),
            "tpot_ms": self.tpot_ms.snapshot(),
            "itl_ms": self.itl_ms.snapshot(),
            "step_ms": self.step_ms.snapshot(),
            "host_ms": self.host_ms.snapshot(),
            "device_ms": self.device_ms.snapshot(),
            "batched_tokens": self.batched_tokens.snapshot(),
            "kv_restore_seconds": self.kv_restore_s.snapshot(),
            "queue_wait_ms": self.queue_wait_ms.snapshot(),
            "saturation": dict(self.saturation),
            "slo": {
                "targets": {"ttft_ms": self.slo_ttft_ms,
                            "tpot_ms": self.slo_tpot_ms},
                "tenants": {
                    t: {"finished": st.finished, "met": st.met,
                        "tokens": st.tokens,
                        "goodput_tokens": st.goodput_tokens,
                        "attainment": round(st.attainment, 4)}
                    for t, st in sorted(self.tenants.items())
                },
            },
            "padding": {
                "useful_tokens_total": self.useful_tokens_total,
                "padded_tokens_total": self.padded_tokens_total,
                "efficiency": round(self.padding_efficiency, 4),
            },
            "overlap": {
                "ratio": round(self.overlap_ratio, 4),
                "host_ms_total": round(self.host_ms_total, 3),
                "overlapped_host_ms_total": round(
                    self.overlapped_host_ms_total, 3),
            },
        }


def nearest_rank_pct(xs: list, p: float) -> float:
    """Nearest-rank percentile over a sequence: index ceil(p*n)-1.
    (int(p*n) would bias toward the max — p50 of [10, 20] must be 10.)
    The int(p*100*n) form sidesteps float error in p itself (0.99*n)."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = max(0, -(-int(p * 100 * len(xs)) // 100) - 1)
    return xs[min(len(xs) - 1, idx)]


class OrchestratorAggregator:
    """``stats_path`` is a path *prefix*: per-stage request records stream
    to ``{prefix}.stage{N}.stats.jsonl`` and E2E records to
    ``{prefix}.e2e.stats.jsonl`` (reference: the per-stage ``*.stats.jsonl``
    files of metrics/stats.py:115, wired at omni.py:692-697)."""

    def __init__(self, num_stages: int, stats_path: Optional[str] = None,
                 window: int = 4096):
        self.stages = {i: StageStats(stage_id=i) for i in range(num_stages)}
        self.edges: dict[tuple[int, int], TransferEdgeStats] = {}
        # in-flight only: finished entries are EVICTED (a long-running
        # server harvests stats every heartbeat — unbounded history would
        # grow memory forever and make summary() sort a lifetime of
        # latencies on the engine thread)
        self.requests: dict[str, RequestE2EStats] = {}
        self.per_request: deque = deque(maxlen=window)
        self._recent_e2e_ms: deque = deque(maxlen=window)
        self.num_finished = 0
        self._stats_path = stats_path

    def _append_jsonl(self, suffix: str, record: dict) -> None:
        with open(f"{self._stats_path}.{suffix}.stats.jsonl", "a") as f:
            f.write(json.dumps(record) + "\n")

    # ------------------------------------------------------------ recording
    def record_arrival(self, request_id: str) -> None:
        self.requests[request_id] = RequestE2EStats(
            request_id=request_id, arrival_ts=time.time(),
            arrival_mono=time.monotonic(),
        )

    def record_finish(self, request_id: str) -> None:
        r = self.requests.pop(request_id, None)
        if r is None:
            return
        r.finish_ts = time.time()
        r.finish_mono = time.monotonic()
        self.num_finished += 1
        self._recent_e2e_ms.append(r.e2e_ms)
        if self._stats_path:
            self._append_jsonl("e2e", {
                "request_id": r.request_id,
                "arrival_ts": r.arrival_ts,
                "finish_ts": r.finish_ts,
                "e2e_ms": round(r.e2e_ms, 3),
            })

    def record_stage_request(self, s: StageRequestStats) -> None:
        self.per_request.append(s)
        st = self.stages.setdefault(s.stage_id, StageStats(stage_id=s.stage_id))
        st.num_requests += 1
        st.tokens_in += s.tokens_in
        st.tokens_out += s.tokens_out
        st.gen_ms_total += s.gen_ms
        if self._stats_path:
            self._append_jsonl(f"stage{s.stage_id}", asdict(s))

    def record_transfer(self, from_stage: int, to_stage: int,
                        nbytes: int, ms: float) -> None:
        key = (from_stage, to_stage)
        edge = self.edges.setdefault(
            key, TransferEdgeStats(from_stage=from_stage, to_stage=to_stage)
        )
        edge.num_transfers += 1
        edge.bytes_total += nbytes
        edge.transfer_ms_total += ms

    # ------------------------------------------------------------- summary
    def summary(self) -> dict:
        e2e = list(self._recent_e2e_ms)

        def pct(p):
            return nearest_rank_pct(e2e, p)

        return {
            "stages": {
                i: {
                    "num_requests": st.num_requests,
                    "tokens_in": st.tokens_in,
                    "tokens_out": st.tokens_out,
                    "tps": round(st.tps, 2),
                }
                for i, st in self.stages.items()
            },
            "edges": {
                f"{k[0]}->{k[1]}": {
                    "transfers": e.num_transfers,
                    "bytes": e.bytes_total,
                    "ms": round(e.transfer_ms_total, 2),
                }
                for k, e in self.edges.items()
            },
            "e2e": {
                "num_finished": self.num_finished,
                # percentiles over the recent window, not lifetime
                "window": len(e2e),
                "p50_ms": round(pct(0.50), 2),
                "p90_ms": round(pct(0.90), 2),
                "p99_ms": round(pct(0.99), 2),
            },
        }
