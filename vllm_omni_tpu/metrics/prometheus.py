"""Prometheus text exposition for the pipeline + engine metrics.

The serving layer's ``GET /metrics`` renders through here (JSON summary
stays available at ``/metrics?format=json``).  Dependency-free on
purpose — the runtime ships no prometheus_client, matching the
native-runtime stance of the stdlib HTTP server.

``METRIC_SPECS`` is the single source of truth for the exported metric
surface: name (without the ``vllm_omni_tpu_`` prefix), type, help, and
the labels every sample must carry.  ``validate_exposition`` parses a
rendered exposition back against it — ``scripts/check_metrics_names.py``
and the metrics tests both run that check so the surface can't silently
drift.
"""

from __future__ import annotations

import re
from typing import Optional

METRIC_PREFIX = "vllm_omni_tpu_"

# metric name must match this (prefix + lowercase/underscore only — no
# digits, which is why the E2E latency series is "request_latency_ms")
NAME_RE = re.compile(r"vllm_omni_tpu_[a-z_]+")

# name -> (type, help, required label names)
METRIC_SPECS: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "requests_finished_total": (
        "counter", "Requests that completed the full pipeline", ()),
    "request_latency_ms": (
        "gauge", "End-to-end request latency percentiles (recent window)",
        ("quantile",)),
    "stage_requests_total": (
        "counter", "Requests processed per stage", ("stage",)),
    "stage_tokens_in_total": (
        "counter", "Input tokens per stage", ("stage",)),
    "stage_tokens_out_total": (
        "counter", "Output tokens per stage", ("stage",)),
    "stage_tokens_per_second": (
        "gauge", "Generation throughput per stage", ("stage",)),
    "transfer_count_total": (
        "counter", "Inter-stage transfers per edge",
        ("from_stage", "to_stage")),
    "transfer_bytes_total": (
        "counter", "Inter-stage transfer bytes per edge",
        ("from_stage", "to_stage")),
    "transfer_ms_total": (
        "counter", "Inter-stage transfer milliseconds per edge",
        ("from_stage", "to_stage")),
    "scheduler_waiting": (
        "gauge", "Requests in the waiting queue", ("stage",)),
    "scheduler_running": (
        "gauge", "Requests in the running batch", ("stage",)),
    "preemptions_total": (
        "counter", "Requests preempted (recompute policy)", ("stage",)),
    "rejections_total": (
        "counter", "Requests rejected at intake or error-finished",
        ("stage",)),
    "kv_pages_total": (
        "gauge", "KV cache pages in the pool", ("stage",)),
    "kv_pages_used": (
        "gauge", "KV cache pages allocated to live requests", ("stage",)),
    "kv_page_utilization": (
        "gauge", "Fraction of KV cache pages in use", ("stage",)),
    "prefix_cache_hits_total": (
        "counter", "Automatic-prefix-cache hits", ("stage",)),
    "prefix_cache_hit_tokens_total": (
        "counter", "Prompt tokens served from the prefix cache",
        ("stage",)),
    # ---- kvcache subsystem: radix prefix index + tiered offload
    # (docs/kv_cache.md)
    "kv_prefix_hit_tokens_total": (
        "counter",
        "Prompt tokens adopted from the radix prefix index (all tiers)",
        ("stage",)),
    "kv_tier_hbm_pages": (
        "gauge", "KV pages holding live data on the device", ("stage",)),
    "kv_tier_host_pages": (
        "gauge", "KV payloads parked in the host-RAM tier", ("stage",)),
    "kv_tier_remote_pages": (
        "gauge", "KV payloads parked in the remote tier", ("stage",)),
    "kv_offload_bytes_total": (
        "counter",
        "KV bytes moved per tier and direction (out = away from HBM, "
        "in = restored toward it)", ("stage", "tier", "dir")),
    "kv_restore_seconds": (
        "histogram",
        "Tier-restore latency per request run (fetch + inject)",
        ("stage",)),
    "kv_restored_tokens_total": (
        "counter",
        "Recompute tokens avoided by tier restores (cold prefix "
        "adoptions + park restores)", ("stage",)),
    "kv_parked_tokens_total": (
        "counter", "Tokens parked to the tiers at preemption",
        ("stage",)),
    "engine_steps_total": (
        "counter", "Engine step() executions", ("stage",)),
    "tokens_generated_total": (
        "counter", "Output tokens sampled", ("stage",)),
    "prefill_tokens_total": (
        "counter", "Prompt tokens prefilled", ("stage",)),
    "ttft_ms": (
        "histogram", "Time to first token", ("stage",)),
    "tpot_ms": (
        "histogram", "Time per output token (finished requests)",
        ("stage",)),
    "itl_ms": (
        "histogram", "Inter-token latency", ("stage",)),
    "engine_step_ms": (
        "histogram", "Engine step wall time", ("stage",)),
    # step-phase breakdown (async pipelined engine, docs/async_engine.md)
    "engine_step_host_ms": (
        "histogram",
        "Host-side work per engine step (schedule, retire, bookkeeping)",
        ("stage",)),
    "engine_step_device_ms": (
        "histogram",
        "Device-bound wait per engine step (execute or lagged retire)",
        ("stage",)),
    "engine_step_overlap_ratio": (
        "gauge",
        "Fraction of host step work overlapped with in-flight device "
        "compute", ("stage",)),
    # lifetime counter pairing with engine_step_host_ms_sum: rate()
    # over any window recovers a WINDOWED overlap ratio, which the
    # cumulative gauge above hides after long uptime
    "engine_step_overlapped_host_ms_total": (
        "counter",
        "Host step work milliseconds performed while a dispatched "
        "device step was in flight", ("stage",)),
    # ---- unified ragged batching (docs/ragged_batching.md)
    "engine_step_padding_efficiency": (
        "gauge",
        "Useful tokens / padded device rows across dispatches "
        "(1.0 = zero padding)", ("stage",)),
    "engine_step_batched_tokens": (
        "histogram", "Real tokens computed per engine step", ("stage",)),
    "engine_step_useful_tokens_total": (
        "counter", "Real tokens computed across device dispatches",
        ("stage",)),
    "engine_step_padded_tokens_total": (
        "counter", "Padded device rows across dispatches", ("stage",)),
    # ---- live roofline attribution (metrics/roofline.py,
    # docs/performance.md): achieved FLOPs / HBM bytes per step from
    # static model geometry × the token mix, over the platform peaks —
    # rolling-window means, wall-clock denominator (host stalls count
    # against utilization, exactly as they count against goodput)
    "engine_step_mfu": (
        "gauge",
        "Achieved model FLOPs over the platform bf16 peak, rolling "
        "window over recent steps (wall-clock denominator)", ("stage",)),
    "engine_step_mbu": (
        "gauge",
        "Achieved HBM bytes (weights + KV traffic) over the platform "
        "bandwidth peak per phase (prefill | decode | mixed — a "
        "token-packed step carrying both row kinds reports honestly "
        "as mixed), rolling window",
        ("stage", "phase")),
    # jit shape-cache telemetry: the unified path shrinks the cache
    # from a (batch, seq) grid to a token-bucket line — measurable here
    "jit_compiles_total": (
        "counter", "Fresh XLA executable compiles in the model runner",
        ("stage",)),
    "jit_cache_hits_total": (
        "counter", "Runner dispatches served by the jit shape cache",
        ("stage",)),
    "jit_compile_seconds_total": (
        "counter",
        "Cumulative seconds spent blocked on fresh compiles "
        "(first call per shape, to completion)", ("stage",)),
    # async pipeline drain granularity (docs/async_engine.md): sync
    # steps per reason while async scheduling is on.  Since PR 11 only
    # host-state reasons exist (kv_transfer | kv_offload | streaming |
    # reshaped) — the shape-based fallback matrix (spec / logprobs /
    # collect_hidden / embeds / prefill) is deleted with the split
    # executor and those label values can no longer be emitted
    "async_fallback_total": (
        "counter",
        "Async pipeline steps that fell back to the synchronous path",
        ("stage", "reason")),
    # ---- serving-curve observability (docs/load_testing.md): SLO
    # attainment + goodput per tenant, admission-control shedding,
    # queueing, and per-phase saturation — the engine-side face of the
    # open-loop load harness (vllm_omni_tpu/loadgen/)
    "slo_attainment_ratio": (
        "gauge",
        "Finished requests meeting every configured SLO target "
        "(TTFT/TPOT) over all finished, per tenant", ("stage", "tenant")),
    "slo_requests_total": (
        "counter", "Finished requests judged against the SLO targets",
        ("stage", "tenant")),
    # lifetime counter pair for slo_attainment_ratio: rate() over any
    # window recovers a WINDOWED attainment the cumulative gauge hides
    "slo_requests_met_total": (
        "counter", "Finished requests inside every SLO target",
        ("stage", "tenant")),
    "goodput_tokens_total": (
        "counter",
        "Output tokens from requests that met their SLO targets "
        "(tokens_generated_total counts all — the gap is wasted work)",
        ("stage", "tenant")),
    "shed_requests_total": (
        "counter",
        "Arrivals refused by admission control (HTTP 429), per reason "
        "— distinct from 503 retryable / 504 deadline_exceeded",
        ("stage", "reason", "tenant")),
    "request_queue_depth": (
        "gauge", "Waiting-queue depth per tenant", ("stage", "tenant")),
    "queue_wait_ms": (
        "histogram", "Arrival to first scheduled, per request",
        ("stage",)),
    "phase_saturation_ratio": (
        "gauge",
        "Fraction of the capacity ceiling used per phase (prefill/"
        "decode token budget, running seats) at the last schedule",
        ("stage", "phase")),
    # ---- introspection (docs/debugging.md): device-memory ledger,
    # span-loss accounting, stall-watchdog state
    "device_memory_bytes": (
        "gauge",
        "Live device memory per component (weights, kv_pages, "
        "spec_buffers, workspace); components sum to the device total",
        ("stage", "component")),
    "device_memory_peak_bytes": (
        "gauge",
        "Peak watermark of device memory per component (monotone)",
        ("stage", "component")),
    "trace_spans_dropped_total": (
        "counter",
        "Trace spans evicted from the recorder ring before any drain "
        "(a growing count means the trace files have holes)", ()),
    "watchdog_trips_total": (
        "counter", "Stall-watchdog trips (true hangs, compile stalls "
        "exempted)", ()),
    "watchdog_tripped": (
        "gauge",
        "Whether the stall watchdog has tripped (1 = /health serves "
        "503)", ()),
    "diffusion_requests_total": (
        "counter", "Diffusion requests generated", ("stage",)),
    "diffusion_batches_total": (
        "counter", "Diffusion batches executed", ("stage",)),
    "diffusion_gen_seconds": (
        "histogram", "Diffusion batch generation time", ("stage",)),
    "hbm_bytes": (
        "gauge", "Device HBM capacity", ()),
    # ---- resilience subsystem (vllm_omni_tpu/resilience/metrics.py) —
    # orchestrator-side restart/retry/breaker/deadline/fault counters
    "stage_restarts_total": (
        "counter", "Supervised stage worker restarts", ("stage",)),
    "stage_heartbeat_misses_total": (
        "counter", "Heartbeat intervals without a worker pong",
        ("stage",)),
    "requests_redelivered_total": (
        "counter",
        "Queued-but-unstarted requests redelivered after a restart",
        ("stage",)),
    "requests_failed_retryable_total": (
        "counter",
        "Requests failed fast with a retryable error (worker lost)",
        ("stage",)),
    "connector_retries_total": (
        "counter", "Connector RPC attempts that failed and were retried",
        ("site",)),
    "circuit_breaker_trips_total": (
        "counter", "Circuit breaker transitions to OPEN", ("site",)),
    "circuit_breaker_open": (
        "gauge", "Whether the edge's circuit breaker is open",
        ("site",)),
    "deadline_exceeded_total": (
        "counter", "Requests terminated by their end-to-end deadline",
        ("stage",)),
    "faults_injected_total": (
        "counter", "Fault-plan injections fired (testing only)",
        ("site",)),
    # ---- disaggregated prefill/decode serving (vllm_omni_tpu/disagg/,
    # docs/disaggregation.md) — handoff volume/latency, failover ledger,
    # router tier health, degradation state
    "kv_handoff_bytes_total": (
        "counter",
        "Prefill->decode KV handoff bytes per direction (out = shipped "
        "by the prefill tier, in = received by the decode tier)",
        ("dir",)),
    "kv_handoff_seconds": (
        "histogram",
        "Prefill->decode KV handoff latency per request (ship + "
        "receive + integrity verification)", ()),
    "failover_total": (
        "counter",
        "Requests re-routed by the disagg router, per reason (replica "
        "death, handoff failure, adoption failure, tier loss)",
        ("reason",)),
    "router_healthy_replicas": (
        "gauge",
        "Replicas in the dispatch rotation per tier (healthy, not "
        "drained)", ("role",)),
    "degraded_mode": (
        "gauge",
        "Whether the router is serving colocated because a tier has "
        "zero healthy replicas (1 = degraded)", ()),
    # ---- control plane (vllm_omni_tpu/controlplane/,
    # docs/control_plane.md) — re-role/autoscale actuation ledger,
    # fleet shape, and the WFQ scheduler's deferral accounting
    "controlplane_reroles_total": (
        "counter",
        "Completed live role flips (drain -> quiesce -> flip -> "
        "re-admit) per direction", ("from_role", "to_role")),
    "controlplane_replicas": (
        "gauge", "Non-dead replicas per role, as the controller sees "
        "the fleet", ("role",)),
    "controlplane_actions_total": (
        "counter",
        "Control-plane actions applied on the router thread (drain, "
        "undrain, rerole, scale_up, remove_replica)", ("action",)),
    "wfq_deferred_requests_total": (
        "counter",
        "Deficit-round-robin rounds that held a tenant's head-of-line "
        "request back while placing other work (weighted-fair overload "
        "scheduling)", ("stage", "tenant")),
    # ---- omnipulse (metrics/alerts.py + metrics/attribution.py,
    # docs/observability.md): alert lifecycle + per-tenant heavy-hitter
    # attribution.  Attribution values are space-saving sketch
    # ESTIMATES (est >= true >= est - total/capacity); only the top-k
    # tenants per meter render, inside the tenant cardinality cap
    "alerts_firing": (
        "gauge", "Whether the named alert rule is firing (1 = its "
        "condition held past for-duration)", ("alert",)),
    "alert_transitions_total": (
        "counter",
        "Alert lifecycle transitions per rule and destination state "
        "(pending | firing | resolved | inactive)", ("alert", "to")),
    "tenant_tokens_total": (
        "counter",
        "Per-tenant token consumption by kind (prefill | decode), "
        "space-saving estimate over the top-k heavy hitters",
        ("stage", "tenant", "kind")),
    "tenant_kv_page_seconds_total": (
        "counter",
        "Per-tenant KV page-seconds of residency per tier (hbm = live "
        "device pages, host = parked payloads), sketch estimate",
        ("stage", "tenant", "tier")),
    "tenant_handoff_bytes_total": (
        "counter",
        "Per-tenant prefill->decode KV handoff bytes, sketch estimate",
        ("stage", "tenant")),
    "tenant_queue_wait_ms_total": (
        "counter",
        "Per-tenant cumulative arrival-to-first-scheduled wait, "
        "sketch estimate", ("stage", "tenant")),
    "tenant_sheds_total": (
        "counter",
        "Per-tenant admission-control sheds, sketch estimate — unlike "
        "shed_requests_total this sees past the cardinality cap",
        ("stage", "tenant")),
    "attribution_tracked_tenants": (
        "gauge",
        "Distinct tenants currently tracked by the attribution sketch "
        "per meter (bounded by the sketch capacity)",
        ("stage", "meter")),
    # ---- omniscope (metrics/cache_economics.py,
    # docs/observability.md): fleet KV-cache economics — router-
    # aggregated radix digests scoring every dispatch for wasted
    # re-prefill (the regret signal prefix-affinity routing minimizes)
    "fleet_prefix_hit_tokens_total": (
        "counter",
        "Prompt tokens served from ANY replica's prefix cache, "
        "fleet-wide (delta-accumulated across replica replacement)",
        ()),
    "fleet_prefill_tokens_total": (
        "counter",
        "Prompt tokens prefilled fleet-wide — the hit-rate "
        "denominator's other half", ()),
    "fleet_prefix_hit_rate": (
        "gauge",
        "Fleet prefix hit rate: hit tokens / (hit + prefilled) over "
        "the fleet's lifetime counters", ()),
    "fleet_duplicate_prefill_tokens_total": (
        "counter",
        "Wasted re-prefill: tokens the chosen replica prefilled that "
        "another in-rotation replica (peer_replica) or a parked cold "
        "copy (peer_cold_tier) already held", ("reason",)),
    "fleet_duplicate_prefix_tokens": (
        "gauge",
        "Tokens of prefix content currently duplicated across replica "
        "caches (k replicas holding a page count k-1 redundant "
        "copies), from the bounded digests", ()),
    "cache_digest_nodes": (
        "gauge",
        "Radix digest entries exported by the replica on the last "
        "stride refresh (hard-capped — the digest cost bound)",
        ("replica",)),
    "tenant_duplicate_prefill_tokens_total": (
        "counter",
        "Per-tenant wasted re-prefill tokens, sketch estimate — which "
        "tenants' traffic the cache-blind router scatters",
        ("stage", "tenant")),
    # ---- omniaffinity (disagg/router.py, docs/disaggregation.md):
    # prefix-affinity dispatch + the cluster KV fabric
    "router_affinity_dispatch_total": (
        "counter",
        "Affinity-scored placements by outcome: hit (a warm owner "
        "won), miss (cold prefix — load + tenant-hash owner), "
        "load_override (a warm hit existed but load won the score)",
        ("outcome",)),
    "kv_prefix_pull_bytes_total": (
        "counter",
        "Bytes of shared-prefix KV pulled from the cluster fabric "
        "instead of re-prefilled; src=peer when a live replica still "
        "advertises the prefix HBM-resident, cold otherwise",
        ("src",)),
    "kv_prefix_pull_seconds": (
        "histogram",
        "Fabric prefix-pull latency: fetch + integrity verify + "
        "re-publish, as seen by the router thread", ()),
}

#: attribution meter -> (/metrics series, fixed extra labels); meters
#: without a row stay /debug/tenants-only
_ATTRIBUTION_SERIES: dict[str, tuple[str, dict]] = {
    "prefill_tokens": ("tenant_tokens_total", {"kind": "prefill"}),
    "decode_tokens": ("tenant_tokens_total", {"kind": "decode"}),
    "kv_page_seconds_hbm": ("tenant_kv_page_seconds_total",
                            {"tier": "hbm"}),
    "kv_page_seconds_host": ("tenant_kv_page_seconds_total",
                             {"tier": "host"}),
    "handoff_bytes": ("tenant_handoff_bytes_total", {}),
    "queue_wait_ms": ("tenant_queue_wait_ms_total", {}),
    "sheds": ("tenant_sheds_total", {}),
    "duplicate_prefill_tokens": (
        "tenant_duplicate_prefill_tokens_total", {}),
}

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _fmt_value(v) -> str:
    if v is None:
        return "0"
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v) -> str:
    """Prometheus text-format label escaping (backslash, quote,
    newline).  Label values can carry CLIENT input (the tenant label
    comes from the x-omni-tenant header), so unescaped rendering would
    let one request corrupt the whole exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


class _Exposition:
    """Buffers samples per metric family: the text format requires every
    line of a family to form ONE group (HELP/TYPE then all samples) —
    interleaving per-stage loop output would break strict OpenMetrics
    parsers even though the Prometheus server tolerates it."""

    def __init__(self):
        # family name -> sample lines, in first-use order
        self._families: dict[str, list[str]] = {}

    def sample(self, name: str, labels: dict, value,
               suffix: str = "") -> None:
        full = METRIC_PREFIX + name
        self._families.setdefault(name, []).append(
            f"{full}{suffix}{_fmt_labels(labels)} {_fmt_value(value)}")

    def histogram(self, name: str, labels: dict, snap: dict) -> None:
        """Render a stats.Histogram snapshot (cumulative buckets)."""
        for le, cum in snap.get("buckets", ()):
            self.sample(name, {**labels, "le": _fmt_value(le)}, cum,
                        suffix="_bucket")
        self.sample(name, labels, snap.get("sum", 0.0), suffix="_sum")
        self.sample(name, labels, snap.get("count", 0), suffix="_count")

    def render(self) -> str:
        lines: list[str] = []
        for name, samples in self._families.items():
            spec = METRIC_SPECS[name]
            full = METRIC_PREFIX + name
            lines.append(f"# HELP {full} {spec[1]}")
            lines.append(f"# TYPE {full} {spec[0]}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


def render_exposition(summary: dict, engine_snaps: dict,
                      device: Optional[dict] = None,
                      resilience: Optional[dict] = None,
                      process_stats: Optional[dict] = None,
                      disagg: Optional[dict] = None) -> str:
    """``summary``: OrchestratorAggregator.summary(); ``engine_snaps``:
    {stage_id: LLMEngine/DiffusionEngine.metrics_snapshot() or {}};
    ``resilience``: resilience_metrics.snapshot() (restart/retry/
    breaker/deadline counters, labels already attached);
    ``process_stats``: process-level introspection counters
    ({spans_dropped, watchdog_trips, watchdog_tripped});
    ``disagg``: DisaggRouter.disagg_snapshot() (the handoff-latency
    histogram — the disagg counters/gauges ride ``resilience``)."""
    exp = _Exposition()
    e2e = summary.get("e2e", {})
    exp.sample("requests_finished_total", {}, e2e.get("num_finished", 0))
    for q, key in (("0.5", "p50_ms"), ("0.9", "p90_ms"),
                   ("0.99", "p99_ms")):
        exp.sample("request_latency_ms", {"quantile": q}, e2e.get(key, 0.0))
    for sid, st in sorted(summary.get("stages", {}).items()):
        labels = {"stage": sid}
        exp.sample("stage_requests_total", labels, st.get("num_requests", 0))
        exp.sample("stage_tokens_in_total", labels, st.get("tokens_in", 0))
        exp.sample("stage_tokens_out_total", labels, st.get("tokens_out", 0))
        exp.sample("stage_tokens_per_second", labels, st.get("tps", 0.0))
    for edge, e in sorted(summary.get("edges", {}).items()):
        frm, _, to = str(edge).partition("->")
        labels = {"from_stage": frm, "to_stage": to}
        exp.sample("transfer_count_total", labels, e.get("transfers", 0))
        exp.sample("transfer_bytes_total", labels, e.get("bytes", 0))
        exp.sample("transfer_ms_total", labels, e.get("ms", 0.0))
    for sid, snap in sorted(engine_snaps.items()):
        if not snap:
            continue
        labels = {"stage": sid}
        sched = snap.get("scheduler")
        if sched:
            exp.sample("scheduler_waiting", labels, sched.get("waiting", 0))
            exp.sample("scheduler_running", labels, sched.get("running", 0))
            exp.sample("preemptions_total", labels,
                       sched.get("preemptions", 0))
            exp.sample("rejections_total", labels,
                       sched.get("rejections", 0))
        kv = snap.get("kv")
        if kv:
            exp.sample("kv_pages_total", labels, kv.get("pages_total", 0))
            exp.sample("kv_pages_used", labels, kv.get("pages_used", 0))
            exp.sample("kv_page_utilization", labels,
                       kv.get("utilization", 0.0))
        pc = snap.get("prefix_cache")
        if pc and pc.get("enabled"):
            exp.sample("prefix_cache_hits_total", labels, pc.get("hits", 0))
            exp.sample("prefix_cache_hit_tokens_total", labels,
                       pc.get("hit_tokens", 0))
        tiers = snap.get("kv_tiers")
        if tiers:
            exp.sample("kv_prefix_hit_tokens_total", labels,
                       tiers.get("prefix_hit_tokens", 0))
            exp.sample("kv_tier_hbm_pages", labels,
                       tiers.get("hbm_pages", 0))
            exp.sample("kv_tier_host_pages", labels,
                       tiers.get("host_pages", 0))
            exp.sample("kv_tier_remote_pages", labels,
                       tiers.get("remote_pages", 0))
            for edge, n in sorted(
                    (tiers.get("bytes_moved") or {}).items()):
                tier, _, direction = str(edge).partition("/")
                exp.sample("kv_offload_bytes_total",
                           {**labels, "tier": tier, "dir": direction}, n)
            exp.sample("kv_restored_tokens_total", labels,
                       tiers.get("restored_tokens", 0))
            exp.sample("kv_parked_tokens_total", labels,
                       tiers.get("parked_tokens", 0))
        if snap.get("kv_restore_seconds", {}).get("count"):
            exp.histogram("kv_restore_seconds", labels,
                          snap["kv_restore_seconds"])
        counters = snap.get("counters")
        if counters:
            exp.sample("engine_steps_total", labels,
                       counters.get("num_steps", 0))
            exp.sample("tokens_generated_total", labels,
                       counters.get("tokens_generated", 0))
            exp.sample("prefill_tokens_total", labels,
                       counters.get("prefill_tokens", 0))
        gauges = snap.get("gauges")
        if gauges and not sched:
            # engines without a scheduler snapshot still expose depth
            exp.sample("scheduler_waiting", labels,
                       gauges.get("num_waiting", 0))
            exp.sample("scheduler_running", labels,
                       gauges.get("num_running", 0))
        for hist_name in ("ttft_ms", "tpot_ms", "itl_ms"):
            h = snap.get(hist_name)
            if h:
                exp.histogram(hist_name, labels, h)
        if snap.get("step_ms"):
            exp.histogram("engine_step_ms", labels, snap["step_ms"])
        if snap.get("host_ms"):
            exp.histogram("engine_step_host_ms", labels, snap["host_ms"])
        if snap.get("device_ms"):
            exp.histogram("engine_step_device_ms", labels,
                          snap["device_ms"])
        overlap = snap.get("overlap")
        if overlap:
            exp.sample("engine_step_overlap_ratio", labels,
                       overlap.get("ratio", 0.0))
            exp.sample("engine_step_overlapped_host_ms_total", labels,
                       overlap.get("overlapped_host_ms_total", 0.0))
        if snap.get("batched_tokens"):
            exp.histogram("engine_step_batched_tokens", labels,
                          snap["batched_tokens"])
        padding = snap.get("padding")
        if padding:
            exp.sample("engine_step_padding_efficiency", labels,
                       padding.get("efficiency", 0.0))
            exp.sample("engine_step_useful_tokens_total", labels,
                       padding.get("useful_tokens_total", 0))
            exp.sample("engine_step_padded_tokens_total", labels,
                       padding.get("padded_tokens_total", 0))
        roofline = snap.get("roofline")
        if roofline:
            exp.sample("engine_step_mfu", labels,
                       roofline.get("mfu", 0.0))
            for phase, v in sorted((roofline.get("mbu") or {}).items()):
                exp.sample("engine_step_mbu",
                           {**labels, "phase": phase}, v)
        compile_stats = snap.get("compile")
        if compile_stats:
            exp.sample("jit_compiles_total", labels,
                       compile_stats.get("compiles", 0))
            exp.sample("jit_cache_hits_total", labels,
                       compile_stats.get("cache_hits", 0))
            exp.sample("jit_compile_seconds_total", labels,
                       compile_stats.get("compile_s", 0.0))
        for reason, count in sorted(
                (snap.get("async_fallback") or {}).items()):
            exp.sample("async_fallback_total",
                       {**labels, "reason": reason}, count)
        # serving-curve observability: queue depth + shed ledger + SLO
        # attainment/goodput per tenant + queue-wait + saturation
        queue = snap.get("queue")
        if queue:
            for tenant, depth in sorted(
                    (queue.get("depth_by_tenant") or {}).items()):
                exp.sample("request_queue_depth",
                           {**labels, "tenant": tenant}, depth)
        for key, n in sorted((snap.get("shed") or {}).items()):
            reason, _, tenant = str(key).partition("/")
            exp.sample("shed_requests_total",
                       {**labels, "reason": reason,
                        "tenant": tenant or "default"}, n)
        # WFQ deferral ledger (docs/control_plane.md): rounds a
        # tenant's head-of-line request waited behind other tenants
        wfq = snap.get("wfq")
        if wfq:
            for tenant, n in sorted(
                    (wfq.get("deferred_by_tenant") or {}).items()):
                exp.sample("wfq_deferred_requests_total",
                           {**labels, "tenant": tenant}, n)
        slo = snap.get("slo")
        if slo:
            for tenant, st in sorted((slo.get("tenants") or {}).items()):
                tl = {**labels, "tenant": tenant}
                exp.sample("slo_attainment_ratio", tl,
                           st.get("attainment", 0.0))
                exp.sample("slo_requests_total", tl,
                           st.get("finished", 0))
                exp.sample("slo_requests_met_total", tl, st.get("met", 0))
                exp.sample("goodput_tokens_total", tl,
                           st.get("goodput_tokens", 0))
        # per-tenant heavy-hitter attribution: top-k sketch estimates
        # per meter (docs/observability.md); only meters with traffic
        # render, and every value is declared approximate in HELP.
        # Rows without the lifetime ``export`` slot are skipped: top-k
        # bounds each scrape, but under adversarial churn its
        # membership over time is unbounded, and every label value
        # lives forever in the scrape database — the sketch layer
        # budgets MAX_TENANT_SERIES distinct tenants per engine for
        # its whole life (attribution.py), and per-key estimates never
        # decrease, so the counter-typed series stay monotone.
        # /debug/tenants keeps the full uncapped boards
        attr = snap.get("attribution")
        if attr:
            for meter, doc in sorted((attr.get("meters") or {}).items()):
                series = _ATTRIBUTION_SERIES.get(meter)
                if series is None:
                    continue
                name, extra = series
                for row in doc.get("top") or ():
                    if not row.get("export", True):
                        continue
                    exp.sample(name, {**labels, "tenant": row["tenant"],
                                      **extra}, row["est"])
                if doc.get("tenants_tracked"):
                    exp.sample("attribution_tracked_tenants",
                               {**labels, "meter": meter},
                               doc["tenants_tracked"])
        if snap.get("queue_wait_ms"):
            exp.histogram("queue_wait_ms", labels, snap["queue_wait_ms"])
        for phase, v in sorted((snap.get("saturation") or {}).items()):
            exp.sample("phase_saturation_ratio",
                       {**labels, "phase": phase}, v)
        # device-memory ledger: per-component live/peak bytes
        # (components sum to total; docs/debugging.md)
        dm = snap.get("device_memory")
        if dm:
            for comp, v in sorted((dm.get("components") or {}).items()):
                cl = {**labels, "component": comp}
                exp.sample("device_memory_bytes", cl,
                           v.get("bytes", 0))
                exp.sample("device_memory_peak_bytes", cl,
                           v.get("peak_bytes", 0))
        diff = snap.get("diffusion")
        if diff:
            exp.sample("diffusion_requests_total", labels,
                       diff.get("requests_total", 0))
            exp.sample("diffusion_batches_total", labels,
                       diff.get("batches_total", 0))
            if diff.get("gen_seconds"):
                exp.histogram("diffusion_gen_seconds", labels,
                              diff["gen_seconds"])
    if device and device.get("hbm_bytes"):
        exp.sample("hbm_bytes", {}, device["hbm_bytes"])
    if process_stats:
        exp.sample("trace_spans_dropped_total", {},
                   process_stats.get("spans_dropped", 0))
        exp.sample("watchdog_trips_total", {},
                   process_stats.get("watchdog_trips", 0))
        exp.sample("watchdog_tripped", {},
                   1 if process_stats.get("watchdog_tripped") else 0)
    if disagg and disagg.get("handoff_seconds", {}).get("count"):
        exp.histogram("kv_handoff_seconds", {},
                      disagg["handoff_seconds"])
    if disagg and disagg.get("prefix_pull_seconds", {}).get("count"):
        exp.histogram("kv_prefix_pull_seconds", {},
                      disagg["prefix_pull_seconds"])
    cache = (disagg or {}).get("cache")
    if cache:
        # fleet cache economics (metrics/cache_economics.py): the
        # router's aggregated digest board
        exp.sample("fleet_prefix_hit_tokens_total", {},
                   cache.get("fleet_hit_tokens", 0))
        exp.sample("fleet_prefill_tokens_total", {},
                   cache.get("fleet_prefill_tokens", 0))
        exp.sample("fleet_prefix_hit_rate", {},
                   cache.get("hit_rate", 0.0))
        for reason, v in sorted(
                (cache.get("duplicate_by_reason") or {}).items()):
            exp.sample("fleet_duplicate_prefill_tokens_total",
                       {"reason": reason}, v)
        exp.sample("fleet_duplicate_prefix_tokens", {},
                   cache.get("duplicate_prefix_tokens", 0))
        for rid, n in sorted(
                (cache.get("digest_nodes") or {}).items()):
            exp.sample("cache_digest_nodes", {"replica": str(rid)}, n)
    for name, samples in (resilience or {}).items():
        if name not in METRIC_SPECS:
            continue  # unknown names never leak past the drift guard
        for labels, value in samples:
            exp.sample(name, labels, value)
    return exp.render()


def render_from_omni(omni, device: Optional[dict] = None) -> str:
    """Render the exposition for a (sync) ``Omni`` orchestrator: the
    aggregator summary plus every stage's engine snapshot (proc stages
    report the last snapshot shipped over their command channel) plus
    the resilience counters — this process's own, merged with the
    snapshots stage WORKERS ship on their outputs frames (deadline
    kills happen at the worker's scheduler; without the merge /metrics
    would report 0 for process-disaggregated stages)."""
    from vllm_omni_tpu.resilience.metrics import (
        merge_snapshots,
        resilience_metrics,
    )
    from vllm_omni_tpu.tracing import get_recorder

    summary = omni.metrics.summary()
    snaps = {}
    worker_res = []
    for stage in getattr(omni, "stages", ()):
        fn = getattr(stage, "engine_metrics_snapshot", None)
        snaps[stage.stage_id] = fn() if fn is not None else {}
        rfn = getattr(stage, "resilience_snapshot", None)
        if rfn is not None:
            worker_res.append(rfn())
    wd = getattr(omni, "watchdog", None)
    process_stats = {
        # THIS process's recorder (stage workers drain theirs over the
        # outputs frames before their rings can evict)
        "spans_dropped": get_recorder().spans_dropped,
        "watchdog_trips": getattr(wd, "trips", 0),
        "watchdog_tripped": getattr(wd, "tripped", None) is not None,
    }
    # a disagg-routed deployment hangs its router off the orchestrator;
    # its handoff histogram joins the exposition (counters/gauges
    # already ride the resilience registry)
    router = getattr(omni, "router", None)
    return render_exposition(
        summary, snaps, device=device,
        resilience=merge_snapshots(resilience_metrics.snapshot(),
                                   *worker_res),
        process_stats=process_stats,
        disagg=(router.disagg_snapshot() if router is not None
                else None))


# ------------------------------------------------------------ validation
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _base_name(sample_name: str) -> str:
    """Strip histogram sample suffixes back to the declared metric name."""
    stripped = sample_name[len(METRIC_PREFIX):]
    for suffix in _HIST_SUFFIXES:
        if stripped.endswith(suffix):
            base = stripped[: -len(suffix)]
            if base in METRIC_SPECS and METRIC_SPECS[base][0] == "histogram":
                return base
    return stripped


def validate_exposition(text: str) -> list[str]:
    """Check a rendered exposition against METRIC_SPECS; returns a list
    of violations (empty = clean).  Rules: every sample name matches
    ``vllm_omni_tpu_[a-z_]+`` (histogram ``_bucket/_sum/_count`` samples
    validate against their base name), is declared in METRIC_SPECS, and
    carries every label its spec requires (``stage`` where applicable)."""
    errors = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        sample_name, _, labels_str, _ = m.groups()
        if not sample_name.startswith(METRIC_PREFIX):
            errors.append(
                f"line {lineno}: {sample_name} lacks the "
                f"{METRIC_PREFIX} prefix")
            continue
        base = _base_name(sample_name)
        spec = METRIC_SPECS.get(base)
        if spec is None:
            errors.append(
                f"line {lineno}: {sample_name} not declared in "
                "METRIC_SPECS")
            continue
        if not NAME_RE.fullmatch(METRIC_PREFIX + base):
            errors.append(
                f"line {lineno}: {METRIC_PREFIX + base} violates the "
                "naming rule vllm_omni_tpu_[a-z_]+")
        labels = dict(_LABEL_RE.findall(labels_str or ""))
        for required in spec[2]:
            if required not in labels:
                errors.append(
                    f"line {lineno}: {sample_name} missing required "
                    f"label {required!r}")
    return errors


def validate_specs() -> list[str]:
    """Static check of the registry itself (names must be regex-clean
    even before anything renders)."""
    errors = []
    for name, (mtype, help_text, labels) in METRIC_SPECS.items():
        if not NAME_RE.fullmatch(METRIC_PREFIX + name):
            errors.append(
                f"{METRIC_PREFIX + name} violates vllm_omni_tpu_[a-z_]+")
        if mtype not in ("counter", "gauge", "histogram"):
            errors.append(f"{name}: unknown type {mtype!r}")
        if not help_text:
            errors.append(f"{name}: empty help text")
    return errors
