"""Live roofline attribution: per-step achieved MFU / MBU, zero syncs.

"The Anatomy of a Triton Attention Kernel" (PAPERS.md) makes the case
for attributing achieved FLOPs and bytes per dispatch — which this repo
only did OFFLINE in bench.py until now.  This module is the live face:
every engine step self-reports how close it ran to the hardware
roofline, from quantities the step loop already holds on the host:

- **achieved FLOPs** = static model geometry × the step's useful-token
  mix.  Dense matmul cost is ``2 × active-params`` per token (MoE
  counts the routed top-k experts + shared expert, not the resident
  total); attention adds ``4 × heads × head_dim × layers`` per
  (new-token × context-position) pair; the LM head bills per sampled
  row.  Context sums come from the scheduler's ``start_pos`` /
  ``num_new_tokens`` — host ints, **zero device syncs** (the same
  stance as the PR 8 memory ledger; this module lives in the omnilint
  OL2 HOT_PATHS manifest).
- **achieved HBM bytes** = active weight bytes read once per dispatch
  + KV read over every attended context position + KV write for every
  new position.  Decode is the bandwidth-bound phase; this is the
  quantity that explains why its MFU is structurally low.
- **denominators** come from ``platforms/`` (``peak_tflops_bf16`` /
  ``peak_hbm_gbps``) and the step's WALL time — the operator quantity:
  host stalls and pipeline bubbles count against utilization, exactly
  as they count against goodput.  Kernel-level numbers stay bench.py's
  job.

Surfaces: ``engine_step_mfu`` / ``engine_step_mbu{phase}`` gauges on
/metrics (rolling-window means), per-record ``mfu``/``mbu``/``phase``
fields in the flight recorder (record schema v3), and the rolling
window on ``/debug/engine``.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Optional

from vllm_omni_tpu.analysis.runtime import traced

#: rolling-window length (steps) for the /metrics gauges — long enough
#: to smooth scheduler jitter, short enough that a regime change (batch
#: collapse, drained replica) shows within seconds
DEFAULT_WINDOW = 128


@dataclass(frozen=True)
class ModelGeometry:
    """Static per-token cost model of one transformer forward.

    All quantities are per-DEVICE (divide by TP degree upstream if the
    runner shards; today the engine computes per-process totals against
    the per-chip peak, which is exact for TP=1 and conservative
    otherwise)."""

    #: dense matmul FLOPs per token (projections + MLP + norms ~ 0)
    flops_per_token: float
    #: attention FLOPs per (new token × attended context position):
    #: QK^T + AV = 4 × heads × head_dim per layer, summed over layers
    attn_flops_per_ctx: float
    #: LM-head FLOPs per sampled row (2 × hidden × vocab)
    lm_head_flops_per_row: float
    #: bytes of (active) weights read per dispatch
    weight_bytes: float
    #: KV-cache bytes per token position (all layers, K+V)
    kv_bytes_per_pos: float

    @classmethod
    def from_transformer_config(cls, cfg, dtype_bytes: int
                                ) -> "ModelGeometry":
        """Derive the cost model from a ``TransformerConfig``.  MoE
        counts ACTIVE parameters per token (top-k routed + shared
        expert); attention uses the dense per-layer shape."""
        h = cfg.hidden_size
        q_dim = cfg.num_heads * cfg.head_dim
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        attn_params = h * q_dim + 2 * h * kv_dim + q_dim * h
        if getattr(cfg, "moe", False):
            inter = cfg.moe_intermediate_size or cfg.intermediate_size
            mlp_params = (cfg.num_experts_per_tok * 3 * h * inter
                          + (3 * h * cfg.shared_expert_size
                             if getattr(cfg, "shared_expert_size", 0)
                             else 0))
        else:
            mlp_params = 3 * h * cfg.intermediate_size
        per_layer = attn_params + mlp_params
        active_params = cfg.num_layers * per_layer
        return cls(
            flops_per_token=2.0 * active_params,
            attn_flops_per_ctx=(4.0 * cfg.num_heads * cfg.head_dim
                                * cfg.num_layers),
            lm_head_flops_per_row=2.0 * h * cfg.vocab_size,
            weight_bytes=float(active_params * dtype_bytes
                               + h * cfg.vocab_size * dtype_bytes),
            kv_bytes_per_pos=float(2 * cfg.num_layers * kv_dim
                                   * dtype_bytes),
        )

    # ----------------------------------------------------------- costs
    def step_flops(self, new_tokens: int, ctx_positions: float,
                   sampled_rows: int) -> float:
        """Achieved FLOPs of one step: ``new_tokens`` computed
        positions attending over ``ctx_positions`` total (new × ctx
        pairs, summed by the caller from start_pos/num_new_tokens),
        with ``sampled_rows`` LM-head rows."""
        return (self.flops_per_token * new_tokens
                + self.attn_flops_per_ctx * ctx_positions
                + self.lm_head_flops_per_row * sampled_rows)

    def step_bytes(self, new_tokens: int, ctx_positions: float) -> float:
        """Achieved HBM traffic of one step: weights read once per
        dispatch, KV read per attended position, KV written per new
        position."""
        return (self.weight_bytes
                + self.kv_bytes_per_pos * ctx_positions
                + self.kv_bytes_per_pos * new_tokens)

    def arithmetic_intensity(self, new_tokens: int, ctx_positions: float,
                             sampled_rows: int) -> float:
        """FLOPs per HBM byte for a given token mix — the roofline
        x-axis.  Structural property of the geometry: prefill (many new
        tokens per dispatch) is always denser than single-token decode."""
        b = self.step_bytes(new_tokens, ctx_positions)
        if b <= 0:
            return 0.0
        return self.step_flops(new_tokens, ctx_positions,
                               sampled_rows) / b


def ctx_positions(start_pos: int, num_new: int) -> float:
    """Total attended context positions for ``num_new`` tokens appended
    from ``start_pos`` under causal attention: token i attends over
    ``start_pos + i + 1`` positions."""
    n = max(int(num_new), 0)
    return n * max(int(start_pos), 0) + n * (n + 1) / 2.0


class RooflineTracker:
    """Rolling per-step MFU/MBU window for one engine.

    Thread contract: ``on_step`` is called by the engine thread inside
    the step loop (host math only); ``snapshot`` by the /metrics and
    /debug HTTP threads — ``_lock`` guards the window and the phase
    aggregates (declared in the omnilint LOCK_GUARDS manifest)."""

    def __init__(self, geometry: ModelGeometry, peak_tflops: float,
                 peak_gbps: float, window: int = DEFAULT_WINDOW):
        self.geometry = geometry
        self.peak_flops = max(float(peak_tflops), 0.0) * 1e12
        self.peak_bytes = max(float(peak_gbps), 0.0) * 1e9
        self._lock = traced(threading.Lock(), "RooflineTracker._lock")
        # (phase, mfu, mbu) per recent step
        self._window: deque = deque(maxlen=max(int(window), 1))
        self._flops_total = 0.0
        self._bytes_total = 0.0

    def on_step(self, *, prefill_tokens: int, prefill_ctx: float,
                decode_tokens: int, decode_ctx: float,
                sampled_rows: int, wall_s: float) -> Optional[dict]:
        """Account one step; returns {"mfu","mbu","phase"} for the
        flight record, or None when nothing was computed.  Values are
        clamped to [0, 1] — the cost model is an estimate and the wall
        clock is host-observed; a >1 reading is model error, not free
        FLOPs."""
        new_tokens = prefill_tokens + decode_tokens
        if new_tokens <= 0 or wall_s <= 0:
            return None
        g = self.geometry
        ctx = prefill_ctx + decode_ctx
        flops = g.step_flops(new_tokens, ctx, sampled_rows)
        nbytes = g.step_bytes(new_tokens, ctx)
        mfu = (min(flops / (wall_s * self.peak_flops), 1.0)
               if self.peak_flops > 0 else 0.0)
        mbu = (min(nbytes / (wall_s * self.peak_bytes), 1.0)
               if self.peak_bytes > 0 else 0.0)
        # phase honesty: a token-packed step carrying BOTH prefill and
        # decode rows (the norm under unified batching) is "mixed" — a
        # one-phase label would bill its bytes (mostly decode KV
        # traffic) to the prefill gauge and starve the decode one
        # exactly when traffic is heaviest
        if prefill_tokens > 0 and decode_tokens > 0:
            phase = "mixed"
        elif prefill_tokens > 0:
            phase = "prefill"
        else:
            phase = "decode"
        with self._lock:
            self._window.append((phase, mfu, mbu))
            self._flops_total += flops
            self._bytes_total += nbytes
        # no rounding: a compile-laden step on a tiny model reads
        # ~1e-9 MFU, and rounding that to 0.0 would turn "barely
        # utilized" into "did nothing"
        return {"mfu": mfu, "mbu": mbu, "phase": phase}

    def snapshot(self, recent: int = 32) -> dict:
        """JSON-ready rolling view: window means for the gauges
        (``mfu``; ``mbu`` split per phase) + the last ``recent`` steps
        for /debug/engine."""
        with self._lock:
            win = list(self._window)
            flops_total, bytes_total = self._flops_total, self._bytes_total
        by_phase: dict[str, list] = {}
        for phase, _, mbu in win:
            by_phase.setdefault(phase, []).append(mbu)
        mfus = [m for _, m, _ in win]
        return {
            "mfu": sum(mfus) / len(mfus) if mfus else 0.0,
            "mbu": {p: sum(v) / len(v)
                    for p, v in sorted(by_phase.items())},
            "window_steps": len(win),
            "peak_tflops": round(self.peak_flops / 1e12, 3),
            "peak_hbm_gbps": round(self.peak_bytes / 1e9, 3),
            "model_flops_total": flops_total,
            "model_hbm_bytes_total": bytes_total,
            "recent": ([{"phase": p, "mfu": m, "mbu": b}
                        for p, m, b in win[-int(recent):]]
                       if recent and int(recent) > 0 else []),
        }


__all__ = ["ModelGeometry", "RooflineTracker", "ctx_positions",
           "DEFAULT_WINDOW"]
