"""omnipulse: SLO burn-rate alerting over the in-proc metric registries.

The stack can record a bad minute (flight recorder), trace it (journey
spans), and act on sustained pressure (control plane) — this module
*detects* one as it starts.  ``AlertEngine`` is a monitor thread (the
watchdog/controller stance: injectable clock + sleep, ``evaluate_once``
is the whole state machine so tests drive it synchronously) evaluating
declarative :class:`AlertRule`\\s against live engine state:

- **burn_rate** rules implement multi-window multi-burn-rate SLO
  alerting (the SRE-workbook shape): cumulative bad/total counters are
  sampled into a :class:`~vllm_omni_tpu.metrics.stats.DeltaRing` and
  the error-budget burn is computed over BOTH a fast (5m-style) and a
  slow (1h-style) window — the fast window gives low detection latency,
  the slow window stops a single bad second from paging.  All listed
  windows must exceed their threshold to fire.  A window not yet
  backed by a full span of history (early process life) has its burn
  scaled by real coverage, so the slow window holds pages back from
  the very first evaluation instead of degenerating into a second
  copy of the fast window.
- **rate** rules alert on counter velocity over windows (sheds/s,
  failovers/s) — delta over the REAL covered span once the window has
  history, with the nominal window as the floor before it does (the
  early-life guard again).
- **threshold** rules compare an instantaneous gauge (queue depth,
  p99-vs-target, saturation) against a bound, smoothed by
  ``for_duration_s``.
- **state** rules latch on booleans (watchdog tripped, degraded mode).

Lifecycle per rule: ``inactive -> pending -> firing -> resolved``
(pending holds for ``for_duration_s`` before firing — the hysteresis
that keeps a one-evaluation blip from paging), every transition lands
on a bounded ring and on /metrics (``alerts_firing{alert}``,
``alert_transitions_total{alert,to}`` riding the resilience registry).
A probe that raises is counted and SKIPPED — a broken probe must never
fire or resolve an alert (probe-error immunity).

A ``pending -> firing`` transition captures **evidence** while the bad
minute is still alive: one rate-limited dump document through the PR 8
``build_dump``/``dump_to_file`` path (reason ``alert:<name>``, gated on
``OMNI_TPU_FLIGHT_DIR`` and the per-reason dump cooldown) carrying the
flight-recorder tails, a journey-trace slice, every engine's top-k
tenant attribution board, and the rule's window values at the moment it
fired.  The control plane reads firing ``overload=True`` alerts as an
advisory early-shed signal (controlplane/controller.py).

Threading: the evaluation thread and ``force_firing`` (called from the
watchdog thread) both step per-rule lifecycle state — every state
WRITE happens under ``_lock`` (serialized check+set: the two sides
cannot double-land a firing edge), which also guards the rule table
and the transition ring (LOCK_GUARDS manifest); /debug/alerts and
/health READ the per-rule scalars lock-free in the watchdog's
GIL-atomic monitoring-read stance.  Evidence capture runs OUTSIDE the
lock — file writes under it would convoy every reader.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.stats import DeltaRing, burn_rate
from vllm_omni_tpu.resilience.metrics import resilience_metrics

logger = init_logger(__name__)

KIND_BURN = "burn_rate"
KIND_RATE = "rate"
KIND_THRESHOLD = "threshold"
KIND_STATE = "state"

STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"

#: transition ring capacity (bounded like the controlplane action ring)
TRANSITION_RING = 256


@dataclass
class AlertRule:
    """One declarative alert (docs/observability.md has the schema).

    ``probe`` returns a dict and must be cheap host reads only:
      - burn_rate: ``{"bad": cum_bad, "total": cum_total}``
      - rate:      ``{"count": cum_count}``
      - threshold/state: ``{"value": v}``
    ``windows`` is ``((window_s, threshold), ...)`` — burn/rate rules
    require EVERY window to exceed its threshold (multi-window);
    threshold rules use the first entry's threshold instantaneously.
    ``budget`` is the error budget (1 - SLO objective) for burn rules.
    ``overload=True`` marks the rule as an overload signal the control
    plane may read as advisory early-shed.  ``capture_evidence=False``
    skips the firing-edge dump (e.g. ``engine_stalled`` — the watchdog
    already wrote the richer trip dump)."""

    name: str
    kind: str
    probe: Callable[[], dict]
    windows: tuple = ()
    budget: float = 0.01
    for_duration_s: float = 0.0
    overload: bool = False
    capture_evidence: bool = True
    description: str = ""


class _RuleState:
    def __init__(self, rule: AlertRule, clock, interval_s: float):
        self.rule = rule
        horizon = max((w for w, _ in rule.windows), default=60.0) * 1.05
        # size the ring from horizon/cadence so the sample cap never
        # silently shortens a window: at OMNI_TPU_ALERTS_S=1 an hour
        # needs ~3800 samples, not DeltaRing's 720 default
        self.ring = DeltaRing(
            horizon_s=horizon,
            max_samples=max(720,
                            int(horizon / max(interval_s, 1e-3)) + 4),
            clock=clock)
        self.state = STATE_INACTIVE
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.last_values: dict = {}
        self.probe_errors = 0
        self.last_error: Optional[str] = None
        self.transitions = 0
        self.evidence_captured = 0
        self.last_evidence_path: Optional[str] = None


class AlertEngine:
    """The evaluation loop + its read-side views.

    ``evaluate_once()`` is the whole state machine (the thread just
    calls it on an interval) — tests and operators drive it with a
    fake clock, exactly like ``StallWatchdog.check_once``.
    """

    def __init__(self, rules: Optional[list[AlertRule]] = None, *,
                 interval_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.interval_s = float(interval_s)
        self._clock = clock
        self._sleep = sleep
        self._lock = traced(threading.Lock(), "AlertEngine._lock")
        self._rules: dict[str, _RuleState] = {}
        self._transitions: "list[dict]" = []
        self._on_firing: list[Callable[[str, dict], None]] = []
        # extra evidence sections: (key, fn) pairs merged into every
        # bundle's ``extra`` — how deployment-scoped boards (the
        # router's fleet cache board) join the dump without the
        # evidence path importing deployment shapes
        self._evidence_providers: list[
            tuple[str, Callable[[], Any]]] = []
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.evaluations = 0
        for r in rules or ():
            self.add_rule(r)

    # ------------------------------------------------------------- rules
    def add_rule(self, rule: AlertRule) -> None:
        if rule.kind not in (KIND_BURN, KIND_RATE, KIND_THRESHOLD,
                             KIND_STATE):
            raise ValueError(f"unknown alert kind {rule.kind!r}")
        with self._lock:
            self._rules[rule.name] = _RuleState(rule, self._clock,
                                                self.interval_s)
        # the gauge exists from registration so dashboards see 0, not
        # absence, before the first evaluation
        resilience_metrics.set_gauge("alerts_firing", 0,
                                     alert=rule.name)

    def on_firing(self, fn: Callable[[str, dict], None]) -> None:
        """Register ``fn(rule_name, transition_doc)`` called on every
        pending->firing edge (after the built-in evidence capture)."""
        self._on_firing.append(fn)

    def add_evidence_provider(self, key: str,
                              fn: Callable[[], Any]) -> None:
        """Register an extra evidence section: ``fn()`` runs at
        capture time (outside the lock, exceptions contained) and its
        JSON-ready return lands in the bundle under ``key``."""
        self._evidence_providers.append((key, fn))

    # --------------------------------------------------------- lifecycle
    def start(self) -> "AlertEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="alert-engine")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._closed = True

    def _loop(self) -> None:
        while not self._closed:
            self._sleep(self.interval_s)
            if self._closed:
                return
            try:
                self.evaluate_once()
            except Exception:  # the monitor must never kill serving
                logger.exception("alert evaluation failed")

    # ------------------------------------------------------- evaluation
    def evaluate_once(self) -> list[dict]:
        """Probe + evaluate every rule once; returns the transitions
        this evaluation produced.  Probe errors leave the rule's state
        untouched (immunity): a broken sensor is surfaced on
        /debug/alerts, never paged on."""
        now = self._clock()
        self.evaluations += 1
        with self._lock:
            states = list(self._rules.values())
        transitions: list[dict] = []
        fired: list[tuple[_RuleState, dict]] = []
        for rs in states:
            try:
                p = rs.rule.probe() or {}
            except Exception as e:
                rs.probe_errors += 1
                rs.last_error = repr(e)
                continue
            rs.last_error = None
            cond, values = self._condition(rs, p, now)
            rs.last_values = values
            # the lifecycle step AND the gauge run under the lock:
            # force_firing (the watchdog thread) mutates the same
            # per-rule state, and an unserialized check+set (or a
            # stale-state gauge write) would double-land a firing
            # edge or clobber a concurrent force's gauge=1 with 0
            with self._lock:
                t = self._advance(rs, cond, now, values)
                resilience_metrics.set_gauge(
                    "alerts_firing",
                    1 if rs.state == STATE_FIRING else 0,
                    alert=rs.rule.name)
            if t is not None:
                transitions.append(t)
                if t["to"] == STATE_FIRING:
                    fired.append((rs, t))
        # evidence + callbacks OUTSIDE the lock and after the sweep:
        # a slow dump must not delay the other rules' evaluation state
        for rs, t in fired:
            self._on_firing_edge(rs, t)
        return transitions

    def _condition(self, rs: _RuleState, p: dict, now: float
                   ) -> tuple[bool, dict]:
        rule = rs.rule
        values: dict[str, Any] = {}
        if rule.kind == KIND_BURN:
            rs.ring.sample({"bad": float(p.get("bad", 0.0)),
                            "total": float(p.get("total", 0.0))})
            ok = bool(rule.windows)
            for w, th in rule.windows:
                d_bad, _ = rs.ring.window_delta(w, "bad")
                d_total, span = rs.ring.window_delta(w, "total")
                b = burn_rate(d_bad, d_total, rule.budget)
                if 0 < span < w:
                    # under-covered window (early process life): treat
                    # the unobserved remainder as burn-free traffic at
                    # the same rate, i.e. scale by real coverage.  The
                    # slow window keeps its "one bad second cannot
                    # page" guarantee from the first evaluation on,
                    # while a burn SUSTAINED across the history that
                    # does exist still fires
                    b *= span / w
                values[f"burn_{w:g}s"] = round(b, 3)
                if not (span > 0 and b > th):
                    ok = False
            return ok, values
        if rule.kind == KIND_RATE:
            rs.ring.sample({"count": float(p.get("count", 0.0))})
            ok = bool(rule.windows)
            for w, th in rule.windows:
                d, span = rs.ring.window_delta(w, "count")
                # real span once the window is covered; the NOMINAL
                # window as the floor while it is not — the same
                # early-life stance as the burn scaling above (one
                # failover in a 10s-old process must not read as a
                # page-worthy sustained rate over a 5m window)
                r = d / max(span, w) if span > 0 else 0.0
                values[f"rate_{w:g}s"] = round(r, 4)
                if not r > th:
                    ok = False
            return ok, values
        if rule.kind == KIND_THRESHOLD:
            v = float(p.get("value", 0.0))
            th = rule.windows[0][1] if rule.windows else 0.0
            values["value"] = round(v, 4)
            values["threshold"] = th
            return v > th, values
        # KIND_STATE
        v = bool(p.get("value"))
        values["value"] = v
        return v, values

    def _advance(self, rs: _RuleState, cond: bool, now: float,
                 values: dict) -> Optional[dict]:
        """One lifecycle step; returns the transition doc if the state
        changed.  Caller holds ``_lock``."""
        if cond:
            if rs.state == STATE_INACTIVE:
                rs.pending_since = now
                t = self._transition(rs, STATE_PENDING, now, values)
                # zero for-duration fires on the SAME evaluation —
                # fall through so a duration-free rule still records
                # the pending edge (the lifecycle is observable)
                if rs.rule.for_duration_s > 0:
                    return t
            if (rs.state == STATE_PENDING
                    and now - (rs.pending_since or now)
                    >= rs.rule.for_duration_s):
                rs.firing_since = now
                return self._transition(rs, STATE_FIRING, now, values)
            return None
        if rs.state == STATE_FIRING:
            rs.firing_since = None
            rs.pending_since = None
            return self._transition(rs, "resolved", now, values)
        if rs.state == STATE_PENDING:
            # the pending window broke before for_duration: back to
            # inactive without ever firing (the flap the hysteresis
            # exists to absorb)
            rs.pending_since = None
            return self._transition(rs, STATE_INACTIVE, now, values)
        return None

    def _transition(self, rs: _RuleState, to: str, now: float,
                    values: dict) -> Optional[dict]:
        """Record one state change.  Caller holds ``_lock``; returns
        None when another thread already landed the same target state
        (the force_firing/evaluate race both sides must lose at most
        once)."""
        new_state = STATE_INACTIVE if to in ("resolved",
                                             STATE_INACTIVE) else to
        if rs.state == new_state:
            return None
        frm = rs.state
        rs.state = new_state
        rs.transitions += 1
        doc = {"alert": rs.rule.name, "from": frm, "to": to,
               "t": round(now, 3), "ts": time.time(),
               "values": dict(values)}
        self._transitions.append(doc)
        del self._transitions[:-TRANSITION_RING]
        resilience_metrics.inc("alert_transitions_total",
                               alert=rs.rule.name, to=to)
        if to in (STATE_FIRING, "resolved"):
            logger.warning("alert %s: %s -> %s %s", rs.rule.name, frm,
                           to, values)
        return doc

    def force_firing(self, name: str, reason: str = "forced") -> bool:
        """Latch a rule straight to firing (the watchdog's ``on_trip``
        wiring: one source of truth for "this replica is wedged").
        Returns False for an unknown rule or one already firing —
        including one the evaluation thread fires concurrently."""
        now = self._clock()
        with self._lock:
            rs = self._rules.get(name)
            if rs is None:
                return False
            t = self._transition(rs, STATE_FIRING, now,
                                 {"forced": reason})
            if t is None:        # already firing (or lost the race)
                return False
            rs.firing_since = now
            rs.last_values = {"forced": reason}
            resilience_metrics.set_gauge("alerts_firing", 1,
                                         alert=name)
        self._on_firing_edge(rs, t)
        return True

    # --------------------------------------------------------- evidence
    def _on_firing_edge(self, rs: _RuleState, t: dict) -> None:
        if rs.rule.capture_evidence:
            try:
                path = capture_evidence(
                    rs.rule.name, t, snapshot=self.snapshot,
                    providers=list(self._evidence_providers))
            except Exception:
                logger.exception("alert evidence capture failed")
                path = None
            if path is not None:
                rs.evidence_captured += 1
                rs.last_evidence_path = path
        for fn in list(self._on_firing):
            try:
                fn(rs.rule.name, t)
            except Exception:
                logger.exception("alert on_firing callback failed")

    # ---------------------------------------------------------- reading
    def firing(self) -> dict:
        """{name: {"since_s", "values", "overload"}} for firing rules."""
        now = self._clock()
        with self._lock:
            states = list(self._rules.values())
        return {
            rs.rule.name: {
                "since_s": (round(now - rs.firing_since, 3)
                            if rs.firing_since is not None else 0.0),
                "values": dict(rs.last_values),
                "overload": rs.rule.overload,
            }
            for rs in states if rs.state == STATE_FIRING
        }

    def firing_overload(self) -> list[str]:
        """Names of firing rules marked ``overload=True`` — the control
        plane's advisory early-shed signal."""
        with self._lock:
            states = list(self._rules.values())
        return sorted(rs.rule.name for rs in states
                      if rs.state == STATE_FIRING and rs.rule.overload)

    def snapshot(self) -> dict:
        """/debug/alerts: every rule's declaration + live state, the
        transition-ring tail, and the dump-cooldown self-view (the
        rate limit evidence capture rides)."""
        from vllm_omni_tpu.introspection.flight_recorder import (
            dump_cooldown,
        )

        now = self._clock()
        with self._lock:
            states = list(self._rules.values())
            ring = list(self._transitions[-64:])
        rules = {}
        for rs in states:
            r = rs.rule
            rules[r.name] = {
                "kind": r.kind,
                "state": rs.state,
                "overload": r.overload,
                "description": r.description,
                "windows": [list(w) for w in r.windows],
                "budget": r.budget if r.kind == KIND_BURN else None,
                "for_duration_s": r.for_duration_s,
                "pending_for_s": (round(now - rs.pending_since, 3)
                                  if rs.pending_since is not None
                                  else None),
                "firing_for_s": (round(now - rs.firing_since, 3)
                                 if rs.firing_since is not None
                                 else None),
                "last_values": dict(rs.last_values),
                "probe_errors": rs.probe_errors,
                "last_probe_error": rs.last_error,
                "transitions": rs.transitions,
                "evidence": {
                    "captured": rs.evidence_captured,
                    "last_path": rs.last_evidence_path,
                    "enabled": r.capture_evidence,
                },
            }
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "running": self._thread is not None and not self._closed,
            "evaluations": self.evaluations,
            "firing": sorted(n for n, d in rules.items()
                             if d["state"] == STATE_FIRING),
            "rules": rules,
            "transitions": ring,
            "dump_cooldown": dump_cooldown.snapshot(),
        }


# ------------------------------------------------------------- evidence
def capture_evidence(name: str, transition: dict,
                     snapshot: Optional[Callable[[], dict]] = None,
                     providers: tuple = ()
                     ) -> Optional[str]:
    """Assemble and write one alert evidence bundle through the flight
    recorder's dump path: the per-engine step-record rings, a journey-
    trace slice (the recorder's most recent spans, non-destructive),
    every engine's top-k tenant attribution board, any registered
    extra provider sections (e.g. the fleet cache board — a hit-rate
    collapse captures WHICH prefixes scattered), and the firing
    rule's window values.  Returns the written path, or None when
    ``OMNI_TPU_FLIGHT_DIR`` is unset or the per-reason cooldown
    suppressed the write (a flapping alert must not flood the dir)."""
    from vllm_omni_tpu import introspection
    from vllm_omni_tpu.introspection.flight_recorder import (
        _dumping_enabled,
        build_dump,
        dump_to_file,
    )
    from vllm_omni_tpu.tracing import get_recorder

    if not _dumping_enabled():
        return None
    engines = introspection.iter_engines()
    attribution = {}
    for i, e in enumerate(engines):
        attr = getattr(e, "attribution", None)
        if attr is not None:
            # claim_slots=False: an evidence bundle must not burn
            # lifetime /metrics label slots on incident-time tenants
            attribution[str(getattr(e, "stage_id", i))] = \
                attr.snapshot(claim_slots=False)
    extra: dict[str, Any] = {
        "alert": {
            "name": name,
            "transition": dict(transition),
            "engine": snapshot() if snapshot is not None else None,
        },
        "attribution": attribution,
        "journey_tail": get_recorder().tail(256),
        "requests": [
            {"engine": getattr(e, "stage_id", i),
             "table": introspection.request_table(e)}
            for i, e in enumerate(engines)
        ],
    }
    for key, fn in providers:
        try:
            extra[key] = fn()
        except Exception as e:  # one broken board must not void the rest
            extra[key] = {"error": repr(e)}
    doc = build_dump(
        f"alert:{name}",
        recorders=[e.flight for e in engines
                   if getattr(e, "flight", None) is not None],
        extra=extra, include_stacks=False)
    return dump_to_file(doc)


# -------------------------------------------------------- default rules
def build_default_rules(
    omni, *,
    fast_window_s: float = 300.0,
    slow_window_s: float = 3600.0,
    fast_burn: float = 14.4,
    slow_burn: float = 6.0,
    slo_objective: float = 0.99,
    queue_depth_limit: Optional[float] = None,
    saturation_limit: float = 0.98,
    shed_rate_limit: float = 0.5,
    failover_rate_limit: float = 0.1,
    latency_mult: float = 1.0,
    for_duration_s: float = 15.0,
    prefix_hit_objective: float = 0.5,
) -> list[AlertRule]:
    """The stock rule set over an ``Omni``-shaped orchestrator (probes
    are getattr-defensive duck-typed reads, the debugz stance).  SLO
    burn rules only engage once traffic produces judged completions;
    latency rules only exist when SLO targets are configured."""

    def engines():
        return [e for e in (getattr(s, "engine", None)
                            for s in getattr(omni, "stages", ()))
                if e is not None
                and getattr(e, "step_metrics", None) is not None]

    def slo_probe() -> dict:
        bad = total = 0
        for e in engines():
            t = e.step_metrics.slo_totals()
            bad += t["bad"]
            total += t["finished"]
        return {"bad": bad, "total": total}

    def shed_probe() -> dict:
        n = 0
        for e in engines():
            counts = getattr(getattr(e, "scheduler", None),
                             "shed_counts", None) or {}
            n += sum(counts.values())
        return {"count": n}

    def failover_probe() -> dict:
        samples = resilience_metrics.snapshot().get(
            "failover_total", [])
        return {"count": sum(v for _, v in samples)}

    def queue_probe() -> dict:
        return {"value": sum(
            len(getattr(getattr(e, "scheduler", None), "waiting", ()))
            for e in engines())}

    def saturation_probe() -> dict:
        v = 0.0
        for e in engines():
            sat = getattr(e.step_metrics, "saturation", None) or {}
            v = max(v, *sat.values()) if sat else v
        return {"value": v}

    def watchdog_probe() -> dict:
        wd = getattr(omni, "watchdog", None)
        return {"value": wd is not None
                and getattr(wd, "tripped", None) is not None}

    def degraded_probe() -> dict:
        samples = resilience_metrics.snapshot().get("degraded_mode", [])
        return {"value": any(v for _, v in samples)}

    def prefix_probe() -> dict:
        """Burn shape over prefix-cache economics: bad = prompt tokens
        PREFILLED (cache misses), total = hit + prefilled.  Prefers
        the disagg router's fleet board; single-engine deployments
        fall back to summing engine counters."""
        cache = getattr(getattr(omni, "router", None), "cache", None)
        if cache is not None:
            expo = cache.exposition()
            bad = int(expo.get("fleet_prefill_tokens", 0))
            return {"bad": bad,
                    "total": int(expo.get("fleet_hit_tokens", 0)) + bad}
        bad = total = 0
        for e in engines():
            kv = getattr(getattr(e, "scheduler", None), "kv", None)
            if kv is None or not getattr(kv, "enable_prefix_caching",
                                         False):
                continue
            prefill = int(getattr(e.step_metrics, "prefill_tokens", 0))
            bad += prefill
            total += int(getattr(kv, "prefix_hit_tokens", 0)) + prefill
        return {"bad": bad, "total": total}

    budget = max(1.0 - slo_objective, 1e-9)
    rules = [
        AlertRule(
            name="slo_fast_burn", kind=KIND_BURN, probe=slo_probe,
            windows=((fast_window_s, fast_burn),
                     (slow_window_s, fast_burn)),
            budget=budget, overload=True,
            description="error budget burning at page speed in BOTH "
                        "the fast and slow windows"),
        AlertRule(
            name="slo_slow_burn", kind=KIND_BURN, probe=slo_probe,
            windows=((slow_window_s, slow_burn),),
            budget=budget, for_duration_s=for_duration_s,
            description="sustained slow burn (ticket, not page)"),
        AlertRule(
            name="queue_depth_high", kind=KIND_THRESHOLD,
            probe=queue_probe,
            windows=((0.0, queue_depth_limit
                      if queue_depth_limit is not None else 64.0),),
            for_duration_s=for_duration_s, overload=True,
            description="fleet waiting-queue depth past the bound"),
        AlertRule(
            name="saturation_high", kind=KIND_THRESHOLD,
            probe=saturation_probe,
            windows=((0.0, saturation_limit),),
            for_duration_s=for_duration_s, overload=True,
            description="a phase capacity axis pinned at its ceiling"),
        AlertRule(
            name="shed_rate_high", kind=KIND_RATE, probe=shed_probe,
            windows=((fast_window_s, shed_rate_limit),),
            overload=True,
            description="admission control shedding arrivals (429s/s "
                        "over the fast window)"),
        AlertRule(
            name="failover_rate_high", kind=KIND_RATE,
            probe=failover_probe,
            windows=((fast_window_s, failover_rate_limit),),
            description="disagg router re-routing requests (replica "
                        "deaths / handoff failures per second)"),
        AlertRule(
            name="engine_stalled", kind=KIND_STATE,
            probe=watchdog_probe, capture_evidence=False,
            description="stall watchdog tripped (the trip dump is the "
                        "evidence; /health already serves 503)"),
        AlertRule(
            name="degraded_mode", kind=KIND_STATE,
            probe=degraded_probe,
            description="router serving colocated because a tier has "
                        "zero healthy replicas"),
        AlertRule(
            name="prefix_hit_rate_low", kind=KIND_BURN,
            probe=prefix_probe,
            windows=((fast_window_s, 1.0),),
            budget=max(1.0 - prefix_hit_objective, 1e-9),
            for_duration_s=for_duration_s,
            description="fleet prefix hit rate below objective: the "
                        "miss budget (prefilled / total prompt "
                        "tokens) burning at >1x over the fast window"),
    ]
    # latency-vs-target rules need a target to compare against; the
    # Histogram's percentile() is already a bounded recent window
    cfg_engines = engines()
    slo_ttft = next((e.step_metrics.slo_ttft_ms for e in cfg_engines
                     if e.step_metrics.slo_ttft_ms is not None), None)
    slo_tpot = next((e.step_metrics.slo_tpot_ms for e in cfg_engines
                     if e.step_metrics.slo_tpot_ms is not None), None)
    if slo_ttft is not None:
        rules.append(AlertRule(
            name="ttft_p_high", kind=KIND_THRESHOLD,
            probe=lambda: {"value": max(
                (e.step_metrics.ttft_ms.percentile(0.99)
                 for e in engines()), default=0.0)},
            windows=((0.0, slo_ttft * latency_mult),),
            for_duration_s=for_duration_s,
            description="recent-window p99 TTFT past the SLO target"))
    if slo_tpot is not None:
        rules.append(AlertRule(
            name="tpot_p_high", kind=KIND_THRESHOLD,
            probe=lambda: {"value": max(
                (e.step_metrics.tpot_ms.percentile(0.99)
                 for e in engines()), default=0.0)},
            windows=((0.0, slo_tpot * latency_mult),),
            for_duration_s=for_duration_s,
            description="recent-window p99 TPOT past the SLO target"))
    return rules
