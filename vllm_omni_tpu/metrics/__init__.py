from vllm_omni_tpu.metrics.stats import (
    OrchestratorAggregator,
    RequestE2EStats,
    StageRequestStats,
    StageStats,
    TransferEdgeStats,
)

__all__ = [
    "OrchestratorAggregator",
    "RequestE2EStats",
    "StageRequestStats",
    "StageStats",
    "TransferEdgeStats",
]
