"""Per-tenant heavy-hitter attribution: bounded-memory answer to
"which tenant is eating the fleet".

The SLO ledger (stats.py ``TenantSLOStats``) already keys on tenant,
but it is capped at ``MAX_TENANT_SERIES`` distinct labels and collapses
everyone else into "other" — correct for /metrics cardinality, useless
for attribution: at millions-of-users scale the tenant that suddenly
floods the fleet is overwhelmingly likely to be one of the collapsed
ones.  This module meters consumption per tenant in O(capacity) memory
regardless of how many tenants exist, using the **space-saving** sketch
(Misra–Gries family; Metwally et al. 2005) with its textbook guarantees:

- for every tracked key: ``est - err <= true <= est`` (the per-key
  ``err`` records the count inherited from the evicted victim);
- every key whose true count exceeds ``total / capacity`` is GUARANTEED
  to be tracked — a genuine heavy hitter can never be missed;
- the overestimate of ANY key is at most ``total / capacity``.

``TenantAttribution`` runs one sketch per *meter* (prefill/decode
tokens, KV page·seconds per tier, handoff bytes, queue wait, sheds) so
each resource axis has its own heavy-hitter board.  Top-k export stays
inside the existing ``cap_tenant`` cardinality budget: /metrics renders
at most ``EXPORT_TOP_K`` tenants per meter, and — because top-k bounds
a scrape but adversarial churn makes its membership over time
unbounded, while every label value lives forever in the scrape
database — each snapshot row carries an ``export`` flag backed by a
LIFETIME set of at most ``MAX_TENANT_SERIES`` distinct tenants (slots
claimed on first top-k appearance, so a shed-flooding tenant that
never finishes a request still gets one).  Per-key estimates never
decrease, so exported series stay monotone (counter-safe).  The full
uncapped board is on ``/debug/tenants``.

Hot-path discipline: ``add()`` is called from the engine step loop
(this file rides the omnilint OL2 HOT_PATHS manifest) — pure host
dict/heap arithmetic, zero device syncs.  Thread contract: the engine
thread adds while /metrics and /debug snapshot, so the per-instance
lock guards the sketch tables (LOCK_GUARDS manifest).
"""

from __future__ import annotations

import heapq
import threading
from typing import Iterable, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.metrics.stats import MAX_TENANT_SERIES, sanitize_tenant

#: meters a TenantAttribution tracks by default — one sketch each.
#: Units differ per meter (documented in docs/observability.md):
#: tokens, page·seconds, bytes, milliseconds, request counts.
METERS = (
    "prefill_tokens",
    "decode_tokens",
    "kv_page_seconds_hbm",
    "kv_page_seconds_host",
    "handoff_bytes",
    "queue_wait_ms",
    "sheds",
    # wasted re-prefill tokens (metrics/cache_economics.py): added by
    # the disagg router at dispatch time, so per-tenant redundancy
    # rides the same sketch/export machinery as every other meter
    "duplicate_prefill_tokens",
)

#: tenants exported per meter on /metrics — strictly inside the
#: MAX_TENANT_SERIES cardinality cap (stats.py) so attribution can
#: never widen the exposition past what the SLO ledger already allows
EXPORT_TOP_K = 16


class SpaceSavingSketch:
    """Space-saving heavy hitters over weighted increments.

    ``capacity`` bounds memory: at most that many (key -> [est, err])
    counters exist, ever.  When a new key arrives at a full table, the
    key with the MINIMUM estimate is evicted and the newcomer inherits
    its estimate as ``err`` (the possible overcount).  Increments are
    floats so page·seconds and byte meters ride the same structure.

    Eviction needs the current minimum; a lazy min-heap of
    ``(est_at_push, key)`` keeps that amortized O(log n) — entries go
    stale when their key's count grows (counts only grow), so the pop
    loop discards entries that no longer match the live table.

    NOT thread-safe on its own — TenantAttribution holds the lock.
    """

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        # key -> [estimate, error]; error = estimate inherited from the
        # evicted victim (0 for keys admitted into free space)
        self._counts: dict[str, list] = {}
        # lazy min-heap over (estimate, key); stale entries (estimate
        # no longer current) are discarded at pop time
        self._heap: list[tuple[float, str]] = []
        self.total = 0.0

    def add(self, key: str, amount: float = 1.0) -> None:
        if amount <= 0:
            return
        self.total += amount
        if len(self._heap) > 8 * self.capacity:
            # stale-entry compaction: the lazy heap gains one entry per
            # add and only sheds them at eviction pops — rebuild from
            # the live table so a long-running engine stays O(capacity)
            self._heap = [(row[0], k)
                          for k, row in self._counts.items()]
            heapq.heapify(self._heap)
        row = self._counts.get(key)
        if row is not None:
            row[0] += amount
            heapq.heappush(self._heap, (row[0], key))
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = [amount, 0.0]
            heapq.heappush(self._heap, (amount, key))
            return
        # full: evict the minimum-estimate key; the newcomer inherits
        # its estimate as the error bound (the space-saving move)
        while self._heap:
            est, victim = heapq.heappop(self._heap)
            row = self._counts.get(victim)
            if row is not None and row[0] == est:
                break
        else:  # pragma: no cover - heap always covers the live table
            victim, est = min(
                self._counts.items(), key=lambda kv: kv[1][0])[0], 0.0
            est = self._counts[victim][0]
        del self._counts[victim]
        self._counts[key] = [est + amount, est]
        heapq.heappush(self._heap, (est + amount, key))

    def __len__(self) -> int:
        return len(self._counts)

    def estimate(self, key: str) -> tuple[float, float]:
        """(estimate, error) for ``key``; (0, 0) when untracked."""
        row = self._counts.get(key)
        return (row[0], row[1]) if row is not None else (0.0, 0.0)

    @property
    def max_overestimate(self) -> float:
        """The proven bound: no estimate exceeds truth by more than
        ``total / capacity`` (tight only under adversarial churn)."""
        return self.total / self.capacity

    def top(self, k: int) -> list[tuple[str, float, float]]:
        """The k largest estimates as (key, est, err), descending.
        Deterministic tie-break on the key so snapshots are stable."""
        rows = sorted(self._counts.items(),
                      key=lambda kv: (-kv[1][0], kv[0]))
        return [(key, row[0], row[1]) for key, row in rows[:k]]


class TenantAttribution:
    """One space-saving sketch per consumption meter, keyed by
    sanitized tenant.  The engine adds; /metrics and /debug/tenants
    snapshot — the lock guards the sketch tables."""

    def __init__(self, capacity: int = 256,
                 meters: Iterable[str] = METERS,
                 export_cap: int = MAX_TENANT_SERIES):
        self.capacity = capacity
        self.export_cap = export_cap
        self._lock = traced(threading.Lock(), "TenantAttribution._lock")
        self._meters: dict[str, SpaceSavingSketch] = {
            m: SpaceSavingSketch(capacity) for m in meters}
        # lifetime /metrics label budget: the first ``export_cap``
        # distinct tenants to reach any meter's top-k claim the slots
        self._exported: set[str] = set()

    def add(self, tenant: Optional[str], meter: str,
            amount: float = 1.0) -> None:
        """Meter ``amount`` of ``meter`` against ``tenant``.  The
        tenant is CLIENT input — sanitized here so hostile bytes never
        become sketch keys (the sketch itself bounds cardinality, so
        no ``cap_tenant`` collapse: attribution exists precisely to
        see past that cap)."""
        sketch = self._meters.get(meter)
        if sketch is None or amount <= 0:
            return
        key = sanitize_tenant(tenant)
        with self._lock:
            sketch.add(key, float(amount))

    def top_k(self, meter: str, k: int = EXPORT_TOP_K
              ) -> list[tuple[str, float, float]]:
        sketch = self._meters.get(meter)
        if sketch is None:
            return []
        with self._lock:
            return sketch.top(k)

    def _exportable(self, key: str) -> bool:
        """Lifetime label-budget check (caller holds the lock): a
        tenant already holding a slot, or one claiming a free slot
        now, renders on /metrics; everyone else is /debug-only."""
        if key in self._exported:
            return True
        if len(self._exported) < self.export_cap:
            self._exported.add(key)
            return True
        return False

    def snapshot(self, k: int = EXPORT_TOP_K, *,
                 claim_slots: bool = True) -> dict:
        """JSON-ready per-meter heavy-hitter board (the
        ``/debug/tenants`` and engine-snapshot shape): top-k rows with
        estimate + error + the lifetime ``export`` flag, tracked-key
        count, the lifetime total, and the proven overestimate bound.
        ``claim_slots=False`` reports current slot membership without
        consuming any — debug and evidence readers must not burn the
        /metrics label budget on tenants the exposition never saw."""
        doc: dict[str, dict] = {}
        with self._lock:
            for meter, sketch in self._meters.items():
                doc[meter] = {
                    "total": round(sketch.total, 3),
                    "tenants_tracked": len(sketch),
                    "max_overestimate": round(
                        sketch.max_overestimate, 3),
                    "top": [
                        {"tenant": key, "est": round(est, 3),
                         "err": round(err, 3),
                         "export": (self._exportable(key)
                                    if claim_slots
                                    else key in self._exported)}
                        for key, est, err in sketch.top(k)
                    ],
                }
        return {"capacity": self.capacity, "meters": doc}
