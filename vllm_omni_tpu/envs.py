"""Environment-variable registry.

Equivalent in role to the reference's ``vllm_omni/diffusion/envs.py:19`` env
registry: one module that owns every environment knob, with typed accessors,
so flags are discoverable and greppable.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

# name -> (default, parser)
_ENV_REGISTRY: dict[str, tuple[str, Callable[[str], object]]] = {}


def _register(name: str, default: str, parser: Callable[[str], object]):
    _ENV_REGISTRY[name] = (default, parser)


def _get(name: str):
    default, parser = _ENV_REGISTRY[name]
    return parser(os.environ.get(name, default))


_bool = lambda s: s.lower() in ("1", "true", "yes", "on")

# Attention backend override for DiT stages (reference:
# DIFFUSION_ATTENTION_BACKEND, attention/selector.py:77). Values:
# "pallas_flash", "xla", "auto".
_register("OMNI_TPU_DIFFUSION_ATTENTION_BACKEND", "auto", str)
# Attention backend for AR paged attention: "pallas_paged", "xla", "auto".
_register("OMNI_TPU_AR_ATTENTION_BACKEND", "auto", str)
# Force interpret mode for pallas kernels (CPU testing).
_register("OMNI_TPU_PALLAS_INTERPRET", "0", _bool)
# Directory for jax profiler traces (reference: VLLM_TORCH_PROFILER_DIR).
_register("OMNI_TPU_PROFILER_DIR", "", str)
# Stats jsonl output (reference: --log-stats).
_register("OMNI_TPU_STATS_DIR", "", str)
# Per-request trace output path PREFIX ({prefix}.trace.jsonl +
# {prefix}.trace.json Chrome trace) — the env face of Omni(trace_path=).
_register("OMNI_TPU_TRACE_PATH", "", str)
# Connector backend default for single-node stage transfer.
_register("OMNI_TPU_CONNECTOR", "shm", str)
# Per-stage logging prefix.
_register("OMNI_TPU_LOGGING_PREFIX", "", str)
# Root log level for the package logger.
_register("OMNI_TPU_LOG_LEVEL", "INFO", str)
# RNG seed default.
_register("OMNI_TPU_SEED", "0", int)
# Default end-to-end request deadline in seconds (0 = unbounded); per
# call / per request values override (resilience/deadline.py).
_register("OMNI_TPU_DEFAULT_DEADLINE_S", "0", float)
# Fault-injection plan, e.g. "seed=42;stage1:kill_after=2;conn:drop_pct=0.2"
# (resilience/faults.py grammar).  Inherited by spawned stage workers.
_register("OMNI_TPU_FAULTS", "", str)
# Flight-recorder dump directory (introspection/flight_recorder.py):
# crash/SIGUSR2/watchdog dumps land here as JSON; empty disables the
# file-writing face (the in-memory ring and /debug endpoints stay on).
_register("OMNI_TPU_FLIGHT_DIR", "", str)
# Per-engine flight-recorder ring capacity (step records kept).
_register("OMNI_TPU_FLIGHT_CAPACITY", "256", int)
# Alert-engine evaluation interval in seconds (metrics/alerts.py):
# > 0 starts the evaluation thread over the default burn-rate/overload
# rule set.  0 (default) builds the engine without the thread — tests
# and operators drive evaluate_once() directly, and /debug/alerts
# still answers.
_register("OMNI_TPU_ALERTS_S", "0", float)
# Per-reason flight-dump cooldown in seconds (introspection/
# flight_recorder.py DumpCooldown): repeated dumps with the same
# reason into the same OMNI_TPU_FLIGHT_DIR within the window are
# suppressed (and counted) — a flapping alert or a held-down SIGUSR2
# must not flood the incident directory.  0 disables the limit.
_register("OMNI_TPU_DUMP_COOLDOWN_S", "30", float)
# Stall-watchdog deadline in seconds (introspection/watchdog.py): a
# busy engine making no step progress for this long — with no XLA
# compile in flight — trips the watchdog (dump + /health 503).
# 0 disables the monitor thread (the default: compiles on remote chips
# legitimately stall for tens of seconds, so the deadline is a
# deployment decision).
_register("OMNI_TPU_WATCHDOG_S", "0", float)


def __getattr__(name: str):
    if name in _ENV_REGISTRY:
        return _get(name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def env_names() -> list[str]:
    return sorted(_ENV_REGISTRY)
