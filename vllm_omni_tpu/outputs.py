"""Request output types.

Mirrors the reference's ``OmniRequestOutput`` union surface (reference:
vllm_omni/outputs.py:66,90 — one type covering pipeline-stage text outputs
and diffusion image/audio/video outputs, with ``from_pipeline`` /
``from_diffusion`` constructors).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class CompletionOutput:
    index: int
    token_ids: list[int]
    text: Optional[str] = None
    finish_reason: Optional[str] = None
    # per-token logprob dicts when the request asked for logprobs
    logprobs: Optional[list] = None


@dataclass
class OmniRequestOutput:
    request_id: str
    finished: bool = False
    # AR pipeline fields
    prompt_token_ids: list[int] = field(default_factory=list)
    outputs: list[CompletionOutput] = field(default_factory=list)
    # which stage produced this output + what modality it is
    # (reference: engine_output_type text/latent/audio/image)
    stage_id: int = 0
    final_output_type: str = "text"
    # diffusion / multimodal payloads (PIL images, waveforms, latents, ...)
    images: list[Any] = field(default_factory=list)
    multimodal_output: dict[str, Any] = field(default_factory=dict)
    metrics: dict[str, float] = field(default_factory=dict)
    # the source request's additional_information, carried along so
    # stage input processors can propagate per-request conditioning
    # (voice vectors, reference audio) to downstream stages
    additional_information: dict[str, Any] = field(default_factory=dict)

    @property
    def is_error(self) -> bool:
        """True when any completion finished with an error — error outputs
        terminate the request at the stage that produced them instead of
        feeding garbage to downstream stages."""
        return any(c.finish_reason == "error" for c in self.outputs)

    @property
    def error_message(self) -> Optional[str]:
        if not self.is_error:
            return None
        msg = self.multimodal_output.get("error")
        if msg:
            return str(msg)
        for c in self.outputs:
            if c.finish_reason == "error" and c.text:
                return c.text
        return "request failed"

    @property
    def error_kind(self) -> Optional[str]:
        """"invalid_request" (client's fault, HTTP 400) | "internal"
        (500) | "deadline_exceeded" (time budget spent, 504) |
        "retryable" (transient infra failure before any output — e.g. a
        stage worker died mid-execution — safe to resubmit, 503) |
        "shed" (admission control refused a healthy server at capacity
        — back off and retry, 429; see docs/load_testing.md)."""
        if not self.is_error:
            return None
        return self.multimodal_output.get("error_kind", "internal")

    @classmethod
    def from_error(cls, request_id: str, message: str, stage_id: int = 0,
                   kind: str = "internal"):
        return cls(
            request_id=request_id,
            finished=True,
            outputs=[CompletionOutput(
                index=0, token_ids=[], text=message, finish_reason="error",
            )],
            stage_id=stage_id,
            multimodal_output={"error": message, "error_kind": kind},
        )

    @classmethod
    def from_pipeline(cls, request, stage_id: int = 0, text: Optional[str] = None):
        mm = dict(request.multimodal_output)
        if request.finish_reason == "error":
            if request.additional_information.get("error"):
                mm.setdefault("error",
                              request.additional_information["error"])
            if request.additional_information.get("error_kind"):
                mm.setdefault("error_kind",
                              request.additional_information["error_kind"])
        return cls(
            request_id=request.request_id,
            finished=request.is_finished,
            prompt_token_ids=list(request.prompt_token_ids),
            outputs=[CompletionOutput(
                index=0,
                token_ids=list(request.output_token_ids),
                text=text,
                finish_reason=request.finish_reason,
                logprobs=(list(request.output_logprobs)
                          if request.output_logprobs else None),
            )],
            stage_id=stage_id,
            final_output_type="text",
            multimodal_output=mm,
            additional_information=dict(request.additional_information),
        )

    @classmethod
    def from_diffusion(cls, request_id: str, images: list, final_output_type: str = "image"):
        return cls(
            request_id=request_id,
            finished=True,
            images=list(images),
            final_output_type=final_output_type,
        )
