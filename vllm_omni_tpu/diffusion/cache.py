"""Step-cache acceleration for DiT denoise loops (TeaCache analogue).

Reference: vllm_omni/diffusion/cache/ — ``CacheBackend`` ABC (base.py:31),
selector (selector.py:9), and the TeaCache hook skipping transformer
evaluations when the timestep-modulated input changed little
(teacache/hook.py:30, rel-L1 accumulation vs threshold).  The reference
reports 1.5-2.0x speedup at preserved quality
(docs/user_guide/diffusion_acceleration.md:15).

TPU-first mechanics: the reference installs Python forward-hooks that
branch per step — impossible under jit.  Here the skip decision is a
``lax.cond`` *inside* the compiled denoise loop: both branches are traced
once, the TPU executes only the taken branch at runtime, so skipped steps
genuinely save the DiT forward while the whole loop stays one XLA
computation.  State (last velocity, last input, accumulated rel-L1) rides
the ``fori_loop`` carry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class StepCacheConfig:
    # "teacache": input-drift gate skipping the WHOLE model eval
    # "dbcache": dual-block cache (reference:
    #   diffusion/cache/cache_dit_backend.py DBCacheConfig) — the first
    #   ``fn_compute_blocks`` transformer blocks ALWAYS compute (a fresh
    #   anchor every step), their output drift gates reuse of a cached
    #   tail-contribution delta; higher quality than whole-model skipping
    #   because part of the network tracks every step
    backend: str = "teacache"     # "" disables
    rel_l1_threshold: float = 0.15
    # never skip the first/last steps (quality anchors, mirroring the
    # reference's warmup + final-step guards)
    warmup_steps: int = 1
    tail_steps: int = 1
    # dbcache: number of leading blocks always computed
    fn_compute_blocks: int = 4

    @property
    def enabled(self) -> bool:
        return bool(self.backend)

    @staticmethod
    def from_dict(backend: str, d: dict) -> "StepCacheConfig":
        known = {k: v for k, v in (d or {}).items()
                 if k in StepCacheConfig.__dataclass_fields__ and k != "backend"}
        return StepCacheConfig(backend=backend, **known)


def init_carry(latents: jax.Array):
    """(prev_velocity, prev_input, accumulated rel-L1) — accum starts at
    +inf so step 0 always computes."""
    return (
        jnp.zeros_like(latents),
        latents,
        jnp.asarray(jnp.inf, jnp.float32),
    )


def cached_eval(
    cache_cfg: StepCacheConfig,
    eval_fn: Callable[[jax.Array], jax.Array],
    latents: jax.Array,
    carry,
    i: jax.Array,
    num_steps: jax.Array,
):
    """Evaluate (or reuse) the velocity for this step.

    Returns (velocity, new_carry, skipped_flag).  ``eval_fn(latents)`` must
    be shape-preserving from latents to velocity.
    """
    prev_v, prev_lat, accum = carry
    diff = jnp.mean(jnp.abs(
        latents.astype(jnp.float32) - prev_lat.astype(jnp.float32)))
    base = jnp.mean(jnp.abs(prev_lat.astype(jnp.float32)))
    rel = diff / jnp.maximum(base, 1e-8)
    accum_new = accum + rel

    in_window = (i >= cache_cfg.warmup_steps) & (
        i < num_steps - cache_cfg.tail_steps
    )
    skip = in_window & (accum_new < cache_cfg.rel_l1_threshold)

    def do_skip(_):
        # reuse the previous velocity; keep accumulating drift
        return prev_v, prev_lat, accum_new

    def do_compute(_):
        # match the carry dtype (CFG guidance math may promote to f32)
        v = eval_fn(latents).astype(prev_v.dtype)
        # reset the accumulator relative to this freshly-computed input
        return v, latents, jnp.asarray(0.0, jnp.float32)

    v, new_prev_lat, new_accum = jax.lax.cond(skip, do_skip, do_compute, None)
    return v, (v, new_prev_lat, new_accum), skip


def dbcache_init_carry(latents: jax.Array):
    """(prev_anchor_velocity, cached_tail_delta, accumulated rel-L1)."""
    return (
        jnp.zeros_like(latents),
        jnp.zeros_like(latents),
        jnp.asarray(jnp.inf, jnp.float32),
    )


def dbcache_eval(
    cache_cfg: StepCacheConfig,
    eval_first: Callable,   # (latents) -> (state, anchor_velocity)
    eval_rest: Callable,    # (state) -> full_velocity
    latents: jax.Array,
    carry,
    i: jax.Array,
    num_steps: jax.Array,
):
    """Dual-block cached velocity: the anchor (first Fn blocks + output
    head) computes EVERY step; when its drift since the last full compute
    stays under threshold, the cached tail delta (full - anchor) is
    reused instead of running the remaining blocks.

    Returns (velocity, new_carry, skipped_flag)."""
    prev_anchor, delta, accum = carry
    state, v_anchor = eval_first(latents)
    v_anchor = v_anchor.astype(prev_anchor.dtype)
    diff = jnp.mean(jnp.abs(
        v_anchor.astype(jnp.float32) - prev_anchor.astype(jnp.float32)))
    base = jnp.mean(jnp.abs(prev_anchor.astype(jnp.float32)))
    rel = diff / jnp.maximum(base, 1e-8)
    accum_new = accum + rel

    in_window = (i >= cache_cfg.warmup_steps) & (
        i < num_steps - cache_cfg.tail_steps
    )
    skip = in_window & (accum_new < cache_cfg.rel_l1_threshold)

    def do_skip(_):
        return v_anchor + delta, delta, accum_new

    def do_compute(_):
        v = eval_rest(state).astype(prev_anchor.dtype)
        return v, v - v_anchor, jnp.asarray(0.0, jnp.float32)

    v, new_delta, new_accum = jax.lax.cond(skip, do_skip, do_compute,
                                           None)
    return v, (v_anchor, new_delta, new_accum), skip


def run_denoise_loop(cache_cfg, schedule, eval_velocity, latents, num_steps,
                     solver: str = "euler", eval_split=None):
    """Shared denoise fori_loop, optionally gated by the step cache.

    ``eval_velocity(latents, i)`` -> velocity (shape-preserving).  Returns
    ``(final_latents, skipped_count)``.  One implementation for every
    pipeline (image/video/audio) so cache-semantics changes land once.

    ``solver``: "euler" (FlowMatch Euler) or "unipc" (order-2 UniPC-style
    multistep, scheduler.multistep_step — fewer steps for the same
    quality; reference: scheduling_flow_unipc_multistep.py:741).
    """
    from vllm_omni_tpu.diffusion import scheduler as fm

    if solver not in ("euler", "unipc"):
        raise ValueError(f"unknown solver {solver!r}")
    multistep = solver == "unipc"
    use_cache = cache_cfg is not None and cache_cfg.enabled
    use_dbcache = use_cache and cache_cfg.backend == "dbcache"
    if use_dbcache and eval_split is None:
        raise ValueError(
            "dbcache needs the pipeline's split evaluation "
            "(eval_first, eval_rest) — this pipeline only supports "
            "teacache")

    def ms_init(lat):
        return (jnp.zeros_like(lat, jnp.float32),
                jnp.asarray(0.0, jnp.float32))

    def advance(lat, v, i, ms):
        if multistep:
            new_lat, x0, lam = fm.multistep_step(
                schedule, lat, v, i, ms[0], ms[1])
            return new_lat, (x0, lam)
        return fm.step(schedule, lat, v, i), ms

    if use_dbcache:
        eval_first, eval_rest = eval_split

        def body(i, carry):
            lat, cc, ms, skipped = carry
            v, cc, skip = dbcache_eval(
                cache_cfg, lambda l: eval_first(l, i), eval_rest, lat,
                cc, i, num_steps,
            )
            lat, ms = advance(lat, v, i, ms)
            return (lat, cc, ms, skipped + skip.astype(jnp.int32))

        lat, _, _, skipped = jax.lax.fori_loop(
            0, num_steps, body,
            (latents, dbcache_init_carry(latents), ms_init(latents),
             jnp.asarray(0, jnp.int32)),
        )
        return lat, skipped

    if use_cache:

        def body(i, carry):
            lat, cc, ms, skipped = carry
            v, cc, skip = cached_eval(
                cache_cfg, lambda l: eval_velocity(l, i), lat, cc, i,
                num_steps,
            )
            lat, ms = advance(lat, v, i, ms)
            return (lat, cc, ms, skipped + skip.astype(jnp.int32))

        lat, _, _, skipped = jax.lax.fori_loop(
            0, num_steps, body,
            (latents, init_carry(latents), ms_init(latents),
             jnp.asarray(0, jnp.int32)),
        )
        return lat, skipped

    def body(i, carry):
        lat, ms = carry
        lat, ms = advance(lat, eval_velocity(lat, i), i, ms)
        return lat, ms

    lat, _ = jax.lax.fori_loop(
        0, num_steps, body, (latents, ms_init(latents)))
    return lat, jnp.asarray(0, jnp.int32)
