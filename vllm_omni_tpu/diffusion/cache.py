"""Step-cache acceleration for DiT denoise loops (TeaCache analogue).

Reference: vllm_omni/diffusion/cache/ — ``CacheBackend`` ABC (base.py:31),
selector (selector.py:9), and the TeaCache hook skipping transformer
evaluations when the timestep-modulated input changed little
(teacache/hook.py:30, rel-L1 accumulation vs threshold).  The reference
reports 1.5-2.0x speedup at preserved quality
(docs/user_guide/diffusion_acceleration.md:15).

TPU-first mechanics: the reference installs Python forward-hooks that
branch per step — impossible under jit.  Here the skip decision is a
``lax.cond`` *inside* the compiled denoise loop: both branches are traced
once, the TPU executes only the taken branch at runtime, so skipped steps
genuinely save the DiT forward while the whole loop stays one XLA
computation.  State (last velocity, last input, accumulated rel-L1) rides
the ``fori_loop`` carry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class StepCacheConfig:
    # "teacache": input-drift gate skipping the WHOLE model eval
    # "taylorseer": like teacache, but skipped steps EXTRAPOLATE the
    #   velocity with a first/second-order Taylor step from finite
    #   differences of past computed evals instead of holding the last
    #   value (reference: cache-dit TaylorSeerCalibratorConfig,
    #   cache/cache_dit_backend.py:17)
    # "dbcache": dual-block cache (reference:
    #   diffusion/cache/cache_dit_backend.py DBCacheConfig) — the first
    #   ``fn_compute_blocks`` transformer blocks ALWAYS compute (a fresh
    #   anchor every step), their output drift gates reuse of a cached
    #   tail-contribution delta; higher quality than whole-model skipping
    #   because part of the network tracks every step
    backend: str = "teacache"     # "" disables
    rel_l1_threshold: float = 0.15
    # never skip the first/last steps (quality anchors, mirroring the
    # reference's warmup + final-step guards)
    warmup_steps: int = 1
    tail_steps: int = 1
    # dbcache: number of leading blocks always computed
    fn_compute_blocks: int = 4
    # taylorseer: extrapolation order (1 = linear, 2 = quadratic)
    taylor_order: int = 1
    # SCM (Step Computation Masking, reference cache-dit
    # scm_steps_mask, cache_dit_backend.py:46-55): a DETERMINISTIC
    # compute mask over step indices replacing the drift gate — entry i
    # True => step i computes, False => the cache serves it (warmup/
    # tail anchors still always compute).  None => dynamic drift gate.
    scm_steps_mask: "Optional[tuple]" = None

    @property
    def enabled(self) -> bool:
        return bool(self.backend)

    @staticmethod
    def from_dict(backend: str, d: dict) -> "StepCacheConfig":
        known = {k: v for k, v in (d or {}).items()
                 if k in StepCacheConfig.__dataclass_fields__ and k != "backend"}
        if "scm_steps_mask" in known and known["scm_steps_mask"] is not None:
            known["scm_steps_mask"] = tuple(
                bool(x) for x in known["scm_steps_mask"])
        return StepCacheConfig(backend=backend, **known)


def init_carry(latents: jax.Array):
    """(prev_velocity, prev_input, accumulated rel-L1) — accum starts at
    +inf so step 0 always computes."""
    return (
        jnp.zeros_like(latents),
        latents,
        jnp.asarray(jnp.inf, jnp.float32),
    )


def cached_eval(
    cache_cfg: StepCacheConfig,
    eval_fn: Callable[[jax.Array], jax.Array],
    latents: jax.Array,
    carry,
    i: jax.Array,
    num_steps: jax.Array,
    scm_mask=None,
):
    """Evaluate (or reuse) the velocity for this step.

    Returns (velocity, new_carry, skipped_flag).  ``eval_fn(latents)`` must
    be shape-preserving from latents to velocity.
    """
    prev_v, prev_lat, accum = carry
    diff = jnp.mean(jnp.abs(
        latents.astype(jnp.float32) - prev_lat.astype(jnp.float32)))
    base = jnp.mean(jnp.abs(prev_lat.astype(jnp.float32)))
    rel = diff / jnp.maximum(base, 1e-8)
    accum_new = accum + rel

    in_window = (i >= cache_cfg.warmup_steps) & (
        i < num_steps - cache_cfg.tail_steps
    )
    # a reusable velocity exists only after the first compute (accum is
    # +inf until then) — the SCM mask must not serve init_carry's zeros
    computed_once = jnp.isfinite(accum_new)
    if scm_mask is not None:
        skip = in_window & computed_once & ~scm_mask[i]
    else:
        skip = in_window & (accum_new < cache_cfg.rel_l1_threshold)

    def do_skip(_):
        # reuse the previous velocity; keep accumulating drift
        return prev_v, prev_lat, accum_new

    def do_compute(_):
        # match the carry dtype (CFG guidance math may promote to f32)
        v = eval_fn(latents).astype(prev_v.dtype)
        # reset the accumulator relative to this freshly-computed input
        return v, latents, jnp.asarray(0.0, jnp.float32)

    v, new_prev_lat, new_accum = jax.lax.cond(skip, do_skip, do_compute, None)
    return v, (v, new_prev_lat, new_accum), skip


def _scm_mask_array(cache_cfg: StepCacheConfig, sched_len: int):
    """Padded compute-mask [sched_len] from the config's tuple (True
    beyond the configured range so over-length schedules stay exact)."""
    import numpy as np

    m = np.ones((sched_len,), bool)
    mask = cache_cfg.scm_steps_mask
    n = min(len(mask), sched_len)
    m[:n] = np.asarray(mask[:n], bool)
    return jnp.asarray(m)


def taylor_init_carry(latents: jax.Array):
    """(v0, v1, v2, i0, i1, i2, prev_lat, accum): the last THREE
    computed velocities with their step indices (Newton
    divided-difference anchors, oldest first) plus the last computed
    input and the rel-L1 drift accumulator."""
    z = jnp.zeros_like(latents)
    return (z, z, z,
            jnp.asarray(-3, jnp.int32), jnp.asarray(-2, jnp.int32),
            jnp.asarray(-1, jnp.int32),
            latents, jnp.asarray(jnp.inf, jnp.float32))


def taylorseer_eval(
    cache_cfg: StepCacheConfig,
    eval_fn: Callable[[jax.Array], jax.Array],
    latents: jax.Array,
    carry,
    i: jax.Array,
    num_steps: jax.Array,
    scm_mask=None,
):
    """Evaluate, or Taylor-extrapolate, the velocity for this step.

    Skipped steps advance the last computed velocity along its Newton
    divided-difference derivative(s) through the last 2 (order 1) or 3
    (order 2) computed anchors instead of holding it — the calibrator
    idea of cache-dit's TaylorSeer.  Returns
    (velocity, new_carry, skipped_flag)."""
    v0, v1, v2, i0, i1, i2, prev_lat, accum = carry
    diff = jnp.mean(jnp.abs(
        latents.astype(jnp.float32) - prev_lat.astype(jnp.float32)))
    base = jnp.mean(jnp.abs(prev_lat.astype(jnp.float32)))
    rel = diff / jnp.maximum(base, 1e-8)
    accum_new = accum + rel

    in_window = (i >= cache_cfg.warmup_steps) & (
        i < num_steps - cache_cfg.tail_steps
    )
    # a valid derivative needs at least two computed anchors
    have_two = i1 >= 0
    if scm_mask is not None:
        skip = in_window & have_two & ~scm_mask[i]
    else:
        skip = in_window & have_two & (
            accum_new < cache_cfg.rel_l1_threshold)

    def do_skip(_):
        f = jnp.float32
        t, t1, t2 = i.astype(f), i1.astype(f), i2.astype(f)
        d21 = (v2.astype(f) - v1.astype(f)) / jnp.maximum(t2 - t1, 1.0)
        v = v2.astype(f) + d21 * (t - t2)
        if cache_cfg.taylor_order >= 2:
            t0 = i0.astype(f)
            have_three = (i0 >= 0).astype(f)
            d10 = (v1.astype(f) - v0.astype(f)) / jnp.maximum(
                t1 - t0, 1.0)
            d210 = (d21 - d10) / jnp.maximum(t2 - t0, 1.0)
            # Newton form through (t1, t2): + d2 * (t-t2)(t-t1)
            v = v + have_three * d210 * (t - t2) * (t - t1)
        return (v.astype(v2.dtype),
                (v0, v1, v2, i0, i1, i2, prev_lat, accum_new))

    def do_compute(_):
        v = eval_fn(latents).astype(v2.dtype)
        return (v, (v1, v2, v, i1, i2, i,
                    latents, jnp.asarray(0.0, jnp.float32)))

    v, new_carry = jax.lax.cond(skip, do_skip, do_compute, None)
    return v, new_carry, skip


def dbcache_init_carry(latents: jax.Array):
    """(prev_anchor_velocity, cached_tail_delta, accumulated rel-L1)."""
    return (
        jnp.zeros_like(latents),
        jnp.zeros_like(latents),
        jnp.asarray(jnp.inf, jnp.float32),
    )


def dbcache_eval(
    cache_cfg: StepCacheConfig,
    eval_first: Callable,   # (latents) -> (state, anchor_velocity)
    eval_rest: Callable,    # (state) -> full_velocity
    latents: jax.Array,
    carry,
    i: jax.Array,
    num_steps: jax.Array,
):
    """Dual-block cached velocity: the anchor (first Fn blocks + output
    head) computes EVERY step; when its drift since the last full compute
    stays under threshold, the cached tail delta (full - anchor) is
    reused instead of running the remaining blocks.

    Returns (velocity, new_carry, skipped_flag)."""
    prev_anchor, delta, accum = carry
    state, v_anchor = eval_first(latents)
    v_anchor = v_anchor.astype(prev_anchor.dtype)
    diff = jnp.mean(jnp.abs(
        v_anchor.astype(jnp.float32) - prev_anchor.astype(jnp.float32)))
    base = jnp.mean(jnp.abs(prev_anchor.astype(jnp.float32)))
    rel = diff / jnp.maximum(base, 1e-8)
    accum_new = accum + rel

    in_window = (i >= cache_cfg.warmup_steps) & (
        i < num_steps - cache_cfg.tail_steps
    )
    skip = in_window & (accum_new < cache_cfg.rel_l1_threshold)

    def do_skip(_):
        return v_anchor + delta, delta, accum_new

    def do_compute(_):
        v = eval_rest(state).astype(prev_anchor.dtype)
        return v, v - v_anchor, jnp.asarray(0.0, jnp.float32)

    v, new_delta, new_accum = jax.lax.cond(skip, do_skip, do_compute,
                                           None)
    return v, (v_anchor, new_delta, new_accum), skip


def init_cache_carry(cache_cfg, latents):
    """The cache backend's initial carry for ``latents``-shaped state —
    the host-visible half of the cross-chunk contract (chunked host
    loops thread this through ``run_denoise_loop(..., carry_in=...,
    return_carry=True)`` so skip state survives device-call
    boundaries)."""
    if cache_cfg is None or not cache_cfg.enabled:
        return None
    if cache_cfg.backend == "dbcache":
        return dbcache_init_carry(latents)
    if cache_cfg.backend == "taylorseer":
        return taylor_init_carry(latents)
    return init_carry(latents)


def run_denoise_loop(cache_cfg, schedule, eval_velocity, latents, num_steps,
                     solver: str = "euler", eval_split=None,
                     step_offset=None, total_steps=None, carry_in=None,
                     return_carry: bool = False):
    """Shared denoise fori_loop, optionally gated by the step cache.

    ``eval_velocity(latents, i)`` -> velocity (shape-preserving).  Returns
    ``(final_latents, skipped_count)`` — plus the cache carry when
    ``return_carry`` is set.  One implementation for every pipeline
    (image/video/audio) so cache-semantics changes land once.

    ``solver``: "euler" (FlowMatch Euler) or "unipc" (order-2 UniPC-style
    multistep, scheduler.multistep_step — fewer steps for the same
    quality; reference: scheduling_flow_unipc_multistep.py:741).

    Chunked host loops (remote-attached chips run K steps per device
    call on a schedule rolled to the chunk start) pass ``step_offset``
    (global index of local step 0), ``total_steps`` (the full run
    length, for the warmup/tail window), and thread the cache carry
    through ``carry_in``/``return_carry`` — the loop indexes the
    SCHEDULE locally and the CACHE globally, so skip decisions and
    Taylor anchors are identical to one uninterrupted loop.
    """
    from vllm_omni_tpu.diffusion import scheduler as fm

    if solver not in ("euler", "unipc"):
        raise ValueError(f"unknown solver {solver!r}")
    multistep = solver == "unipc"
    use_cache = cache_cfg is not None and cache_cfg.enabled
    use_dbcache = use_cache and cache_cfg.backend == "dbcache"
    use_taylor = use_cache and cache_cfg.backend == "taylorseer"
    offset = jnp.int32(0) if step_offset is None else step_offset
    total = num_steps if total_steps is None else total_steps
    scm_mask = None
    if use_cache and cache_cfg.scm_steps_mask is not None:
        scm_mask = _scm_mask_array(cache_cfg, int(schedule.sigmas.shape[0]))
    if use_dbcache and eval_split is None:
        raise ValueError(
            "dbcache needs the pipeline's split evaluation "
            "(eval_first, eval_rest) — this pipeline only supports "
            "teacache")
    if use_dbcache and scm_mask is not None:
        raise ValueError(
            "scm_steps_mask is not wired into the dbcache backend — "
            "use teacache or taylorseer for deterministic step masks")
    if multistep and (step_offset is not None or carry_in is not None):
        raise ValueError(
            "chunked denoise carries only the cache state — the unipc "
            "multistep solver state would be lost across chunks; use "
            "the euler solver")

    def ms_init(lat):
        return (jnp.zeros_like(lat, jnp.float32),
                jnp.asarray(0.0, jnp.float32))

    def advance(lat, v, i, ms):
        if multistep:
            new_lat, x0, lam = fm.multistep_step(
                schedule, lat, v, i, ms[0], ms[1])
            return new_lat, (x0, lam)
        return fm.step(schedule, lat, v, i), ms

    if use_cache:
        if use_dbcache:
            eval_first, eval_rest = eval_split

            def cache_eval(lat, i, ig, cc):
                return dbcache_eval(
                    cache_cfg, lambda l: eval_first(l, i), eval_rest,
                    lat, cc, ig, total)

        elif use_taylor:

            def cache_eval(lat, i, ig, cc):
                return taylorseer_eval(
                    cache_cfg, lambda l: eval_velocity(l, i), lat, cc,
                    ig, total, scm_mask=scm_mask)

        else:

            def cache_eval(lat, i, ig, cc):
                return cached_eval(
                    cache_cfg, lambda l: eval_velocity(l, i), lat, cc,
                    ig, total, scm_mask=scm_mask)

        default_carry = init_cache_carry(cache_cfg, latents)

        def body(i, carry):
            lat, cc, ms, skipped = carry
            v, cc, skip = cache_eval(lat, i, i + offset, cc)
            lat, ms = advance(lat, v, i, ms)
            return (lat, cc, ms, skipped + skip.astype(jnp.int32))

        lat, cc, _, skipped = jax.lax.fori_loop(
            0, num_steps, body,
            (latents, carry_in if carry_in is not None else default_carry,
             ms_init(latents), jnp.asarray(0, jnp.int32)),
        )
        if return_carry:
            return lat, skipped, cc
        return lat, skipped

    def body(i, carry):
        lat, ms = carry
        lat, ms = advance(lat, eval_velocity(lat, i), i, ms)
        return lat, ms

    lat, _ = jax.lax.fori_loop(
        0, num_steps, body, (latents, ms_init(latents)))
    if return_carry:
        return lat, jnp.asarray(0, jnp.int32), None
    return lat, jnp.asarray(0, jnp.int32)
