"""FlowMatch Euler discrete scheduler (jit-friendly).

Role of the reference's diffusers FlowMatchEulerDiscreteScheduler use in
QwenImagePipeline.prepare_latents/timesteps (pipeline_qwen_image.py:638-659)
and the UniPC variant (scheduling_flow_unipc_multistep.py:741 — later).

Flow matching ODE with velocity prediction:  x_{t'} = x_t + (s' - s) * v,
sigmas in [1, 0], optionally resolution-shifted (``mu`` / dynamic shifting
per image sequence length, as Qwen-Image uses).  All state is precomputed
arrays — the per-step update is pure arithmetic inside the jitted loop.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def compute_dynamic_shift_mu(
    image_seq_len: int,
    base_seq_len: int = 256,
    max_seq_len: int = 8192,
    base_shift: float = 0.5,
    max_shift: float = 0.9,
) -> float:
    """Resolution-dependent timestep shift (diffusers calculate_shift)."""
    m = (max_shift - base_shift) / (max_seq_len - base_seq_len)
    b = base_shift - m * base_seq_len
    return image_seq_len * m + b


@dataclass(frozen=True)
class FlowMatchSchedule:
    sigmas: jax.Array  # [num_steps + 1], sigmas[-1] == 0
    timesteps: jax.Array  # [num_steps], in [0, 1000)

    @property
    def num_steps(self) -> int:
        return self.timesteps.shape[0]


def make_schedule(
    num_steps: int,
    shift: float = 1.0,
    use_dynamic_shifting: bool = False,
    mu: float = 1.0,
    num_train_timesteps: int = 1000,
) -> FlowMatchSchedule:
    sigmas = jnp.linspace(1.0, 1.0 / num_train_timesteps, num_steps)
    if use_dynamic_shifting:
        # exponential time shift with mu (diffusers time_shift)
        sigmas = jnp.exp(mu) / (jnp.exp(mu) + (1.0 / sigmas - 1.0))
    else:
        sigmas = shift * sigmas / (1.0 + (shift - 1.0) * sigmas)
    timesteps = sigmas * num_train_timesteps
    sigmas = jnp.concatenate([sigmas, jnp.zeros((1,))])
    return FlowMatchSchedule(sigmas=sigmas, timesteps=timesteps)


def step(
    schedule: FlowMatchSchedule,
    latents: jax.Array,
    velocity: jax.Array,
    step_index: jax.Array,
) -> jax.Array:
    """One Euler step of the flow ODE (index may be traced)."""
    sigma = schedule.sigmas[step_index]
    sigma_next = schedule.sigmas[step_index + 1]
    lat32 = latents.astype(jnp.float32)
    v32 = velocity.astype(jnp.float32)
    return (lat32 + (sigma_next - sigma) * v32).astype(latents.dtype)


def add_noise(
    latents: jax.Array, noise: jax.Array, sigma: jax.Array
) -> jax.Array:
    """Interpolate clean latents toward noise (image-edit / i2v init)."""
    return (1.0 - sigma) * latents + sigma * noise


# --------------------------------------------------------------- multistep
_LAMBDA_EPS = 1e-5


def _flow_lambda(sigma: jax.Array) -> jax.Array:
    """Half-log-SNR of the flow path x_s = (1-s)x0 + s*eps:
    lambda = log((1-s)/s), clamped away from the endpoints."""
    s = jnp.clip(sigma, _LAMBDA_EPS, 1.0 - _LAMBDA_EPS)
    return jnp.log((1.0 - s) / s)


def multistep_step(
    schedule: FlowMatchSchedule,
    latents: jax.Array,
    velocity: jax.Array,
    step_index: jax.Array,
    prev_x0: jax.Array,
    prev_lambda: jax.Array,
):
    """One order-2 UniPC-style multistep update (data-prediction form).

    Role of the reference's FlowUniPC multistep scheduler
    (scheduling_flow_unipc_multistep.py:741): convert the velocity to a
    data prediction ``x0 = x - sigma*v``, extrapolate with the previous
    step's x0 (second order in the half-log-SNR variable), and take the
    exponential-integrator update — at step 0 this degrades to the
    first-order update, and when sigma_next == 0 it lands exactly on the
    extrapolated x0.  Carry-friendly: returns (new_latents, x0, lambda)
    for the jitted fori_loop.
    """
    sigma = schedule.sigmas[step_index]
    sigma_next = schedule.sigmas[step_index + 1]
    lat32 = latents.astype(jnp.float32)
    v32 = velocity.astype(jnp.float32)
    x0 = lat32 - sigma * v32
    lam = _flow_lambda(sigma)
    lam_next = _flow_lambda(sigma_next)
    h = lam_next - lam
    h0 = lam - prev_lambda
    r0 = h0 / jnp.where(h == 0.0, 1.0, h)
    corr = (x0 - prev_x0) / jnp.where(r0 == 0.0, 1.0, 2.0 * r0)
    # step 0 has no history: pure first-order (corr off)
    d = x0 + jnp.where(step_index == 0, 0.0, 1.0) * corr
    alpha_next = 1.0 - sigma_next
    safe_sigma = jnp.where(sigma == 0.0, 1.0, sigma)
    new_lat = (sigma_next / safe_sigma) * lat32 \
        - alpha_next * jnp.expm1(-h) * d
    # terminal step (sigma_next == 0): the update collapses to d exactly
    new_lat = jnp.where(sigma_next <= _LAMBDA_EPS, d, new_lat)
    return new_lat.astype(latents.dtype), x0, lam


# ------------------------------------------------- EDM cosine DPM-Solver
# StableAudio Open sampling (reference: CosineDPMSolverMultistepScheduler
# from diffusers, pipeline_stable_audio.py:134-139,505-553): EDM
# preconditioning with sigma_data, exponential sigma schedule, the model
# conditioned on t = atan(sigma) * 2/pi (the "cosine" parameterization),
# deterministic DPM-Solver++(2M) updates in lambda = -log(sigma) space.

@dataclass(frozen=True)
class EdmDpmSchedule:
    sigmas: jax.Array       # [steps + 1], last entry 0
    sigma_data: float = 1.0

    @property
    def init_noise_sigma(self) -> float:
        return float(np.sqrt(float(self.sigmas[0]) ** 2
                             + self.sigma_data ** 2))


def make_edm_dpm_schedule(num_steps: int, sigma_min: float = 0.3,
                          sigma_max: float = 500.0,
                          sigma_data: float = 1.0) -> EdmDpmSchedule:
    """Exponential (log-linear) sigma ramp sigma_max -> sigma_min, then
    the terminal 0."""
    sig = np.exp(np.linspace(np.log(sigma_max), np.log(sigma_min),
                             num_steps))
    return EdmDpmSchedule(
        sigmas=jnp.asarray(np.concatenate([sig, [0.0]]), jnp.float32),
        sigma_data=sigma_data)


def edm_precondition_inputs(sample, sigma, sigma_data: float = 1.0):
    """c_in scaling (scale_model_input)."""
    c_in = 1.0 / jnp.sqrt(sigma ** 2 + sigma_data ** 2)
    return sample * c_in


def edm_sigma_to_t(sigma):
    """Model-facing timestep: t = atan(sigma) * 2/pi in [0, 1)."""
    return jnp.arctan(sigma) * (2.0 / jnp.pi)


def edm_precondition_outputs(sample, model_output, sigma,
                             sigma_data: float = 1.0):
    """v-prediction EDM preconditioning: denoised = c_skip * x + c_out
    * F(c_in x, t)."""
    c_skip = sigma_data ** 2 / (sigma ** 2 + sigma_data ** 2)
    c_out = -sigma * sigma_data / jnp.sqrt(sigma ** 2 + sigma_data ** 2)
    return c_skip * sample + c_out * model_output


def edm_sde_dpm_step(latents, denoised, prev_denoised, i, sigmas,
                     noise):
    """One SDE-DPMSolver++(2M) update (alpha = 1, midpoint) — the only
    algorithm the reference's CosineDPMSolverMultistepScheduler runs:

        x_t = (sigma_t/sigma_s) e^{-h} x + (1 - e^{-2h}) D~
              + sigma_t sqrt(1 - e^{-2h}) eps

    with lambda = -log(sigma), h = lambda_t - lambda_s (so e^{-h} =
    sigma_t/sigma_s), D~ = D0 + (D0 - D_prev)/(2 r) on multistep steps
    and D0 on the first.  latents/denoised/noise [B, ...] fp32;
    prev_denoised is ignored at i == 0.  The terminal step
    (sigma_t == 0) collapses to the denoised sample."""
    sigma_s, sigma_t = sigmas[i], sigmas[i + 1]
    sigma_prev = sigmas[jnp.maximum(i - 1, 0)]
    eps = 1e-12
    h = jnp.log(sigma_s / jnp.maximum(sigma_t, eps))
    h_last = jnp.log(sigma_prev / sigma_s)
    r = h_last / jnp.maximum(h, eps)
    d1 = (denoised - prev_denoised) / r
    d = jnp.where(i > 0, denoised + 0.5 * d1, denoised)
    decay = jnp.exp(-h)                       # == sigma_t / sigma_s
    grow = -jnp.expm1(-2.0 * h)               # 1 - e^{-2h}
    out = (sigma_t / sigma_s) * decay * latents + grow * d \
        + sigma_t * jnp.sqrt(grow) * noise
    return jnp.where(sigma_t <= eps, denoised, out)
