"""FlowMatch Euler discrete scheduler (jit-friendly).

Role of the reference's diffusers FlowMatchEulerDiscreteScheduler use in
QwenImagePipeline.prepare_latents/timesteps (pipeline_qwen_image.py:638-659)
and the UniPC variant (scheduling_flow_unipc_multistep.py:741 — later).

Flow matching ODE with velocity prediction:  x_{t'} = x_t + (s' - s) * v,
sigmas in [1, 0], optionally resolution-shifted (``mu`` / dynamic shifting
per image sequence length, as Qwen-Image uses).  All state is precomputed
arrays — the per-step update is pure arithmetic inside the jitted loop.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp


def compute_dynamic_shift_mu(
    image_seq_len: int,
    base_seq_len: int = 256,
    max_seq_len: int = 8192,
    base_shift: float = 0.5,
    max_shift: float = 0.9,
) -> float:
    """Resolution-dependent timestep shift (diffusers calculate_shift)."""
    m = (max_shift - base_shift) / (max_seq_len - base_seq_len)
    b = base_shift - m * base_seq_len
    return image_seq_len * m + b


@dataclass(frozen=True)
class FlowMatchSchedule:
    sigmas: jax.Array  # [num_steps + 1], sigmas[-1] == 0
    timesteps: jax.Array  # [num_steps], in [0, 1000)

    @property
    def num_steps(self) -> int:
        return self.timesteps.shape[0]


def make_schedule(
    num_steps: int,
    shift: float = 1.0,
    use_dynamic_shifting: bool = False,
    mu: float = 1.0,
    num_train_timesteps: int = 1000,
) -> FlowMatchSchedule:
    sigmas = jnp.linspace(1.0, 1.0 / num_train_timesteps, num_steps)
    if use_dynamic_shifting:
        # exponential time shift with mu (diffusers time_shift)
        sigmas = jnp.exp(mu) / (jnp.exp(mu) + (1.0 / sigmas - 1.0))
    else:
        sigmas = shift * sigmas / (1.0 + (shift - 1.0) * sigmas)
    timesteps = sigmas * num_train_timesteps
    sigmas = jnp.concatenate([sigmas, jnp.zeros((1,))])
    return FlowMatchSchedule(sigmas=sigmas, timesteps=timesteps)


def step(
    schedule: FlowMatchSchedule,
    latents: jax.Array,
    velocity: jax.Array,
    step_index: jax.Array,
) -> jax.Array:
    """One Euler step of the flow ODE (index may be traced)."""
    sigma = schedule.sigmas[step_index]
    sigma_next = schedule.sigmas[step_index + 1]
    lat32 = latents.astype(jnp.float32)
    v32 = velocity.astype(jnp.float32)
    return (lat32 + (sigma_next - sigma) * v32).astype(latents.dtype)


def add_noise(
    latents: jax.Array, noise: jax.Array, sigma: jax.Array
) -> jax.Array:
    """Interpolate clean latents toward noise (image-edit / i2v init)."""
    return (1.0 - sigma) * latents + sigma * noise


# --------------------------------------------------------------- multistep
_LAMBDA_EPS = 1e-5


def _flow_lambda(sigma: jax.Array) -> jax.Array:
    """Half-log-SNR of the flow path x_s = (1-s)x0 + s*eps:
    lambda = log((1-s)/s), clamped away from the endpoints."""
    s = jnp.clip(sigma, _LAMBDA_EPS, 1.0 - _LAMBDA_EPS)
    return jnp.log((1.0 - s) / s)


def multistep_step(
    schedule: FlowMatchSchedule,
    latents: jax.Array,
    velocity: jax.Array,
    step_index: jax.Array,
    prev_x0: jax.Array,
    prev_lambda: jax.Array,
):
    """One order-2 UniPC-style multistep update (data-prediction form).

    Role of the reference's FlowUniPC multistep scheduler
    (scheduling_flow_unipc_multistep.py:741): convert the velocity to a
    data prediction ``x0 = x - sigma*v``, extrapolate with the previous
    step's x0 (second order in the half-log-SNR variable), and take the
    exponential-integrator update — at step 0 this degrades to the
    first-order update, and when sigma_next == 0 it lands exactly on the
    extrapolated x0.  Carry-friendly: returns (new_latents, x0, lambda)
    for the jitted fori_loop.
    """
    sigma = schedule.sigmas[step_index]
    sigma_next = schedule.sigmas[step_index + 1]
    lat32 = latents.astype(jnp.float32)
    v32 = velocity.astype(jnp.float32)
    x0 = lat32 - sigma * v32
    lam = _flow_lambda(sigma)
    lam_next = _flow_lambda(sigma_next)
    h = lam_next - lam
    h0 = lam - prev_lambda
    r0 = h0 / jnp.where(h == 0.0, 1.0, h)
    corr = (x0 - prev_x0) / jnp.where(r0 == 0.0, 1.0, 2.0 * r0)
    # step 0 has no history: pure first-order (corr off)
    d = x0 + jnp.where(step_index == 0, 0.0, 1.0) * corr
    alpha_next = 1.0 - sigma_next
    safe_sigma = jnp.where(sigma == 0.0, 1.0, sigma)
    new_lat = (sigma_next / safe_sigma) * lat32 \
        - alpha_next * jnp.expm1(-h) * d
    # terminal step (sigma_next == 0): the update collapses to d exactly
    new_lat = jnp.where(sigma_next <= _LAMBDA_EPS, d, new_lat)
    return new_lat.astype(latents.dtype), x0, lam
