"""Weight-only quantization of param trees.

Reference: vllm_omni/diffusion/quantization/{base,fp8}.py —
``DiffusionQuantizationConfig`` applying FP8 W8A8 (Ada/Hopper) or
weight-only fallback to DiT linear layers, ~1.28x reported speedup
(docs/user_guide/diffusion_acceleration.md:19,46).

TPU paths: int8 weight-only (per-out-channel absmax scaling) and fp8
weight-only (float8_e4m3, per-out-channel scale to the e4m3 dynamic
range).  Either way weights live quantized in HBM (halved weight
bandwidth — the DiT denoise loop is bandwidth-bound at decode-scale
batches) and dequantize inline where the matmul consumes them
(models/common/nn.py ``linear``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def quantize_linear_weight(w: jax.Array) -> dict:
    """[in, out] float -> {w_q int8 [in, out], w_scale f32 [out]}."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    w_q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[None, :]), -127, 127
    ).astype(jnp.int8)
    return {"w_q": w_q, "w_scale": scale}


_FP8_MAX = 448.0  # float8_e4m3 finite max


def quantize_linear_weight_fp8(w: jax.Array) -> dict:
    """[in, out] float -> {w_q float8_e4m3fn [in, out], w_scale f32 [out]}
    (reference: diffusion/quantization/fp8.py weight-only path)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.maximum(absmax / _FP8_MAX, 1e-12)
    w_q = (w.astype(jnp.float32) / scale[None, :]).astype(
        jnp.float8_e4m3fn)
    return {"w_q": w_q, "w_scale": scale}


def quantize_params(tree, min_size: int = 0, mode: str = "int8"):
    """Replace every linear-style leaf dict (2-D "w") with its quantized
    weight-only form; "b" and norms pass through.  ``min_size`` skips small
    matrices where dequant overhead outweighs the bandwidth win.
    ``mode``: "int8" | "fp8"."""
    quantize = {
        "int8": quantize_linear_weight,
        "fp8": quantize_linear_weight_fp8,
    }[mode]
    n_quant = 0

    def walk(node):
        nonlocal n_quant
        if isinstance(node, dict):
            if "w" in node and getattr(node["w"], "ndim", 0) == 2 \
                    and node["w"].size >= min_size:
                n_quant += 1
                q = quantize(node["w"])
                rest = {k: v for k, v in node.items() if k != "w"}
                return {**rest, **q}
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    out = walk(tree)
    logger.info("quantized %d linear weights to %s", n_quant, mode)
    return out
