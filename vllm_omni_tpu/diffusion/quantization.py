"""Weight-only quantization of param trees.

Reference: vllm_omni/diffusion/quantization/{base,fp8}.py —
``DiffusionQuantizationConfig`` applying FP8 W8A8 (Ada/Hopper) or
weight-only fallback to DiT linear layers, ~1.28x reported speedup
(docs/user_guide/diffusion_acceleration.md:19,46).

TPU paths: int8 weight-only (per-out-channel absmax scaling) and fp8
weight-only (float8_e4m3, per-out-channel scale to the e4m3 dynamic
range).  Either way weights live quantized in HBM (halved weight
bandwidth — the DiT denoise loop is bandwidth-bound at decode-scale
batches) and dequantize inline where the matmul consumes them
(models/common/nn.py ``linear``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def quantize_linear_weight(w: jax.Array) -> dict:
    """[in, out] float -> {w_q int8 [in, out], w_scale f32 [out]}."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    w_q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[None, :]), -127, 127
    ).astype(jnp.int8)
    return {"w_q": w_q, "w_scale": scale}


_FP8_MAX = 448.0  # float8_e4m3 finite max


def quantize_linear_weight_int4(w: jax.Array) -> dict:
    """[in, out] float -> {w_q4 int8 [ceil(in/2), out], w_scale f32 [out]}.

    Two 4-bit values pack per byte along the IN dimension (row 2i in the
    low nibble, row 2i+1 in the high nibble); per-out-channel absmax
    scaling to [-7, 7].  Packed int8 rather than jnp.int4 storage: the
    sub-byte dtype cannot cross a jit boundary on the axon TPU backend
    (device_put recurses re-sharding S4 layouts), and packed bytes are
    backend-portable.  4x smaller than bf16 — the lever that fits the
    full 60-layer Qwen-Image DiT (41 GB bf16 -> 10.3 GB) resident in one
    16 GB chip's HBM."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)  # [out]
    scale = jnp.maximum(absmax / 7.0, 1e-12)
    q = jnp.clip(jnp.round(wf / scale[None, :]), -8, 7).astype(jnp.int8)
    if q.shape[0] % 2:
        q = jnp.pad(q, ((0, 1), (0, 0)))
    lo, hi = q[0::2], q[1::2]
    packed = jnp.bitwise_or(
        jnp.left_shift(hi, 4), jnp.bitwise_and(lo, jnp.int8(0x0F)))
    return {"w_q4": packed, "w_scale": scale}


def unpack_int4(packed: jax.Array, in_dim: int, dtype) -> jax.Array:
    """{[in//2, out] packed int8} -> [in, out] ``dtype`` values in
    [-8, 7] (the inverse of ``quantize_linear_weight_int4``'s packing,
    before the scale multiply).  Arithmetic shifts sign-extend both
    nibbles; the interleave restores the original row order."""
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    w = jnp.stack([lo, hi], axis=1).reshape(-1, packed.shape[1])
    return w[:in_dim].astype(dtype)


def quantize_linear_weight_fp8(w: jax.Array) -> dict:
    """[in, out] float -> {w_q float8_e4m3fn [in, out], w_scale f32 [out]}
    (reference: diffusion/quantization/fp8.py weight-only path)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scale = jnp.maximum(absmax / _FP8_MAX, 1e-12)
    w_q = (w.astype(jnp.float32) / scale[None, :]).astype(
        jnp.float8_e4m3fn)
    return {"w_q": w_q, "w_scale": scale}


def _quantize_tree(tree, quantize_fn, min_size: int):
    """Shared walk: replace every linear-style leaf dict (2-D "w") with
    ``quantize_fn(w)``; "b" and 1-D norm weights pass through.
    ``min_size`` skips small matrices where dequant overhead outweighs
    the bandwidth win.

    Identity-memoized: bench trees alias repeated blocks to a few
    distinct host buffers (offload.host_tiled_init_aliased); quantizing
    each alias separately would materialize tens of GB of near-duplicate
    arrays and defeat the aliasing.  Aliased inputs stay aliased in the
    output.  Returns (new_tree, n_distinct_quantized)."""
    memo: dict[int, object] = {}
    n_quant = 0

    def walk(node):
        nonlocal n_quant
        if isinstance(node, dict):
            hit = memo.get(id(node))
            if hit is not None:
                return hit
            if "w" in node and getattr(node["w"], "ndim", 0) == 2 \
                    and node["w"].size >= min_size:
                n_quant += 1
                q = quantize_fn(node["w"])
                rest = {k: v for k, v in node.items() if k != "w"}
                out = {**rest, **q}
            else:
                out = {k: walk(v) for k, v in node.items()}
            memo[id(node)] = out
            return out
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(tree), n_quant


def quantize_params(tree, min_size: int = 0, mode: str = "int8"):
    """Quantize a DEVICE param tree in place of its float linears.
    ``mode``: "int8" | "fp8"."""
    quantize = {
        "int8": quantize_linear_weight,
        "fp8": quantize_linear_weight_fp8,
        "int4": quantize_linear_weight_int4,
    }[mode]
    out, n_quant = _quantize_tree(tree, quantize, min_size)
    logger.info("quantized %d linear weights to %s", n_quant, mode)
    return out


def quantize_linear_weight_host(w, mode: str = "int8") -> dict:
    """Host (numpy) twin of the device quantizers, for layerwise-streamed
    param trees that must stay in host memory: quantizing with jnp would
    round-trip every block through the device.  Same math, same rounding
    (IEEE f32 max/div + round-half-even), so streamed-quantized equals
    resident-quantized bit-for-bit."""
    import numpy as np

    wf = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(wf), axis=0)  # [out]
    if mode == "int8":
        scale = np.maximum(absmax / 127.0, 1e-12).astype(np.float32)
        w_q = np.clip(
            np.round(wf / scale[None, :]), -127, 127).astype(np.int8)
    elif mode == "int4":
        scale = np.maximum(absmax / 7.0, 1e-12).astype(np.float32)
        q = np.clip(np.round(wf / scale[None, :]), -8, 7).astype(np.int8)
        if q.shape[0] % 2:
            q = np.pad(q, ((0, 1), (0, 0)))
        lo, hi = q[0::2], q[1::2]
        packed = np.bitwise_or(
            np.left_shift(hi, 4),
            np.bitwise_and(lo, np.int8(0x0F))).astype(np.int8)
        return {"w_q4": packed, "w_scale": scale}
    elif mode == "fp8":
        import ml_dtypes

        scale = np.maximum(absmax / _FP8_MAX, 1e-12).astype(np.float32)
        w_q = (wf / scale[None, :]).astype(ml_dtypes.float8_e4m3fn)
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    return {"w_q": w_q, "w_scale": scale}


def quantize_params_host(tree, min_size: int = 0, mode: str = "int8"):
    """``quantize_params`` for HOST trees (layerwise streaming).  int8
    halves the host->HBM bytes per streamed block — the streamed denoise
    walk is transfer-bound, so the step time drops near-proportionally."""
    out, n_quant = _quantize_tree(
        tree, lambda w: quantize_linear_weight_host(w, mode), min_size)
    logger.info("host-quantized %d distinct linear weights to %s",
                n_quant, mode)
    return out
