"""Diffusion LoRA: adapter loading, caching, and fused activation.

Reference: vllm_omni/diffusion/lora/manager.py:33 ``DiffusionLoRAManager``
(adapter load/cache/activate/pin, scale) + per-layer LoRA linear wrappers
(lora/layers/*.py).

TPU-first mechanics: params are functional pytrees, so "activating" an
adapter is producing a fused tree ``W' = W + scale * (A @ B)`` — one jitted
tree_map-style transform, no per-module wrapper classes, and the fused tree
hits the same compiled executables as the base weights (identical shapes).
Fused trees are cached by (adapter, scale); switching adapters is a cache
lookup, matching the reference's activate/pin semantics.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

# HF/PEFT ("...lora_A.weight") and kohya ("...lora_down.weight") suffixes
_LORA_RE = re.compile(
    r"^(.*?)\.?(lora_A|lora_B|lora_down|lora_up)\.weight$"
)
_ALPHA_RE = re.compile(r"^(.*?)\.alpha$")


class LoRAAdapter:
    """module_path -> (A [r, in], B [out, r], alpha) in checkpoint layout."""

    def __init__(self, name: str):
        self.name = name
        self.a: dict[str, jax.Array] = {}
        self.b: dict[str, jax.Array] = {}
        self.alpha: dict[str, float] = {}

    @property
    def modules(self) -> set[str]:
        return set(self.a) & set(self.b)

    def delta(self, module: str, scale: float) -> jax.Array:
        """[in, out] weight delta in our (transposed) linear layout."""
        a, b = self.a[module], self.b[module]
        r = a.shape[0]
        alpha = self.alpha.get(module, float(r))
        eff = scale * alpha / r
        # checkpoint layout: A [r, in], B [out, r] -> delta [in, out]
        return (b @ a).T * eff


def load_lora_adapter(path: str, name: Optional[str] = None) -> LoRAAdapter:
    """Load a safetensors LoRA file/dir (PEFT or kohya naming)."""
    from vllm_omni_tpu.model_loader.safetensors_loader import iter_safetensors

    adapter = LoRAAdapter(name or os.path.basename(path))
    for key, arr in iter_safetensors(path):
        m = _LORA_RE.match(key)
        if m:
            module, which = m.group(1), m.group(2)
            if which in ("lora_A", "lora_down"):
                adapter.a[module] = jnp.asarray(arr)
            else:
                adapter.b[module] = jnp.asarray(arr)
            continue
        am = _ALPHA_RE.match(key)
        if am:
            adapter.alpha[am.group(1)] = float(arr)
    if not adapter.modules:
        raise ValueError(f"no LoRA A/B pairs found in {path}")
    return adapter


def _leaf(tree, path: tuple):
    node = tree
    for k in path:
        node = node[int(k)] if isinstance(node, list) else node[k]
    return node


def _set_leaf(tree, path: tuple, value):
    if isinstance(tree, list):
        i = int(path[0])
        if len(path) == 1:
            return tree[:i] + [value] + tree[i + 1:]
        return tree[:i] + [_set_leaf(tree[i], path[1:], value)] + tree[i + 1:]
    if len(path) == 1:
        return {**tree, path[0]: value}
    return {**tree, path[0]: _set_leaf(tree[path[0]], path[1:], value)}


def _default_path_map(mod: str) -> tuple:
    for pre in ("base_model.model.", "transformer.", "diffusion_model."):
        if mod.startswith(pre):
            mod = mod[len(pre):]
            break
    return tuple(mod.split("."))


class LoRAManager:
    """Adapter registry + fused-tree cache (reference manager semantics:
    load/cache/activate with scale; manager.py:33)."""

    def __init__(self, path_map=None, max_cached: int = 4):
        # path_map: adapter module name -> tree path tuple; default maps
        # dotted module names directly ("layers.0.to_q" -> ("layers","0","to_q"))
        # after stripping the wrapper prefixes published adapters carry
        # (PEFT "base_model.model.", diffusers "transformer.")
        self._path_map = path_map or _default_path_map
        self._adapters: dict[str, LoRAAdapter] = {}
        self._fused_cache: dict[tuple, object] = {}
        self._max_cached = max_cached
        # Strong reference to the base tree the cache was built against.
        # An id()-based key could collide after the old tree is collected
        # and its id recycled (ADVICE r1 low); identity-checking a held
        # reference cannot, and the engine keeps the base alive anyway.
        self._base_ref: object = None

    def drop_device_state(self) -> None:
        """Release every device buffer this manager holds (engine sleep
        support): the fused-tree cache and base-tree reference (full
        DiT-sized trees) AND each registered adapter's A/B matrices —
        adapters move to host numpy and transparently transfer back on
        the next activation."""
        import numpy as np

        self._fused_cache.clear()
        self._base_ref = None
        for ad in self._adapters.values():
            ad.a = {k: np.asarray(jax.device_get(v))
                    for k, v in ad.a.items()}
            ad.b = {k: np.asarray(jax.device_get(v))
                    for k, v in ad.b.items()}

    def register(self, adapter: LoRAAdapter) -> None:
        self._adapters[adapter.name] = adapter

    def source_path(self, name: str) -> Optional[str]:
        ad = self._adapters.get(name)
        return getattr(ad, "source_path", None) if ad else None

    def load(self, path: str, name: Optional[str] = None) -> str:
        adapter = load_lora_adapter(path, name)
        adapter.source_path = path
        # a reload under the same name invalidates fused trees built
        # against the previous weights
        self._fused_cache = {k: v for k, v in self._fused_cache.items()
                             if k[0] != adapter.name}
        self.register(adapter)
        return adapter.name

    @property
    def adapter_names(self) -> list[str]:
        return sorted(self._adapters)

    def activate(self, base_params, name: str, scale: float = 1.0):
        """Return the fused param tree for (adapter, scale), cached."""
        if base_params is not self._base_ref:
            self._fused_cache.clear()
            self._base_ref = base_params
        key = (name, round(float(scale), 6))
        if key in self._fused_cache:
            return self._fused_cache[key]
        adapter = self._adapters[name]
        fused = base_params
        applied = 0
        for module in sorted(adapter.modules):
            path = self._path_map(module) + ("w",)
            try:
                w = _leaf(base_params, path)
            except (KeyError, IndexError, TypeError):
                logger.warning("lora %s: no target %s", name, module)
                continue
            delta = adapter.delta(module, scale).astype(w.dtype)
            if delta.shape != w.shape:
                raise ValueError(
                    f"lora {name}:{module} delta {delta.shape} != {w.shape}"
                )
            fused = _set_leaf(fused, path, w + delta)
            applied += 1
        if applied == 0:
            raise ValueError(f"lora {name}: no modules applied")
        if len(self._fused_cache) >= self._max_cached:
            self._fused_cache.pop(next(iter(self._fused_cache)))
        self._fused_cache[key] = fused
        logger.info("lora %s fused into %d modules (scale=%s)",
                    name, applied, scale)
        return fused
