"""Diffusion request/sampling types (reference: OmniDiffusionRequest,
diffusion/request.py:11; OmniDiffusionSamplingParams, inputs/data.py:153)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional


class InvalidRequestError(ValueError):
    """Request-parameter validation failure (client's fault — maps to
    HTTP 400 at the API layer, unlike internal pipeline errors)."""


@dataclass
class OmniDiffusionSamplingParams:
    height: int = 1024
    width: int = 1024
    num_inference_steps: int = 50
    guidance_scale: float = 4.0
    negative_prompt: str = ""
    seed: Optional[int] = None
    num_images_per_prompt: int = 1
    # video / audio extensions
    num_frames: int = 1
    fps: int = 16
    # conditioning image for I2V / image-edit pipelines ([H, W, 3] uint8
    # or float in [-1, 1])
    image: Optional[Any] = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class OmniDiffusionRequest:
    prompt: list[str]
    sampling_params: OmniDiffusionSamplingParams = field(
        default_factory=OmniDiffusionSamplingParams
    )
    request_ids: list[str] = field(default_factory=list)
    # pre-computed text embeddings from an upstream stage (stage
    # disaggregation: text-encoder stage -> DiT stage)
    prompt_embeds: Optional[Any] = None
    negative_prompt_embeds: Optional[Any] = None
    arrival_time: float = field(default_factory=time.time)

    def __post_init__(self):
        if isinstance(self.prompt, str):
            self.prompt = [self.prompt]
        if not self.request_ids:
            self.request_ids = [
                f"diff-{int(self.arrival_time * 1e6)}-{i}"
                for i in range(len(self.prompt))
            ]


@dataclass
class DiffusionOutput:
    request_id: str
    prompt: str
    # [H, W, 3] uint8 (image) | [T, H, W, 3] (video) | [N] float (audio)
    data: Any = None
    output_type: str = "image"
    metrics: dict[str, float] = field(default_factory=dict)
