"""Layerwise weight streaming — run models larger than HBM on one chip.

Role of the reference's layerwise offloader (reference:
vllm_omni/diffusion/offloader/layerwise_backend.py:1 — CUDA-stream
prefetched CPU<->GPU parameter streaming with per-layer hooks).  The
TPU-native shape: block weights stay in HOST memory as numpy trees; a
``BlockStreamer`` walks the block list issuing ``jax.device_put`` ahead of
use (double-buffered), so the DMA of block i+1 overlaps the MXU compute of
block i.  Dropping the device reference after use lets the runtime reclaim
the buffer as soon as its consumer finishes — steady-state HBM holds
~``prefetch`` blocks plus activations, regardless of model size.

There are no CUDA streams or hooks to port: JAX's async dispatch gives the
overlap for free, and one jitted per-block executable (shapes are
identical across blocks) replaces per-layer module wrapping.

Used by the Qwen-Image pipeline (``offload="layerwise"``) to run the REAL
20.4B-parameter 60-layer geometry — 41 GB of bf16 weights — on a 16 GB
v5e chip.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


class BlockStreamer:
    """Stream a list of same-shaped host param trees through a per-block
    function with lookahead transfers.

    ``prefetch=2`` keeps at most two blocks in flight: one computing, one
    transferring — the minimum for full DMA/compute overlap.
    """

    def __init__(self, blocks: list, device=None, prefetch: int = 2):
        if not blocks:
            raise ValueError("need at least one block")
        self.blocks = blocks
        self.device = device if device is not None else jax.devices()[0]
        self.prefetch = max(1, prefetch)

    def _put(self, i: int):
        return jax.device_put(self.blocks[i], self.device)

    def run(self, fn: Callable[[Any, Any], Any], carry):
        """carry = fn(block_on_device, carry) for each block in order.

        Backpressure: device_put and jitted dispatch are both async, so
        without a throttle the Python loop would race ahead and enqueue
        EVERY block's transfer — unbounded HBM, defeating streaming.
        After dispatching block i, the host blocks on the carry produced
        ``prefetch`` blocks earlier: at most ~prefetch block weights are
        resident/in-flight at any moment, and the lookahead transfer
        still overlaps the current block's compute."""
        import jax as _jax

        n = len(self.blocks)
        inflight: deque = deque()
        lagging: deque = deque()
        for j in range(min(self.prefetch, n)):
            inflight.append(self._put(j))
        for i in range(n):
            blk = inflight.popleft()
            nxt = i + self.prefetch
            if nxt < n:
                inflight.append(self._put(nxt))
            carry = fn(blk, carry)
            # drop the device reference: the runtime frees the buffers
            # once the dispatched computation consumes them
            del blk
            lagging.append(carry)
            if len(lagging) > self.prefetch:
                _jax.block_until_ready(lagging.popleft())
        return carry


def host_tiled_init(shapes_tree, dtype, seed: int = 0,
                    pool_elems: int = 1 << 22):
    """Fast host-side init for perf runs: fill every leaf by tiling a
    small N(0, 0.02) pool (memcpy-speed, ~GB/s) instead of generating
    tens of billions of fresh randoms.  TPU matmul timing is
    value-independent, so tiled values bench identically to fresh ones —
    use real checkpoints for quality work.

    ``shapes_tree`` is a ``jax.eval_shape`` result; returns a numpy tree.
    """
    rng = np.random.default_rng(seed)
    np_dtype = np.dtype(jax.numpy.dtype(dtype).name) if not _is_bf16(
        dtype) else None
    pool = (rng.standard_normal(pool_elems) * 0.02).astype(np.float32)

    def fill(leaf):
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        arr = np.resize(pool, n).reshape(leaf.shape)
        if np_dtype is None:
            import ml_dtypes

            return arr.astype(ml_dtypes.bfloat16)
        return arr.astype(np_dtype)

    return jax.tree.map(fill, shapes_tree)


def _is_bf16(dtype) -> bool:
    return jax.numpy.dtype(dtype).name == "bfloat16"


def split_host_blocks(params, key: str):
    """Split a host param tree into (top-level tree without ``key``,
    list-of-blocks under ``key``) for streaming."""
    top = {k: v for k, v in params.items() if k != key}
    return top, list(params[key])
