"""Layerwise weight streaming — run models larger than HBM on one chip.

Role of the reference's layerwise offloader (reference:
vllm_omni/diffusion/offloader/layerwise_backend.py:1 — CUDA-stream
prefetched CPU<->GPU parameter streaming with per-layer hooks).  The
TPU-native shape: block weights stay in HOST memory as numpy trees; a
``BlockStreamer`` walks the block list issuing ``jax.device_put`` ahead of
use (double-buffered), so the DMA of block i+1 overlaps the MXU compute of
block i.  Dropping the device reference after use lets the runtime reclaim
the buffer as soon as its consumer finishes — steady-state HBM holds
~``prefetch`` blocks plus activations, regardless of model size.

There are no CUDA streams or hooks to port: JAX's async dispatch gives the
overlap for free, and one jitted per-block executable (shapes are
identical across blocks) replaces per-layer module wrapping.

Used by the Qwen-Image pipeline (``offload="layerwise"``) to run the REAL
20.4B-parameter 60-layer geometry — 41 GB of bf16 weights — on a 16 GB
v5e chip.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


class BlockStreamer:
    """Stream a list of same-shaped host param trees through a per-block
    function with lookahead transfers.

    ``prefetch=2`` keeps at most two blocks in flight: one computing, one
    transferring — the minimum for full DMA/compute overlap.

    ``pinned``: keep the first N blocks RESIDENT in HBM (transferred once
    at construction).  A streamed walk is transfer-bound, so every pinned
    block cuts per-step traffic by one block — size N to what HBM can
    spare beyond activations and the in-flight double buffer
    (``auto_pin``).  ``jax.device_put`` of an already-resident array is a
    no-op, so the walk itself needs no special-casing.
    """

    def __init__(self, blocks: list, device=None, prefetch: int = 2,
                 pinned: int = 0, sync_every: int = 4):
        if not blocks:
            raise ValueError("need at least one block")
        self.device = device if device is not None else jax.devices()[0]
        self.prefetch = max(1, prefetch)
        # how often the host waits on an old carry: every sync costs a
        # device round trip (remote/tunneled chips have ~1s RPC latency,
        # which would dominate the walk if paid per block); batching the
        # backpressure to every N blocks bounds in-flight HBM at
        # ~(prefetch + sync_every) blocks while paying len/N round trips
        self.sync_every = max(1, sync_every)
        pinned = max(0, min(int(pinned), len(blocks)))
        self.pinned = pinned
        if pinned:
            logger.info("pinning %d/%d blocks resident in HBM",
                        pinned, len(blocks))
            resident = [jax.device_put(b, self.device)
                        for b in blocks[:pinned]]
            # one pytree-wide wait: per-block waits would pay one device
            # round trip each (~1s on tunneled chips)
            jax.block_until_ready(resident)
            self.blocks = resident + list(blocks[pinned:])
        else:
            self.blocks = blocks

    @staticmethod
    def auto_pin(blocks: list, reserve_bytes: float = 3.5e9,
                 prefetch: int = 2, sync_every: int = 4) -> int:
        """How many blocks fit resident: (HBM - reserve - in-flight
        headroom) / block size.  ``reserve_bytes`` covers the OTHER
        persistent consumers of a streaming pipeline — resident non-block
        params (e.g. a 1.1 GB text embed table), the fp32 VAE,
        activations, executable scratch; the in-flight headroom covers
        the worst case of run()'s batched backpressure (~prefetch +
        sync_every un-consumed streamed blocks, plus slack), which also
        bounds any sibling streamed walk (the text encoder's layers are
        smaller than DiT blocks)."""
        per_block = sum(
            leaf.nbytes for leaf in jax.tree.leaves(blocks[0]))
        try:
            from vllm_omni_tpu.platforms import current_platform

            hbm = current_platform().hbm_bytes() or 16e9
        except Exception:
            hbm = 16e9
        budget = hbm - reserve_bytes - (prefetch + sync_every + 2) * per_block
        return max(0, min(len(blocks), int(budget // per_block)))

    def _put(self, i: int):
        return jax.device_put(self.blocks[i], self.device)

    def run(self, fn: Callable[[Any, Any], Any], carry):
        """carry = fn(block_on_device, carry) for each block in order.

        Backpressure: device_put and jitted dispatch are both async, so
        without a throttle the Python loop would race ahead and enqueue
        EVERY block's transfer — unbounded HBM, defeating streaming.
        After dispatching block i, the host blocks on the carry produced
        ``prefetch`` blocks earlier: at most ~prefetch block weights are
        resident/in-flight at any moment, and the lookahead transfer
        still overlaps the current block's compute."""
        import jax as _jax

        n = len(self.blocks)
        inflight: deque = deque()
        lagging: deque = deque()
        for j in range(min(self.prefetch, n)):
            inflight.append(self._put(j))
        for i in range(n):
            blk = inflight.popleft()
            nxt = i + self.prefetch
            if nxt < n:
                inflight.append(self._put(nxt))
            carry = fn(blk, carry)
            # drop the device reference: the runtime frees the buffers
            # once the dispatched computation consumes them
            del blk
            lagging.append(carry)
            if len(lagging) > self.prefetch + self.sync_every:
                # drain a batch of old carries in one wait (their
                # computations chain, so waiting on the newest of the
                # batch covers the rest)
                batch = [lagging.popleft()
                         for _ in range(self.sync_every)]
                _jax.block_until_ready(batch[-1])
        return carry


def host_tiled_init(shapes_tree, dtype, seed: int = 0,
                    pool_elems: int = 1 << 22):
    """Fast host-side init for perf runs: fill every leaf by tiling a
    small N(0, 0.02) pool (memcpy-speed, ~GB/s) instead of generating
    tens of billions of fresh randoms.  TPU matmul timing is
    value-independent, so tiled values bench identically to fresh ones —
    use real checkpoints for quality work.

    ``shapes_tree`` is a ``jax.eval_shape`` result; returns a numpy tree.
    """
    rng = np.random.default_rng(seed)
    pool = (rng.standard_normal(pool_elems) * 0.02).astype(np.float32)
    # cast the POOL once (elementwise bf16 conversion runs ~100 MB/s in
    # numpy/ml_dtypes — casting tens of GB leaf-by-leaf takes tens of
    # minutes); tiling the pre-cast pool is a memcpy
    if _is_bf16(dtype):
        import ml_dtypes

        pool = pool.astype(ml_dtypes.bfloat16)
    else:
        pool = pool.astype(np.dtype(jax.numpy.dtype(dtype).name))

    def fill(leaf):
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        return np.resize(pool, n).reshape(leaf.shape)

    return jax.tree.map(fill, shapes_tree)


def _is_bf16(dtype) -> bool:
    return jax.numpy.dtype(dtype).name == "bfloat16"


def host_tiled_init_aliased(shapes_tree, dtype, block_key: str,
                            seed: int = 0, distinct: int = 8):
    """Tiled host init where the repeated blocks under ``block_key``
    ALIAS ``distinct`` materialized trees cyclically.

    Rationale: perf-run weights are value-independent, but first-touch
    page faults on fresh host memory can run ~50 MB/s on sandboxed VMs —
    materializing 40+ GB of distinct randoms takes tens of minutes while
    the streamed TRANSFER volume (what the bench measures) is identical
    whether block i and block i+8 share a host buffer or not.  ``distinct``
    exceeding the streamer's in-flight depth (prefetch + sync_every)
    keeps every in-flight ``jax.device_put`` operating on a different
    buffer, so no transfer can be elided by caching."""
    blocks_shapes = shapes_tree[block_key]
    n = len(blocks_shapes)
    top_shapes = {k: v for k, v in shapes_tree.items() if k != block_key}
    out = host_tiled_init(top_shapes, dtype, seed=seed)
    distinct = max(1, min(distinct, n))
    protos = [
        host_tiled_init(blocks_shapes[j], dtype, seed=seed + 1 + j)
        for j in range(distinct)
    ]
    out[block_key] = [protos[i % distinct] for i in range(n)]
    return out


def split_host_blocks(params, key: str):
    """Split a host param tree into (top-level tree without ``key``,
    list-of-blocks under ``key``) for streaming."""
    top = {k: v for k, v in params.items() if k != key}
    return top, list(params[key])
