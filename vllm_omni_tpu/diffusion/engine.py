"""DiffusionEngine — engine facade for DiT pipelines.

Role of the reference's ``DiffusionEngine`` (diffusion/diffusion_engine.py:
45,69,183,345): resolve the pipeline class from the registry, build it from
``OmniDiffusionConfig``, warm up the jit cache with a dummy generation, and
serve ``step(OmniDiffusionRequest) -> [DiffusionOutput]``.

Where the reference spawns a multiproc executor with one WorkerProc per
GPU + shm MessageQueue broadcast (executor/multiproc_executor.py:47), the
TPU-native engine is single-controller: one process drives the whole mesh
through pjit — the intra-stage fan-out machinery collapses into XLA
(SURVEY.md §7 design stance #1).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.config.diffusion import OmniDiffusionConfig
from vllm_omni_tpu.config.model import resolve_dtype
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    OmniDiffusionRequest,
    OmniDiffusionSamplingParams,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.stats import Histogram
from vllm_omni_tpu.models.registry import DiffusionModelRegistry

logger = init_logger(__name__)

# diffusion batch wall times run seconds-to-minutes, not milliseconds
_GEN_SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                        60.0, 120.0, 300.0)


_UNSET = object()


def resolve_arch(config: OmniDiffusionConfig, declared=_UNSET) -> str:
    """Pipeline class from explicit config or the checkpoint's
    model_index.json ``_class_name`` (reference: omni_diffusion.py:34-109);
    single-repo HF checkpoints (HunyuanImage-3) resolve via config.json
    ``architectures`` instead.  ``declared`` lets a caller that already
    parsed config.json pass its result in (one parse, one view)."""
    if config.model_arch:
        return config.model_arch
    idx = os.path.join(config.model, "model_index.json")
    if os.path.isfile(idx):
        with open(idx) as f:
            name = json.load(f).get("_class_name", "")
        if name:
            return name
    if declared is _UNSET:
        declared = _declared_arch(config.model) if config.model else None
    if declared:
        return declared
    # default flagship
    return "QwenImagePipeline"


def _declared_arch(model: str):
    """Registry architecture declared by a local dir's config.json
    (single-repo HF layout, no model_index.json), or None.  Mirrors the
    reference routing (omni_diffusion.py:78-83): any listed
    architecture the registry knows, plus model_type == "bagel"."""
    p = os.path.join(model, "config.json")
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            cfg = json.load(f)
    except Exception:
        return None
    supported = DiffusionModelRegistry.supported()
    for arch in cfg.get("architectures") or []:
        if arch in supported:
            return arch
    if cfg.get("model_type") == "bagel":
        return "BagelPipeline"
    return None


def _arch_checkpoint(model: str) -> bool:
    return _declared_arch(model) is not None


class DiffusionEngine:
    def __init__(self, od_config: OmniDiffusionConfig, warmup: bool = True):
        self.od_config = od_config
        declared = (_declared_arch(od_config.model)
                    if od_config.model else None)
        arch = resolve_arch(od_config, declared)
        pipeline_cls = DiffusionModelRegistry.resolve(arch)
        dtype = resolve_dtype(od_config.dtype)
        size = od_config.extra.get("size", "")
        pipe_cfg = self._pipeline_config(pipeline_cls, size)
        solver = od_config.extra.get("scheduler")
        if solver:
            if solver not in ("euler", "unipc"):
                raise ValueError(
                    f"unknown scheduler {solver!r} (euler | unipc)")
            if not hasattr(pipe_cfg, "scheduler"):
                raise ValueError(
                    f"{arch} does not support a scheduler override")
            import dataclasses

            pipe_cfg = dataclasses.replace(pipe_cfg, scheduler=solver)
        logger.info("Building %s (size=%s dtype=%s)", arch, size or "default", dtype)
        cache_config = None
        if od_config.cache_backend:
            if od_config.cache_backend not in ("teacache", "dbcache",
                                              "taylorseer"):
                raise ValueError(
                    f"unsupported cache_backend {od_config.cache_backend!r} "
                    "(TPU path supports 'teacache', 'dbcache', "
                    "'taylorseer')"
                )
            from vllm_omni_tpu.diffusion.cache import StepCacheConfig

            cache_config = StepCacheConfig.from_dict(
                od_config.cache_backend, od_config.cache_config
            )
        mesh = None
        if od_config.parallel.world_size > 1:
            # Stage mesh from the configured parallel degrees (reference:
            # initialize_model_parallel, parallel_state.py:624); the
            # pipeline shards weights/activations over it.
            from vllm_omni_tpu.parallel.mesh import build_mesh

            mesh = build_mesh(
                od_config.parallel,
                jax.devices()[: od_config.parallel.world_size],
            )
        self.mesh = mesh
        extra_kwargs = {}

        def require_ctor_param(name, value):
            # optional pipeline features are opted into per-arch by
            # declaring the kwarg; anything else fails loudly here
            # rather than as a TypeError deep in the constructor
            import inspect

            if name not in inspect.signature(
                    pipeline_cls.__init__).parameters:
                raise ValueError(
                    f"{arch} does not support {name}={value!r}")
            extra_kwargs[name] = value

        if od_config.offload:
            require_ctor_param("offload", od_config.offload)
        step_loop = od_config.extra.get("step_loop")
        if step_loop:
            require_ctor_param("step_loop", step_loop)
        step_chunk = od_config.extra.get("step_chunk")
        if step_chunk is not None:  # 0 must reach pipeline validation
            require_ctor_param("step_chunk", int(step_chunk))
        from_ckpt = (
            od_config.model
            and (os.path.isfile(os.path.join(od_config.model,
                                             "model_index.json"))
                 # single-repo HF checkpoints (HunyuanImage-3) carry a
                 # registry architecture in config.json instead
                 or declared is not None)
            and hasattr(pipeline_cls, "from_pretrained")
        )
        quant_at_init = False
        if od_config.quantization in ("int8", "fp8", "int4") \
                and not from_ckpt and not od_config.offload \
                and mesh is None:  # sharded builds quantize post-hoc
            import inspect

            # pipelines exposing quantize_init quantize each DiT block as
            # it is initialized — the only way a model whose bf16 tree
            # exceeds HBM (real Qwen-Image: 41 GB vs 16 GB) can be built
            # quantized-resident; post-hoc quantization would have to
            # materialize the float tree first
            if "quantize_init" in inspect.signature(
                    pipeline_cls.__init__).parameters:
                extra_kwargs["quantize_init"] = od_config.quantization
                quant_at_init = True
        if from_ckpt:
            # diffusers-format checkpoint directory: real weights
            self.pipeline = pipeline_cls.from_pretrained(
                od_config.model, dtype=dtype, seed=od_config.seed,
                cache_config=cache_config, mesh=mesh, **extra_kwargs,
            )
            if solver and hasattr(self.pipeline.cfg, "scheduler"):
                # from_pretrained builds its own config; re-apply the
                # override (it was validated above) before any denoise
                # executable is traced
                import dataclasses

                self.pipeline.cfg = dataclasses.replace(
                    self.pipeline.cfg, scheduler=solver)
        else:
            if od_config.model and os.path.isdir(od_config.model):
                # a real directory without model_index.json is a broken
                # checkpoint path, not a preset name — don't silently
                # serve random weights
                raise ValueError(
                    f"model dir {od_config.model!r} has no "
                    "model_index.json (not a diffusers-format checkpoint)"
                )
            if od_config.model:
                logger.warning(
                    "model %r is not a local checkpoint directory; "
                    "building %s with random-init weights",
                    od_config.model, arch,
                )
            self.pipeline = pipeline_cls(
                pipe_cfg, dtype=dtype, seed=od_config.seed,
                cache_config=cache_config, mesh=mesh, **extra_kwargs,
            )
        if quant_at_init:
            pass  # already quantized block-by-block during init
        elif od_config.quantization in ("int8", "fp8", "int4"):
            from vllm_omni_tpu.diffusion.quantization import (
                quantize_params,
                quantize_params_host,
            )

            # layerwise-streamed trees live in HOST memory: quantize
            # there (halves the per-step host->HBM transfer the walk is
            # bound by); the jnp path would round-trip every block
            # through the device
            quantize = (
                quantize_params_host
                if getattr(self.pipeline, "offload", "") == "layerwise"
                else quantize_params)
            self.pipeline.dit_params = quantize(
                self.pipeline.dit_params, mode=od_config.quantization
            )
        elif od_config.quantization:
            raise ValueError(
                f"unsupported quantization {od_config.quantization!r} "
                "(TPU path supports 'int8'/'fp8'/'int4' weight-only)"
            )
        from vllm_omni_tpu.diffusion.lora import LoRAManager

        self.lora_manager = LoRAManager()
        # observability: step counters + batch-time histogram surfaced
        # through /metrics; stage_id stamped by OmniStage
        self.stage_id = 0
        self._num_requests = 0
        self._num_batches = 0
        self._gen_seconds = Histogram(buckets=_GEN_SECONDS_BUCKETS)
        if warmup:
            self._warmup()

    def metrics_snapshot(self) -> dict:
        return {"diffusion": {
            "requests_total": self._num_requests,
            "batches_total": self._num_batches,
            "gen_seconds": self._gen_seconds.snapshot(),
        }}

    @staticmethod
    def _pipeline_config(pipeline_cls, size: str):
        # Pipelines expose tiny()/bench() presets on their config
        # dataclass; subclasses that reuse a parent's __init__ but carry
        # their own config declare it via ``config_cls``.
        import inspect

        cfg_type = getattr(pipeline_cls, "config_cls", None)
        if cfg_type is None:
            sig = inspect.signature(pipeline_cls.__init__)
            cfg_type = sig.parameters["config"].annotation
            if isinstance(cfg_type, str):
                # postponed annotation: resolve from the module DEFINING
                # the __init__ (an inheriting pipeline's own module may
                # not import the parent's config name)
                import importlib

                mod = importlib.import_module(
                    pipeline_cls.__init__.__module__)
                cfg_type = getattr(mod, cfg_type)
        if size and hasattr(cfg_type, size):
            return getattr(cfg_type, size)()
        return cfg_type()

    def _warmup(self):
        """Compile-warm the denoise loop with a 1-step generation at the
        serving geometry (reference _dummy_run, diffusion_engine.py:316-343).
        The step count is a dynamic loop bound (pipeline steps_bucket), so
        the 1-step warmup compiles the same executable real requests use."""
        t0 = time.perf_counter()
        modality = getattr(self.pipeline, "output_type", "image")
        if modality == "audio":
            sp = OmniDiffusionSamplingParams(
                num_inference_steps=1, guidance_scale=1.0, seed=0,
                extra={"seconds_total": 0.25},
            )
        else:
            mult = getattr(self.pipeline, "geometry_multiple", None)
            if mult is None:
                mult = (
                    self.pipeline.cfg.vae.spatial_ratio
                    * self.pipeline.cfg.dit.patch_size
                )
            h0, w0 = self.od_config.default_height, self.od_config.default_width
            if modality == "video":
                # Video warmup must not reuse the image default geometry:
                # frames * (H/mult) * (W/mult) latent tokens at 1024² with
                # CFG-doubled batch tried to allocate ~1.1 TiB (ADVICE
                # high, round 1). Warm the compile cache at a small spatial
                # size; serving geometries compile on first use like any
                # other shape bucket.
                h0 = min(h0, self.od_config.warmup_video_size)
                w0 = min(w0, self.od_config.warmup_video_size)
            height = max(mult, h0 // mult * mult)
            width = max(mult, w0 // mult * mult)
            sp = OmniDiffusionSamplingParams(
                height=height, width=width, num_inference_steps=1,
                guidance_scale=4.0, seed=0,
                num_frames=2 if modality == "video" else 1,
            )
            if getattr(self.pipeline, "needs_image_cond", False):
                # I2V / image-edit pipelines require a conditioning image
                import numpy as np

                sp.image = np.zeros((height, width, 3), np.uint8)
        self.pipeline.forward(OmniDiffusionRequest(
            prompt=["warmup"], sampling_params=sp))
        logger.info("Warmup done in %.1fs", time.perf_counter() - t0)

    # ------------------------------------------------------- sleep / wake
    # default weight-tree attributes; pipelines with extra trees (e.g.
    # GLM-Image's AR prior) declare their own ``param_attrs`` so sleep()
    # frees EVERYTHING
    _PARAM_ATTRS = ("dit_params", "text_params", "vae_params",
                    "vae_encoder_params", "decoder_params")

    def _param_attrs(self):
        return getattr(self.pipeline, "param_attrs", self._PARAM_ATTRS)

    @property
    def is_asleep(self) -> bool:
        return getattr(self, "_asleep", False)

    def sleep(self) -> None:
        """Offload every pipeline weight tree to host RAM, freeing HBM for
        sibling stages sharing the chip (reference: CuMemAllocator
        sleep/wake, diffusion/worker/diffusion_worker.py:204-271; the TPU
        host-offload row of SURVEY §2.10).  ``step`` refuses while asleep;
        ``wake`` restores the original device placement."""
        if self.is_asleep:
            return
        import numpy as np

        self._host_stash = {}
        for attr in self._param_attrs():
            tree = getattr(self.pipeline, attr, None)
            if tree is None:
                continue
            # device_get copies to host; dropping the pipeline reference
            # releases the HBM buffers
            self._host_stash[attr] = jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x)), tree
            )
            setattr(self.pipeline, attr, None)
        # pipelines with DERIVED trees (e.g. Hunyuan's aliased shared
        # stack) drop them here so no stale device references survive
        hook = getattr(self.pipeline, "post_sleep", None)
        if hook is not None:
            hook()
        # fused LoRA trees + the base ref hold full DiT-sized device
        # buffers; drop them or the eviction is theater
        self.lora_manager.drop_device_state()
        self._asleep = True
        logger.info("engine asleep: %d weight trees offloaded to host",
                    len(self._host_stash))

    def wake(self) -> None:
        if not self.is_asleep:
            return
        place = getattr(self.pipeline, "_place", None)
        for attr, tree in self._host_stash.items():
            if place is not None:
                tree = place(tree, tp=(attr == "dit_params"))
            else:
                tree = jax.device_put(tree)
            setattr(self.pipeline, attr, tree)
        self._host_stash = {}
        self._asleep = False
        hook = getattr(self.pipeline, "post_wake", None)
        if hook is not None:
            hook()
        logger.info("engine awake: weights restored to device")

    def load_lora(self, path: str, name: Optional[str] = None) -> str:
        """Register a LoRA adapter (reference: DiffusionLoRAManager load,
        lora/manager.py:33)."""
        if self.od_config.quantization:
            raise ValueError(
                "LoRA fusion targets float weights; it cannot combine with "
                f"quantization={self.od_config.quantization!r}"
            )
        return self.lora_manager.load(path, name)

    def step(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        if self.is_asleep:
            raise RuntimeError(
                "engine is asleep (weights offloaded to host); call wake()"
            )
        t0 = time.perf_counter()
        # per-request LoRA activation via sampling extras (reference:
        # lora_manager.set_active_adapter, diffusion_worker.py:178-184)
        lora = req.sampling_params.extra.get("lora")
        base = self.pipeline.dit_params
        if lora and self.od_config.quantization:
            raise ValueError(
                "per-request LoRA cannot combine with quantized weights"
            )
        if lora:
            if isinstance(lora, str):
                name, scale, path = lora, 1.0, None
            else:
                name = lora.get("name")
                scale = lora.get("scale", 1.0)
                path = lora.get("path")
            from vllm_omni_tpu.diffusion.request import (
                InvalidRequestError,
            )

            if name is None:
                raise InvalidRequestError("lora request needs a 'name'")
            # serving-layer convenience (reference: per-request lora
            # {name, path, scale} through the Images API,
            # tests/e2e/online_serving/test_images_generations_lora.py):
            # unseen adapters load on first use from their path; the
            # SAME name with a DIFFERENT path reloads (serving the old
            # weights silently would be a trap)
            if path and (name not in self.lora_manager.adapter_names
                         or self.lora_manager.source_path(name) != path):
                self.load_lora(path, name)
            if name not in self.lora_manager.adapter_names:
                # a client naming typo is a 400, not a stage crash
                raise InvalidRequestError(
                    f"unknown lora adapter {name!r} (loaded: "
                    f"{self.lora_manager.adapter_names}); pass a 'path' "
                    "to load it")
            self.pipeline.dit_params = self.lora_manager.activate(
                base, name, scale
            )
        try:
            outs = self.pipeline.forward(req)
        finally:
            self.pipeline.dit_params = base
        dt = time.perf_counter() - t0
        self._num_batches += 1
        self._num_requests += len(outs)
        self._gen_seconds.observe(dt)
        for o in outs:
            o.metrics["gen_s"] = dt
        return outs

    @classmethod
    def make_engine(cls, od_config: OmniDiffusionConfig) -> "DiffusionEngine":
        return cls(od_config)
