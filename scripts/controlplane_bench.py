#!/usr/bin/env python
"""BENCH_r12: the closed-loop control-plane bench (docs/control_plane.md).

A diurnal trace whose traffic MIX shifts over the period — the peak
half-cycle is ingest-shaped (long prompts, 2-token outputs: prefill
pressure) and the trough half-cycle is chat-shaped (short prompts,
longer outputs: decode pressure) — is replayed open-loop over an
in-proc disaggregated fleet at a FIXED replica budget.  Every static
{prefill x decode} split is wrong for half the period by construction;
the controller re-roles to track the mix.  The scoreboard is the
serving curve: the controlled fleet must beat every static topology on
goodput and SLO attainment at the same budget.

Writes BENCH_r12.json: one schema-valid serving_curve point per
configuration, the controller's action ring/sensor summary, and a
mid-flight /metrics probe (validate_exposition clean, controlplane
series live).  Exits nonzero if the controller loses to any static
split (skipped with --smoke, the CI-speed run).

    JAX_PLATFORMS=cpu python scripts/controlplane_bench.py
    JAX_PLATFORMS=cpu python scripts/controlplane_bench.py --smoke
"""

import argparse
import json
import math
import sys
import threading
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from vllm_omni_tpu.controlplane import (  # noqa: E402
    ControlPlane,
    ControlPlaneConfig,
)
from vllm_omni_tpu.disagg.service import (  # noqa: E402
    DisaggService,
    build_inproc_router,
)
from vllm_omni_tpu.engine import EngineConfig  # noqa: E402
from vllm_omni_tpu.loadgen import (  # noqa: E402
    LoadRequest,
    SLOTargets,
    diurnal_arrivals,
    run_inproc,
    summarize,
    validate_curve_point,
)
from vllm_omni_tpu.metrics.prometheus import (  # noqa: E402
    validate_exposition,
)
from vllm_omni_tpu.models.common import transformer as tfm  # noqa: E402
from vllm_omni_tpu.sampling_params import SamplingParams  # noqa: E402


def build_trace(n_requests: int, rate: float, period_s: float,
                seed: int) -> list[LoadRequest]:
    """Diurnal arrivals with a phase-dependent mix: peak half-cycle =
    ingest (prefill-heavy), trough = chat (decode-heavy).  Fully
    seeded — both configurations replay the IDENTICAL trace."""
    import random

    rng = random.Random(seed + 1)
    offsets = diurnal_arrivals(rate, n_requests, period_s=period_s,
                               amplitude=0.6, seed=seed)
    out = []
    for i, t in enumerate(offsets):
        peak = math.sin(2 * math.pi * t / period_s) > 0
        if peak:
            n_prompt, max_tokens, scen = rng.randint(40, 56), 2, "ingest"
        else:
            n_prompt, max_tokens, scen = rng.randint(6, 10), 16, "chat"
        out.append(LoadRequest(
            at_s=t, request_id=f"bench-{i}", scenario=scen,
            tenant="default",
            prompt_token_ids=[rng.randrange(1, 60)
                              for _ in range(n_prompt)],
            max_tokens=max_tokens))
    return out


def run_config(params, cfg, n_prefill, n_decode, trace, slo,
               controlled=False, probe_at=None):
    """One trace replay over one topology; returns (curve_point,
    extras).  ``controlled`` attaches the ControlPlane; ``probe_at``
    (seconds) scrapes /metrics mid-flight on a side thread."""
    base = EngineConfig(
        num_pages=96, page_size=4, max_model_len=160, max_num_seqs=2,
        max_num_batched_tokens=256, dtype=jnp.float32,
        slo_ttft_ms=slo.ttft_ms, slo_tpot_ms=None,
        max_queue_depth=24,
        # precompile BEFORE the trace: a shape-cache miss mid-traffic
        # is a 20-40 s stall that would swamp the topology signal the
        # bench exists to measure — and a re-roled replica must serve
        # its NEW role's shapes without a compile storm, so every
        # engine warms both roles' shape families up front
        warmup=[(1, 8), (1, 16), (1, 64), (2, 8), (2, 16), (2, 64)])
    router = build_inproc_router(params, cfg, base, n_prefill,
                                 n_decode)
    cp = None
    if controlled:
        cp = ControlPlane(router, ControlPlaneConfig(
            poll_interval_s=0.2, hysteresis_ticks=2, cooldown_ticks=8,
            band_low=0.55, band_high=1.8, saturation_gain=2.0))
    service = DisaggService(router, controlplane=cp)
    probe = {}

    def _probe():
        time.sleep(probe_at)
        text = service.render_metrics()
        probe["errors"] = validate_exposition(text)
        probe["controlplane_series_live"] = (
            "controlplane_replicas" in text)
        probe["series"] = sum(1 for ln in text.splitlines()
                              if ln and not ln.startswith("#"))

    prober = None
    if probe_at is not None:
        prober = threading.Thread(target=_probe, daemon=True)
        prober.start()
    t0 = time.monotonic()
    records = run_inproc(service, trace, timeout_s=600.0)
    wall = time.monotonic() - t0
    if prober is not None:
        prober.join(timeout=30)
    offered = len(trace) / max(trace[-1].at_s, 1e-9)
    point = summarize(records, offered_rps=offered, slo=slo)
    extras = {
        "topology": f"{n_prefill}Px{n_decode}D"
                    + ("+ctl" if controlled else ""),
        "wall_s": round(wall, 2),
        "final_shape": {
            "prefill": len(router.prefills),
            "decode": len(router.decodes),
        },
    }
    if cp is not None:
        snap = cp.debug_snapshot()
        extras["controller"] = {
            "reroles": snap["counters"]["reroles"],
            "actions": snap["counters"]["actions"],
            "ticks": snap["ticks"],
            "ring_tail": snap["ring"][-12:],
        }
    if probe:
        extras["metrics_probe"] = probe
    service.shutdown()
    return point, extras


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-speed run: controlled config only, no "
                         "static-comparison assert")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--rate", type=float, default=5.0)
    ap.add_argument("--period", type=float, default=16.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="BENCH_r12.json")
    args = ap.parse_args()

    n = args.requests or (16 if args.smoke else 80)
    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    trace = build_trace(n, args.rate, args.period, args.seed)
    # TTFT is where topology shows: a tier starved for its phase
    # queues arrivals, and queue wait IS the TTFT tail.  The target
    # sits ~6x above the right-shaped fleet's p99 and well under the
    # wrong-shaped fleet's — the signal, not the noise, decides
    slo = SLOTargets(ttft_ms=600.0, e2e_ms=10000.0)
    budget = 3  # replicas, every configuration
    doc = {"bench": "BENCH_r12_controlplane_diurnal",
           "trace": {"requests": n, "rate_rps": args.rate,
                     "period_s": args.period, "seed": args.seed,
                     "mix": "peak=ingest(40-56 prompt/2 out), "
                            "trough=chat(6-10 prompt/16 out)"},
           "slo": slo.as_dict(), "replica_budget": budget,
           "serving_curve": []}

    configs = [] if args.smoke else [(2, 1, False), (1, 2, False)]
    configs.append((1, 2, True))
    for n_pre, n_dec, controlled in configs:
        point, extras = run_config(
            params, cfg, n_pre, n_dec, trace, slo,
            controlled=controlled,
            probe_at=(trace[-1].at_s * 0.6) if controlled else None)
        errs = validate_curve_point(point)
        assert not errs, f"curve point schema violations: {errs}"
        point.update(extras)
        doc["serving_curve"].append(point)
        print(f"[{extras['topology']}] goodput="
              f"{point['goodput_req_per_s']} req/s "
              f"attainment={point['slo_attainment']} "
              f"shed={point['shed']} "
              f"ttft_p99={point['ttft_ms']['p99']}ms "
              f"final={extras['final_shape']}")

    ctl = doc["serving_curve"][-1]
    probe = ctl.get("metrics_probe", {})
    assert probe.get("errors") == [], \
        f"mid-flight /metrics probe not clean: {probe.get('errors')}"
    assert probe.get("controlplane_series_live"), \
        "controlplane series must be live on the mid-flight scrape"
    assert ctl["controller"]["reroles"] >= 1, \
        "the diurnal mix shift must drive at least one re-role"
    if not args.smoke:
        statics = doc["serving_curve"][:-1]
        beaten = all(
            ctl["goodput_req_per_s"] > s["goodput_req_per_s"]
            and ctl["slo_attainment"] >= s["slo_attainment"]
            for s in statics)
        doc["controller_beats_every_static"] = beaten
        assert beaten, (
            "controller lost to a static topology: "
            + json.dumps([{k: s[k] for k in
                           ("topology", "goodput_req_per_s",
                            "slo_attainment")}
                          for s in doc["serving_curve"]], indent=2))
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
