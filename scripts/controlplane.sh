#!/usr/bin/env sh
# Control-plane gate: omnictl end to end — the fake-clock controller
# matrix (pressure model, hysteresis/cooldown anti-flap, the
# drain -> quiesce -> flip -> re-admit state machine, autoscale
# warmup/floors/SLO gating, ring bounds), the WFQ scheduler contract
# (DRR hand-oracle, starvation freedom, priority-ordered shed,
# deferral ledger), the router actuator surface (set_role /
# add_replica / remove_replica / refresh_gauges regression), the
# tiny-model e2e matrix (re-role mid-stream bit-identical to the
# colocated oracle, controller-driven re-role on a live fleet with a
# validate-clean /metrics render, seeded replica-kill convergence
# without flapping, the two-tenant WFQ /metrics split), and finally
# the diurnal trace-replay bench in --smoke mode (schema-valid curve
# point, mid-flight metrics probe clean, at least one re-role).
#
# Standalone face of the same coverage tier-1 carries
# (tests/controlplane + tests/core/test_wfq.py are fast), sitting next
# to scripts/disagg.sh and scripts/loadgen.sh as a pre-merge gate:
#
#   scripts/controlplane.sh              # the whole control-plane contract
#   scripts/controlplane.sh -k rerole    # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the e2e kills replicas and flips roles on purpose; it
# must never touch a real TPU chip a colocated serving process owns
env JAX_PLATFORMS=cpu python -m pytest \
    tests/controlplane/ tests/core/test_wfq.py \
    -q -p no:cacheprovider -m "not slow" "$@"
# trace-replay e2e: the closed-loop diurnal bench, CI-speed — exits
# nonzero unless the controller re-roles, the serving-curve point is
# schema-valid, and the mid-flight /metrics probe validates clean
exec env JAX_PLATFORMS=cpu python scripts/controlplane_bench.py \
    --smoke --out /tmp/BENCH_r12_smoke.json
