#!/usr/bin/env sh
# perfguard gate: CI owns the performance trajectory.
#
# Two legs, mirroring scripts/loadgen.sh as a standalone pre-merge
# gate:
#
#   1. the perfguard unit tier (tests/benchmarks/test_perfguard.py):
#      extractor over every known BENCH_*.json shape, the delta/gate
#      math on hand-built pass / regress / schema-mismatch fixtures,
#      and the live comparison against the repo's own artifacts.
#   2. the deterministic trajectory check: regenerate the virtual-time
#      guard curve (seeded Poisson workload through the loadgen
#      simulator — bit-identical across machines, zero wall-clock) and
#      compare it against the committed BENCH_guard_baseline.json at a
#      TIGHT threshold.  Any change to the admission / goodput /
#      summarize math shows up as a delta here and fails the gate; a
#      deliberate change regenerates the baseline in the same commit:
#
#          python scripts/perfguard.py --emit-guard-curve \
#              BENCH_guard_baseline.json
#
# Usage:
#   scripts/perfguard.sh                    # the whole gate
#   scripts/perfguard.sh -k regress         # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU defensively: the compare paths are stdlib-only and the
# guard-curve emitter imports only the (jax-free) loadgen package, but
# the pytest leg must never touch a real chip a serving process owns
env JAX_PLATFORMS=cpu python -m pytest \
    tests/benchmarks/test_perfguard.py \
    -q -p no:cacheprovider -m "not slow" "$@"

# no exec on the final leg: POSIX sh does not run EXIT traps across
# exec, which would leak one temp curve per gate run
tmp="$(mktemp /tmp/perfguard_curve.XXXXXX.json)"
trap 'rm -f "$tmp"' EXIT
python scripts/perfguard.py --emit-guard-curve "$tmp" >/dev/null
python scripts/perfguard.py BENCH_guard_baseline.json "$tmp" \
    --threshold 0.01 --strict
