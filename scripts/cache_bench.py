#!/usr/bin/env python
"""BENCH_r16/r19: the shared-prefix cache bench, blind vs affinity
(docs/disaggregation.md).

A shared-prefix multi-tenant workload — N tenant-pinned scenarios all
opening with ONE common system prompt (``shared_prefix_catalog``) — is
replayed open-loop over a 2 prefill x 2 decode in-proc fleet.  Two
dispatch modes share the trace, the topology and the SLOs:

- **default (cache-blind)**: the stock queue-depth dispatcher, which
  nothing steers toward the replica that already holds a prefix.  The
  CacheEconomics board quantifies exactly what that costs —
  cross-replica duplicate-prefix bytes, per-dispatch wasted re-prefill
  tokens (the regret ledger), fleet prefix hit-rate — frozen as
  ``BENCH_r16_cacheblind.json``, the baseline the affinity router must
  beat.
- **--affinity**: prefix-affinity dispatch + the cluster KV fabric
  (omniaffinity).  Same trace, same fleet; the router scores
  placements against live radix digests and pulls published prefixes
  through the connector store.  Writes ``BENCH_r19_affinity.json``;
  ``scripts/cache_econ.sh`` gates it against the committed baseline
  (hit-rate and goodput must improve, p99 TTFT must not regress).

Both modes write one schema-valid serving_curve point, the fleet cache
board, and a mid-flight /metrics probe (validate_exposition clean,
every cache-economics series live).  Asserts the digest stays provably
cheap: every replica's exported node count is bounded by the cap.

Full runs repeat the trace ``--trials`` times (default 5; smoke 1) on
a fresh fleet each time and commit the MEDIAN-by-goodput trial —
single-shot wall-clock numbers on a contended host are noise, and the
gate in ``scripts/cache_econ.sh`` compares medians, not lottery
tickets.  Every trial's headline numbers land in the artifact under
``trials`` so the spread is auditable.

    JAX_PLATFORMS=cpu python scripts/cache_bench.py
    JAX_PLATFORMS=cpu python scripts/cache_bench.py --affinity
    JAX_PLATFORMS=cpu python scripts/cache_bench.py --smoke
"""

import argparse
import json
import sys
import threading
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from vllm_omni_tpu.disagg.router import (  # noqa: E402
    DIGEST_MAX_NODES,
)
from vllm_omni_tpu.disagg.service import (  # noqa: E402
    DisaggService,
    build_inproc_router,
)
from vllm_omni_tpu.engine import EngineConfig  # noqa: E402
from vllm_omni_tpu.loadgen import (  # noqa: E402
    SLOTargets,
    build_workload,
    poisson_arrivals,
    run_inproc,
    shared_prefix_catalog,
    summarize,
    validate_curve_point,
)
from vllm_omni_tpu.metrics.prometheus import (  # noqa: E402
    validate_exposition,
)
from vllm_omni_tpu.models.common import transformer as tfm  # noqa: E402

# the series the mid-flight scrape must see live — names, not values:
# a rename that breaks dashboards fails the bench before it ships
CACHE_SERIES = (
    "fleet_prefix_hit_tokens_total",
    "fleet_prefill_tokens_total",
    "fleet_prefix_hit_rate",
    "fleet_duplicate_prefill_tokens_total",
    "fleet_duplicate_prefix_tokens",
    "cache_digest_nodes",
)
#: additionally required live in --affinity mode
AFFINITY_SERIES = (
    "router_affinity_dispatch_total",
)


def build_trace(n_requests: int, rate: float, seed: int,
                n_tenants: int, prefix_len: int):
    catalog = shared_prefix_catalog(n_tenants=n_tenants,
                                    prefix_len=prefix_len)
    arrivals = poisson_arrivals(rate, n_requests, seed=seed)
    return build_workload(arrivals, catalog=catalog, seed=seed,
                          vocab_size=60, id_prefix="cachebench")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-speed run: fewer requests, no "
                         "redundancy-floor assert")
    ap.add_argument("--affinity", action="store_true",
                    help="prefix-affinity dispatch + cluster KV "
                         "fabric (the omniaffinity router) instead of "
                         "the cache-blind queue-depth baseline")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--trials", type=int, default=None,
                    help="independent repeats of the trace (fresh "
                         "fleet each); the median-by-goodput trial is "
                         "committed (default: 5, smoke: 1)")
    ap.add_argument("--rate", type=float, default=6.0)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--prefix-len", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    mode = "affinity" if args.affinity else "cacheblind"
    out = args.out or (
        "BENCH_r19_affinity.json" if args.affinity
        else "BENCH_r16_cacheblind.json")
    n = args.requests or (12 if args.smoke else 64)
    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    trace = build_trace(n, args.rate, args.seed, args.tenants,
                        args.prefix_len)
    slo = SLOTargets(ttft_ms=600.0, e2e_ms=10000.0)
    base = EngineConfig(
        num_pages=96, page_size=4, max_model_len=160, max_num_seqs=2,
        max_num_batched_tokens=256, dtype=jnp.float32,
        slo_ttft_ms=slo.ttft_ms, slo_tpot_ms=None,
        max_queue_depth=24,
        # precompile before the trace: a shape-cache miss mid-traffic
        # is a multi-second stall that would swamp the cache signal
        warmup=[(1, 8), (1, 16), (1, 64), (2, 8), (2, 16), (2, 64)])
    series = CACHE_SERIES + (AFFINITY_SERIES if args.affinity else ())
    n_trials = args.trials or (1 if args.smoke else 5)

    def run_trial():
        router = build_inproc_router(params, cfg, base, 2, 2,
                                     affinity_routing=args.affinity)
        service = DisaggService(router)
        probe = {}

        def _probe():
            time.sleep(max(trace[-1].at_s * 0.6, 0.5))
            text = service.render_metrics()
            probe["errors"] = validate_exposition(text)
            probe["cache_series_live"] = {
                s: (s in text) for s in series}

        prober = threading.Thread(target=_probe, daemon=True)
        prober.start()
        t0 = time.monotonic()
        records = run_inproc(service, trace, timeout_s=600.0)
        wall = time.monotonic() - t0
        prober.join(timeout=30)

        offered = len(trace) / max(trace[-1].at_s, 1e-9)
        point = summarize(records, offered_rps=offered, slo=slo)
        errs = validate_curve_point(point)
        assert not errs, f"curve point schema violations: {errs}"
        point["topology"] = f"2Px2D-{mode}"
        point["wall_s"] = round(wall, 2)

        board = router.cache.board()
        expo = router.cache.exposition()
        service.shutdown()

        # the digest must be provably cheap: bounded node count on
        # every replica, no matter how much traffic the trace pushed
        for rid, nodes in expo["digest_nodes"].items():
            assert nodes <= DIGEST_MAX_NODES, (
                f"replica {rid} exported {nodes} digest nodes "
                f"(cap {DIGEST_MAX_NODES})")
        assert probe.get("errors") == [], \
            f"mid-flight /metrics probe not clean: {probe.get('errors')}"
        missing = [s for s, live in probe["cache_series_live"].items()
                   if not live]
        assert not missing, \
            f"cache-economics series missing mid-flight: {missing}"
        if not args.smoke and not args.affinity:
            # the baseline must actually exhibit the waste the
            # affinity router exists to reclaim — a zero here means
            # the workload no longer exercises redundancy
            assert expo["duplicate_prefix_tokens"] > 0, \
                "cache-blind 2x2 run produced no duplicate prefix pages"
        if not args.smoke and args.affinity:
            # the affinity router must actually route on affinity:
            # warm placements must land (the trace re-serves every
            # tenant's shared prefix many times over)
            outcomes = board["affinity"]["outcomes"]
            assert outcomes.get("hit", 0) > 0, \
                f"affinity run never placed a warm hit: {outcomes}"
        return point, board, probe

    trials = []
    for i in range(n_trials):
        point, board, probe = run_trial()
        trials.append((point, board, probe))
        print(f"trial {i + 1}/{n_trials}: goodput="
              f"{point['goodput_req_per_s']} "
              f"ttft_p99={point['ttft_ms']['p99']} "
              f"hit_rate={board['fleet']['hit_rate']}")

    # commit the median-by-goodput trial: one internally-consistent
    # point (not field-wise medians, which no single run produced)
    ranked = sorted(trials, key=lambda t: t[0]["goodput_req_per_s"])
    point, board, probe = ranked[len(ranked) // 2]

    doc = {
        "bench": f"BENCH_{'r19_affinity' if args.affinity else 'r16_cacheblind'}",
        "trace": {"requests": n, "rate_rps": args.rate,
                  "tenants": args.tenants,
                  "shared_prefix_len": args.prefix_len,
                  "seed": args.seed},
        "slo": slo.as_dict(),
        "topology": {"prefill": 2, "decode": 2,
                     "dispatch": ("prefix-affinity + KV fabric"
                                  if args.affinity
                                  else "queue-depth (cache-blind)")},
        "digest_node_cap": DIGEST_MAX_NODES,
        "trials": [{
            "goodput_req_per_s": p["goodput_req_per_s"],
            "slo_attainment": p["slo_attainment"],
            "ttft_p99_ms": p["ttft_ms"]["p99"],
            "hit_rate": b["fleet"]["hit_rate"],
        } for p, b, _ in trials],
        "serving_curve": [point],
        "cache_board": board,
        "metrics_probe": probe,
    }
    with open(out, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    fleet = board["fleet"]
    print(f"[2Px2D {mode}] goodput="
          f"{point['goodput_req_per_s']} req/s "
          f"attainment={point['slo_attainment']} "
          f"hit_rate={fleet['hit_rate']} "
          f"dup_tokens={fleet['duplicate_prefix_tokens']} "
          f"dup_bytes={fleet['duplicate_prefix_bytes']}")
    if args.affinity:
        print(f"affinity outcomes={board['affinity']['outcomes']} "
              f"fabric={board['fabric']}")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
