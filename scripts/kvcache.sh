#!/usr/bin/env sh
# kvcache gate: the radix prefix index vs the flat chained-hash oracle
# (randomized request streams — same hits, same refcounts, no page
# leaks), the pin/evict-under-pressure regression, and the tiered
# offload contract end to end — park-on-preempt + restore with greedy
# streams bit-identical to a never-offloaded oracle, eviction offload
# to the host tier, remote-tier demotion/promotion, int8 cold-path
# round trips, restore-failure degradation to recompute, and the
# /metrics series (kv_prefix_hit_tokens_total, kv_tier_*_pages,
# kv_offload_bytes_total{tier,dir}, kv_restore_seconds).
#
# Standalone face of the same coverage tier-1 carries (tests/core and
# tests/engine are fast directories), sitting next to scripts/ragged.sh,
# scripts/asyncstep.sh, scripts/omnilint.sh and scripts/faultmatrix.sh
# as a pre-merge gate:
#
#   scripts/kvcache.sh               # radix index + tiered offload
#   scripts/kvcache.sh -k remote     # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the bit-equality oracles run on the fake-device path; the
# gate must never touch a real chip a colocated serving process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/core/test_radix_prefix.py \
    tests/engine/test_kv_offload.py \
    -q -p no:cacheprovider -m "not slow" "$@"
