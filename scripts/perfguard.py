#!/usr/bin/env python
"""perfguard: the perf-regression gate over BENCH_*.json artifacts.

BENCH files accumulated for 12 PRs with no tool that reads two of them
— regressions were caught by vibes.  This script loads any two bench
artifacts, extracts every comparable performance surface through a
schema-versioned extractor, prints a delta table, and exits nonzero
past a configurable regression threshold:

    python scripts/perfguard.py BENCH_r12.json BENCH_new.json
    python scripts/perfguard.py old.json new.json --threshold 0.15

Known artifact shapes (the extractor walks recursively, so nesting
under ``parsed`` / ``secondary_metrics`` / variant blocks is handled):

- ``serving_curve`` lists (loadgen ``summarize`` points: r11/r12 and
  ``OMNI_BENCH_SERVING=1`` runs) — keyed by (path, offered_rps[, the
  point's ``topology``]); goodput / attainment / p99 latencies gate.
- scalar records (diffusion flagship and variants): ``mfu``,
  ``seconds_per_image``.

Exit codes: 0 = no regression beyond threshold; 1 = regression;
2 = schema mismatch (no comparable surface between the two files).

``--emit-guard-curve OUT.json`` writes a seed-deterministic in-proc
serving curve (the loadgen virtual-time simulator — bit-identical
across machines, zero wall-clock) so CI can own the trajectory:
``scripts/perfguard.sh`` regenerates it and compares against the
committed ``BENCH_guard_baseline.json``; any change to the admission /
goodput / summarize math shows up as a nonzero delta there, gated at a
tight threshold, while honest cross-run comparisons of real bench
artifacts use the default (looser) threshold.

Stdlib-only for the compare paths — safe in any CI lane; only the
guard-curve emitter imports ``vllm_omni_tpu.loadgen`` (numpy-free,
jax-free).
"""

from __future__ import annotations

import argparse
import json
import sys

#: required keys for a list entry to count as a serving-curve point
#: (mirrors loadgen.runner.CURVE_POINT_KEYS minus derived sub-dicts —
#: duplicated here so the compare path stays stdlib-only)
_POINT_KEYS = ("offered_rps", "goodput_tok_per_s", "slo_attainment")

#: gated metrics: name -> (+1 higher-is-better | -1 lower-is-better)
GATED_CURVE_METRICS = {
    "goodput_tok_per_s": +1,
    "attained_tok_per_s": +1,
    "slo_attainment": +1,
    "ttft_p99_ms": -1,
    "tpot_p99_ms": -1,
    "e2e_p99_ms": -1,
    "mfu": +1,
}
GATED_SCALAR_METRICS = {
    "mfu": +1,
    "seconds_per_image": -1,
}

SCHEMA = "perfguard/1"


# ------------------------------------------------------------ extraction
def _looks_like_curve(val) -> bool:
    return (isinstance(val, list) and val
            and all(isinstance(p, dict) for p in val)
            and all(all(k in p for k in _POINT_KEYS) for p in val))


def _point_metrics(p: dict) -> dict:
    out = {}
    for k in ("goodput_tok_per_s", "attained_tok_per_s",
              "slo_attainment"):
        if isinstance(p.get(k), (int, float)):
            out[k] = float(p[k])
    for lat in ("ttft_ms", "tpot_ms", "e2e_ms"):
        sub = p.get(lat)
        if isinstance(sub, dict) and isinstance(sub.get("p99"),
                                                (int, float)):
            out[f"{lat[:-3]}_p99_ms"] = float(sub["p99"])
    if isinstance(p.get("mfu"), (int, float)):
        out["mfu"] = float(p["mfu"])
    return out


def extract(doc) -> dict:
    """Walk one bench artifact; returns
    {"schema", "points": {key: {metric: value}},
     "scalars": {key: {metric: value}}} — empty maps when the file has
    no recognizable performance surface."""
    points: dict[str, dict] = {}
    scalars: dict[str, dict] = {}

    def walk(node, path: str) -> None:
        if isinstance(node, dict):
            curve = node.get("serving_curve")
            if _looks_like_curve(curve):
                for p in curve:
                    key = f"{path}serving_curve@rps={p['offered_rps']}"
                    if p.get("topology"):
                        key += f",topo={p['topology']}"
                    points[key] = _point_metrics(p)
            sc = {}
            for k in GATED_SCALAR_METRICS:
                if isinstance(node.get(k), (int, float)):
                    sc[k] = float(node[k])
            if sc and "serving_curve" not in node:
                scalars[path.rstrip("/") or "."] = sc
            for k, v in node.items():
                if k == "serving_curve":
                    continue
                walk(v, f"{path}{k}/")
        elif isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}{i}/")

    walk(doc, "")
    return {"schema": SCHEMA, "points": points, "scalars": scalars}


# ------------------------------------------------------------ comparison
def _rel_delta(base: float, new: float, direction: int) -> float:
    """Signed relative change where NEGATIVE = regression, regardless
    of metric direction.  Ratio-like metrics near zero (attainment,
    mfu) still behave: the denominator floors at a small epsilon."""
    denom = max(abs(base), 1e-9)
    change = (new - base) / denom
    return change * direction


def compare(base: dict, new: dict, threshold: float
            ) -> tuple[list, list, list]:
    """Returns (rows, regressions, missing).  Each row:
    (surface, metric, base, new, signed_delta_frac, gated).

    ``missing`` lists every baseline surface/metric ABSENT from the
    new artifact — a bench that stopped emitting a point (crashed leg,
    dropped field) must be disclosed, never silently un-gated; under
    ``--strict`` (the deterministic CI leg) it fails the gate."""
    rows, regressions, missing = [], [], []
    for section, gated in (("points", GATED_CURVE_METRICS),
                           ("scalars", GATED_SCALAR_METRICS)):
        for key in sorted(set(base[section]) - set(new[section])):
            missing.append(f"{section}: {key} (whole surface)")
        for key in sorted(set(base[section]) & set(new[section])):
            b, n = base[section][key], new[section][key]
            for metric in sorted(set(b) - set(n)):
                if metric in gated:
                    missing.append(f"{section}: {key} {metric}")
            for metric in sorted(set(b) & set(n)):
                direction = gated.get(metric)
                if direction is None:
                    continue
                d = _rel_delta(b[metric], n[metric], direction)
                regressed = d < -threshold
                rows.append((key, metric, b[metric], n[metric], d,
                             regressed))
                if regressed:
                    regressions.append((key, metric, b[metric],
                                        n[metric], d))
    return rows, regressions, missing


def render_table(rows: list, threshold: float) -> str:
    lines = [f"{'surface':56s} {'metric':20s} {'base':>12s} "
             f"{'new':>12s} {'delta':>8s}"]
    for key, metric, b, n, d, regressed in rows:
        flag = " REGRESSED" if regressed else ""
        lines.append(f"{key[:56]:56s} {metric:20s} {b:12.4f} "
                     f"{n:12.4f} {d * 100:+7.1f}%{flag}")
    lines.append(f"(negative delta = worse; gate at "
                 f"-{threshold * 100:.0f}%)")
    return "\n".join(lines)


# ----------------------------------------------- deterministic guard curve
def emit_guard_curve(out_path: str) -> None:
    """Write the seed-deterministic in-proc serving curve: the loadgen
    virtual-time simulator over a seeded Poisson workload — bit-exact
    across machines, so CI compares it against the committed baseline
    at a tight threshold.  Constants are part of the contract: change
    them and the baseline must be regenerated IN THE SAME COMMIT."""
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from vllm_omni_tpu.loadgen import (
        SLOTargets,
        build_workload,
        default_catalog,
        poisson_arrivals,
        simulate,
        summarize,
    )

    slo = SLOTargets(ttft_ms=2000.0, tpot_ms=500.0)
    curve = []
    for i, rate in enumerate((2.0, 8.0, 32.0)):
        arrivals = poisson_arrivals(rate, num_requests=64,
                                    seed=1300 + i)
        wl = build_workload(arrivals, default_catalog(), seed=2300 + i,
                            vocab_size=2000,
                            tenants=["tenant_a", "tenant_b"],
                            id_prefix=f"guard{i}")
        records = simulate(wl, prefill_s=0.05, per_token_s=0.01,
                           servers=4, queue_limit=32)
        curve.append(summarize(records, rate, slo))
    doc = {"bench": "perfguard_deterministic_curve",
           "note": "virtual-time simulator; bit-deterministic — any "
                   "delta vs the committed baseline is a code change "
                   "in the admission/goodput/summarize math",
           "serving_curve": curve}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"guard curve written to {out_path}")


# ------------------------------------------------------------------ main
def run(base_path: str, new_path: str, threshold: float,
        strict: bool = False) -> int:
    try:
        with open(base_path) as f:
            base_doc = json.load(f)
        with open(new_path) as f:
            new_doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perfguard: cannot load artifacts: {e}", file=sys.stderr)
        return 2
    base = extract(base_doc)
    new = extract(new_doc)
    for name, ex in ((base_path, base), (new_path, new)):
        if not ex["points"] and not ex["scalars"]:
            print(f"perfguard: {name}: no comparable performance "
                  "surface (schema mismatch?)", file=sys.stderr)
            return 2
    rows, regressions, missing = compare(base, new, threshold)
    if not rows:
        print("perfguard: the two artifacts share no comparable "
              "surface (different benches?)", file=sys.stderr)
        return 2
    print(render_table(rows, threshold))
    if missing:
        # disclosed always; gated only under --strict (honest cross-PR
        # comparisons legitimately add/retire rate points — the
        # deterministic CI leg must not)
        print(f"\nperfguard: {len(missing)} baseline surface(s) "
              "absent from the new artifact (NOT gated"
              + (" -> strict: REGRESSION" if strict else "") + "):",
              file=sys.stderr)
        for m in missing:
            print(f"  missing {m}", file=sys.stderr)
        if strict:
            return 1
    if regressions:
        print(f"\nperfguard: {len(regressions)} regression(s) beyond "
              f"{threshold * 100:.0f}%:", file=sys.stderr)
        for key, metric, b, n, d in regressions:
            print(f"  {key} {metric}: {b:.4f} -> {n:.4f} "
                  f"({d * 100:+.1f}%)", file=sys.stderr)
        return 1
    print("\nperfguard: no regression beyond threshold")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", nargs="?", help="baseline BENCH_*.json")
    ap.add_argument("new", nargs="?", help="candidate BENCH_*.json")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="relative regression gate (default 0.2 = 20%% "
                         "— bench noise across machines is real; the "
                         "deterministic guard curve uses a tight one)")
    ap.add_argument("--emit-guard-curve", metavar="OUT",
                    help="write the seed-deterministic simulator curve "
                         "and exit")
    ap.add_argument("--strict", action="store_true",
                    help="treat baseline surfaces/metrics missing from "
                         "the new artifact as regressions (the "
                         "deterministic CI leg)")
    args = ap.parse_args(argv)
    if args.emit_guard_curve:
        emit_guard_curve(args.emit_guard_curve)
        return 0
    if not args.base or not args.new:
        ap.error("need BASE and NEW artifacts (or --emit-guard-curve)")
    return run(args.base, args.new, args.threshold, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
