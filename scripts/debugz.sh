#!/usr/bin/env sh
# debugz gate: the introspection subsystem end to end — flight-recorder
# ring determinism + dump-document schema, the stall watchdog's fake-
# clock state machine (compile-stall exemption vs true hang) AND the
# deterministic e2e: boot an in-proc engine, inject a stall through the
# OMNI_TPU_FAULTS "step" site, assert the watchdog trips and its dump
# names the stuck request id, carries all-thread stacks, and the last-N
# step-record tail; the /debug/z + enriched /health endpoint scrapes
# over real HTTP; and device-memory-ledger conservation (components sum
# to total, peaks monotone) on the CPU fallback, with the new
# device_memory_* / trace_spans_dropped_total series validating on
# /metrics.
#
# Standalone face of the same coverage tier-1 carries (tests/
# introspection is a fast directory), sitting next to scripts/
# kvcache.sh, scripts/ragged.sh, scripts/asyncstep.sh, scripts/
# loadgen.sh and scripts/omnilint.sh as a pre-merge gate:
#
#   scripts/debugz.sh              # the whole introspection contract
#   scripts/debugz.sh -k watchdog  # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the engine under the injected stall is a tiny
# random-weight model; the gate must never touch a real chip a
# colocated serving process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/introspection/ \
    -q -p no:cacheprovider -m "not slow" "$@"
