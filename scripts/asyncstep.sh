#!/usr/bin/env sh
# Async pipelined-step gate: the sync-vs-async greedy token-equality
# oracle, stop/EOS one-step-lag rollback, preemption/deadline/abort with
# a step in flight, pipelined spec/logprobs/collect_hidden/embeds
# batches (the retired fallback reasons asserted absent), the retired
# multi-step knob's no-op contract, and the CPU-backend overlap
# microbench (overlap ratio > 0).
#
# Standalone face of the same coverage tier-1 carries — tests/engine is
# a fast directory, so tests/engine/test_async_step.py rides
# `pytest -m 'not slow'` exactly like the tests/resilience fast units —
# sitting next to scripts/omnilint.sh and scripts/faultmatrix.sh as a
# pre-merge gate:
#
#   scripts/asyncstep.sh                 # async pipeline suite
#   scripts/asyncstep.sh -k oracle       # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the oracle compares bit-identical greedy streams on the
# fake-device path; it must never touch a real chip a colocated serving
# process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/engine/test_async_step.py tests/engine/test_multi_step_decode.py \
    -q -p no:cacheprovider -m "not slow" "$@"
