#!/usr/bin/env python
"""The omniaffinity win gate: BENCH_r19_affinity.json must beat
BENCH_r16_cacheblind.json, per the pre-registered criteria —

- fleet prefix **hit-rate improves** (strictly),
- **goodput improves** (strictly),
- **p99 TTFT does not regress** (beyond a small latency-tail noise
  allowance),

plus the standard perfguard no-regression sweep over every gated
curve metric the two artifacts share.  The two benches label their
serving-curve points with different topologies (``2Px2D-cacheblind``
vs ``2Px2D-affinity``) — honest labels, but perfguard only compares
matching surfaces, so the comparison runs on aligned copies (the
affinity point re-labeled to the baseline topology).  Both artifacts
are 5-trial median-by-goodput runs from the same machine
(scripts/cache_bench.py); single-trial numbers are lottery tickets.

    python scripts/affinity_gate.py                      # committed pair
    python scripts/affinity_gate.py BASE.json NEW.json   # explicit pair
"""

import copy
import json
import sys

sys.path.insert(0, ".")

from scripts.perfguard import compare, extract, render_table  # noqa: E402

#: p99 TTFT is a tail percentile of a 64-request run: allow this much
#: relative noise before calling "no regress" violated
TTFT_TOLERANCE = 0.05
#: perfguard sweep threshold (same default as scripts/perfguard.py)
THRESHOLD = 0.2


def _headline(doc):
    point = doc["serving_curve"][0]
    return {
        "hit_rate": float(doc["cache_board"]["fleet"]["hit_rate"]),
        "goodput_req_per_s": float(point["goodput_req_per_s"]),
        "ttft_p99_ms": float(point["ttft_ms"]["p99"]),
    }


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    base_path = argv[0] if argv else "BENCH_r16_cacheblind.json"
    new_path = argv[1] if len(argv) > 1 else "BENCH_r19_affinity.json"
    with open(base_path) as f:
        base_doc = json.load(f)
    with open(new_path) as f:
        new_doc = json.load(f)

    # align the topology label so perfguard sees one shared surface
    aligned = copy.deepcopy(new_doc)
    for bp, np_ in zip(base_doc["serving_curve"],
                       aligned["serving_curve"]):
        np_["topology"] = bp["topology"]
    rows, regressions, missing = compare(
        extract(base_doc), extract(aligned), THRESHOLD)
    if not rows:
        print("affinity_gate: no comparable surface between "
              f"{base_path} and {new_path}", file=sys.stderr)
        return 2
    print(render_table(rows, THRESHOLD))
    failures = [f"perfguard: {key} {metric}: {b:.4f} -> {n:.4f} "
                f"({d * 100:+.1f}%)"
                for key, metric, b, n, d in regressions]
    failures += [f"perfguard: missing surface {m}" for m in missing]

    b, n = _headline(base_doc), _headline(new_doc)
    print(f"\nhit_rate:  {b['hit_rate']:.6f} -> {n['hit_rate']:.6f}")
    print(f"goodput:   {b['goodput_req_per_s']:.4f} -> "
          f"{n['goodput_req_per_s']:.4f} req/s")
    print(f"ttft_p99:  {b['ttft_p99_ms']:.1f} -> "
          f"{n['ttft_p99_ms']:.1f} ms")
    if not n["hit_rate"] > b["hit_rate"]:
        failures.append("hit-rate must strictly improve")
    if not n["goodput_req_per_s"] > b["goodput_req_per_s"]:
        failures.append("goodput must strictly improve")
    if n["ttft_p99_ms"] > b["ttft_p99_ms"] * (1 + TTFT_TOLERANCE):
        failures.append(
            f"p99 TTFT regressed beyond {TTFT_TOLERANCE:.0%}")

    if failures:
        print(f"\naffinity_gate: FAIL ({len(failures)}):",
              file=sys.stderr)
        for f_ in failures:
            print(f"  {f_}", file=sys.stderr)
        return 1
    print("\naffinity_gate: PASS — affinity beats the cache-blind "
          "baseline on hit-rate and goodput without a TTFT regression")
    return 0


if __name__ == "__main__":
    sys.exit(main())
