#!/usr/bin/env sh
# loadgen gate: the open-loop serving-curve harness end to end —
# seeded Poisson / trace-replay determinism (same seed, same arrival
# schedule), goodput-vs-throughput math against a hand-computed oracle,
# SLO attainment edge cases (exactly-at-target, zero completions,
# 1-token TPOT), the virtual-time smoke curve at 2 offered-load points
# asserting monotone non-increasing goodput ratio past saturation plus
# a schema-valid serving_curve artifact, the 429 shed path returning
# before engine admission, the x-omni-tenant split of the SLO/goodput/
# queue-depth series on /metrics, and a fast in-process AsyncOmni run
# producing a schema-valid serving_curve record.
#
# Standalone face of the same coverage tier-1 carries (tests/loadgen is
# a fast directory), sitting next to scripts/kvcache.sh,
# scripts/ragged.sh, scripts/asyncstep.sh and scripts/omnilint.sh as a
# pre-merge gate:
#
#   scripts/loadgen.sh               # the whole serving-curve contract
#   scripts/loadgen.sh -k shed       # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the smoke curve runs a tiny random-weight model on the
# fake-device path; the gate must never touch a real chip a colocated
# serving process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/loadgen/ \
    -q -p no:cacheprovider -m "not slow" "$@"
