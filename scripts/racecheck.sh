#!/usr/bin/env sh
# omnirace standalone gate: the concurrency-correctness subset.
#
#  1. self-lint with ONLY the OL7-OL9 families enforced (lock
#     discipline against the LOCK_GUARDS manifest, lock-order cycles,
#     blocking-under-lock) — no baseline: concurrency findings are
#     never allowed to accumulate as debt;
#  2. the runtime detector's unit suite plus the connector regression,
#     with OMNI_TPU_LOCK_CHECK=1 so every traced lock records into the
#     live order graph and the seeded-deadlock regression is exercised.
#
# The full tier-1 run covers both anyway (tests/analysis/test_selflint
# and the threaded suites' conftests); this wrapper is the fast
# pre-commit face for concurrency-touching changes.
set -eu
cd "$(dirname "$0")/.."

echo "== omnirace: static (OL7-OL9 self-lint) =="
python -m vllm_omni_tpu.analysis --no-baseline --rules OL7,OL8,OL9 \
    vllm_omni_tpu bench.py scripts

echo "== omnirace: runtime (lock-order detector) =="
exec env JAX_PLATFORMS=cpu OMNI_TPU_LOCK_CHECK=1 python -m pytest -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    tests/analysis/test_runtime_lockcheck.py \
    tests/analysis/test_rules_lock_discipline.py \
    tests/analysis/test_rules_lock_order.py \
    tests/analysis/test_rules_blocking.py \
    tests/distributed/test_connectors.py
