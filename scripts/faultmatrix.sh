#!/usr/bin/env sh
# Fault-injection matrix gate: runs the resilience test suite — retry/
# breaker units, fault-plan replay determinism, deadline propagation,
# supervisor state machine, and (unless FAULTMATRIX_FAST=1) the
# cross-process worker-kill e2e matrix on both transports.
#
# Standalone face of the same coverage tier-1 carries (the fast units
# ride `-m 'not slow'`; the kill e2e is slow-marked), sitting next to
# scripts/omnilint.sh as a pre-merge gate:
#
#   scripts/faultmatrix.sh                      # full matrix
#   FAULTMATRIX_FAST=1 scripts/faultmatrix.sh   # fast units only
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the matrix kills workers on purpose; it must never touch
# a real TPU chip a colocated serving process owns
if [ "${FAULTMATRIX_FAST:-0}" = "1" ]; then
    exec env JAX_PLATFORMS=cpu python -m pytest tests/resilience -q \
        -p no:cacheprovider -m "not slow" "$@"
fi
exec env JAX_PLATFORMS=cpu python -m pytest tests/resilience -q \
    -p no:cacheprovider "$@"
