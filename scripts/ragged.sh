#!/usr/bin/env sh
# Unified-ragged-batching gate: the ragged paged-attention kernel vs its
# XLA reference oracle (GQA, empty-seq, 1-token decode rows, multi-query
# spec-verify rows, page/q-block boundary lengths, interpret mode) plus
# the engine-level contract — greedy/spec/logprobs/hidden/embeds streams
# bit-identical to the pre-deletion split-path oracle fixtures, ONE
# device dispatch per mixed step, chunked-prefill resume, preemption
# mid-chunk/mid-verify, async pipelining with the retired fallback
# reasons asserted absent, padding-efficiency vs the old bucket grid —
# and the mixed spec+logprobs+embeds serving smoke (deterministic
# fallback/completion assertions on the PR 7 harness accounting).
#
# Standalone face of the same coverage tier-1 carries (tests/ops and
# tests/engine are fast directories), sitting next to
# scripts/asyncstep.sh, scripts/omnilint.sh and scripts/faultmatrix.sh
# as a pre-merge gate:
#
#   scripts/ragged.sh                # ragged kernel + unified engine
#   scripts/ragged.sh -k dispatch    # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the oracle compares bit-identical greedy streams on the
# fake-device path; it must never touch a real chip a colocated serving
# process owns
env JAX_PLATFORMS=cpu python -m pytest \
    tests/ops/test_ragged_paged_attention.py \
    tests/ops/test_autotune.py \
    tests/engine/test_unified_batch.py \
    tests/engine/test_oracle_fixtures.py \
    -q -p no:cacheprovider -m "not slow" "$@"
# mixed serving smoke: spec + logprobs + embeds + sampled tenants on
# one async engine — the retired fallback reasons must stay at zero
# and every offered request must complete
exec env JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/mixed_smoke.py \
    --rates 8 --requests 16 --check-fallback
