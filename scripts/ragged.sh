#!/usr/bin/env sh
# Unified-ragged-batching gate: the ragged paged-attention kernel vs its
# XLA reference oracle (GQA, empty-seq, 1-token decode rows, page/
# q-block boundary lengths, interpret mode) plus the engine-level
# contract — unified-vs-split greedy bit-equality on staggered mixed
# waves, ONE device dispatch per mixed step, chunked-prefill resume,
# preemption mid-chunk, async+unified pipelining, prefix-cache feeding,
# padding-efficiency improvement.
#
# Standalone face of the same coverage tier-1 carries (tests/ops and
# tests/engine are fast directories), sitting next to
# scripts/asyncstep.sh, scripts/omnilint.sh and scripts/faultmatrix.sh
# as a pre-merge gate:
#
#   scripts/ragged.sh                # ragged kernel + unified engine
#   scripts/ragged.sh -k dispatch    # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the oracle compares bit-identical greedy streams on the
# fake-device path; it must never touch a real chip a colocated serving
# process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/ops/test_ragged_paged_attention.py \
    tests/engine/test_unified_batch.py \
    -q -p no:cacheprovider -m "not slow" "$@"
