#!/usr/bin/env python
"""BENCH_r20: int8-resident paged KV vs the bf16 pool (omniquant-kv).

Two engine arms share ONE HBM page-pool budget (``--budget-bytes``):
the dense bf16 layout and the int8 layout (``kv_cache_dtype=int8``,
per-page per-head scales resident next to the pages).  The bench
measures what the quantized layout is FOR — session capacity:

- **concurrency ladder** (per arm, engine-direct): N identical
  long-decode sessions start together; the step loop timestamps every
  token of every session, so TPOT here is the real inter-token
  latency, preempt/recompute stalls included (an SSE client can't see
  those — this server end-loads its streams).
  ``max_sessions_at_tpot_slo`` is the largest N where every session
  completes and the p99-across-sessions of each session's WORST
  inter-token gap stays under the target.  The
  dense pool runs out of pages first — the scheduler's
  preempt/recompute thrash is exactly what blows the p99 — so the
  int8 arm must hold MORE concurrent sessions at the same SLO, at a
  decode tok/s the artifact also records alongside the rung's
  preemption count.
- **serving curve** (int8 arm, open-loop in-proc): the same offered
  rates the r11 unified-engine baseline committed (4/8/16 rps),
  written at the top level so ``scripts/perfguard.py`` finds the
  comparable surface:

      python scripts/perfguard.py BENCH_r11_unified.json \\
          BENCH_r20_kvquant.json

  The full run invokes that gate itself (``--no-gate`` to skip): the
  quantized engine must not regress goodput / attainment / p99s
  against the committed full-precision baseline.

Full runs repeat everything ``--trials`` times (default 3; smoke 1) on
fresh engines and commit the MEDIAN-by-goodput trial — wall-clock
numbers on a contended host are noise, and the gate compares medians,
not lottery tickets.  Every trial's headline numbers land under
``trials`` so the spread is auditable.

    JAX_PLATFORMS=cpu python scripts/kv_quant_bench.py --smoke
    JAX_PLATFORMS=cpu python scripts/kv_quant_bench.py
"""

import argparse
import json
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from vllm_omni_tpu.loadgen import (  # noqa: E402
    RequestRecord,
    SLOTargets,
    build_workload,
    poisson_arrivals,
    run_inproc,
    summarize,
    validate_curve_point,
)
from vllm_omni_tpu.loadgen.workload import Scenario  # noqa: E402

#: per-session worst-case inter-token latency the ladder holds
#: sessions to — an order of magnitude over a clean tiny-model decode
#: step (2-6 ms), violated by a session parking behind a
#: preempt/recompute cycle (it waits for a peer to finish and free
#: pages, tens of steps of stall); an aggregate-p99 over all gaps
#: would average a single victim's stall away, so the rung gate takes
#: the p99 over SESSIONS of each session's worst gap
TPOT_SLO_MS = 30.0
LADDER_SLO = SLOTargets(ttft_ms=60_000.0, tpot_ms=TPOT_SLO_MS)
#: one ladder session: 16-token prompt + fixed-length decode
SESSION_PROMPT = 16
#: the r11 baseline's SLO targets — the gated curve reuses them
CURVE_SLO = SLOTargets(ttft_ms=2000.0, tpot_ms=500.0)

CHAT_CATALOG = [Scenario("chat", weight=1.0, prompt_len=(4, 12),
                         output_len=(8, 12), stream=True)]


def _engine(arm, budget, page_size, max_model_len):
    import jax
    import jax.numpy as jnp

    from vllm_omni_tpu.engine import EngineConfig, LLMEngine
    from vllm_omni_tpu.models.common import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    return LLMEngine(params, cfg, EngineConfig(
        num_pages=64, page_size=page_size, max_model_len=max_model_len,
        max_num_seqs=16, max_queue_depth=64, dtype=jnp.bfloat16,
        kv_cache_dtype=arm, kv_hbm_budget_bytes=budget,
        # capacity bench: random prompts never hit, and cached free
        # pages would blur the rung-to-rung pool accounting
        enable_prefix_caching=False,
        # precompile every bucket the ladder walks: a mid-rung XLA
        # compile would bill its stall to that rung's gaps (OL11)
        warmup=True))


def _ladder_rung(eng, label, n, decode_len, rng):
    """One burst of N sessions on a drained engine; every token of
    every session is timestamped from the step loop."""
    from vllm_omni_tpu.sampling_params import SamplingParams

    sp = SamplingParams(temperature=0.0, max_tokens=decode_len)
    preempt0 = eng.scheduler.num_preemptions
    for i in range(n):
        prompt = [int(t) for t in rng.integers(
            1, 60, size=SESSION_PROMPT)]
        eng.add_request(prompt, sp, request_id=f"{label}-c{n}-s{i}")
    handles = {r.request_id: r for r in eng.scheduler.waiting}
    assert len(handles) == n
    times = {rid: [] for rid in handles}
    t0 = time.monotonic()
    while eng.has_unfinished_requests:
        eng.step()
        now = time.monotonic() - t0
        for rid, req in handles.items():
            while len(times[rid]) < len(req.output_token_ids):
                times[rid].append(now)
    records, gaps, worst = [], [], []
    for rid, ts in times.items():
        session_gaps = [b - a for a, b in zip(ts, ts[1:])]
        gaps.extend(session_gaps)
        if session_gaps:
            worst.append(max(session_gaps))
        records.append(RequestRecord(
            request_id=rid, tenant="bench", scenario="session",
            arrival_s=0.0, fired_s=0.0,
            first_s=ts[0] if ts else None,
            end_s=ts[-1] if ts else None,
            tokens_out=len(ts),
            status="ok" if len(ts) == decode_len else "error"))
    wall = time.monotonic() - t0
    point = summarize(records, offered_rps=n / max(wall, 1e-9),
                      slo=LADDER_SLO)
    errs = validate_curve_point(point)
    assert not errs, f"ladder point schema violations: {errs}"
    def _p99(vals):
        s = sorted(vals)
        return s[max(int(np.ceil(0.99 * len(s))) - 1, 0)] if s else 0.0

    point["concurrency"] = n
    point["wall_s"] = round(wall, 3)
    # the REAL per-token latency tail (step-loop timestamps): the rung
    # is held to worst_p99 — the p99 over sessions of each session's
    # WORST gap — because summarize()'s tpot is a per-request mean and
    # even a p99 over all gaps averages one victim's stall away
    point["itl_ms"] = {
        "p99": round(_p99(g * 1000.0 for g in gaps), 3),
        "worst_p99": round(_p99(w * 1000.0 for w in worst), 3),
        "max": round(max(gaps) * 1000.0, 3) if gaps else 0.0,
    }
    point["preemptions"] = eng.scheduler.num_preemptions - preempt0
    return point


def run_ladder(arm, label, budget, rungs, decode_len, page_size,
               max_model_len):
    eng = _engine(arm, budget, page_size, max_model_len)
    pages = eng.scheduler.kv.num_pages
    bpt = eng.metrics_snapshot()["kv"]["bytes_per_token"]
    print(f"ladder: {label} arm ({pages} pages in {budget} B)")
    points = []
    for n in rungs:
        rng = np.random.default_rng(1000 + n)
        point = _ladder_rung(eng, label, n, decode_len, rng)
        points.append(point)
        print(f"  [{label}] N={n}: completed={point['completed']}/{n} "
              f"itl_worst_p99={point['itl_ms']['worst_p99']}ms "
              f"preempts={point['preemptions']} "
              f"tok/s={point['attained_tok_per_s']}")
    held = [p for p in points
            if p["completed"] == p["concurrency"]
            and p["itl_ms"]["worst_p99"] <= TPOT_SLO_MS]
    best = max(held, key=lambda p: p["concurrency"]) if held else None
    return {
        "kv_pages": pages,
        "bytes_per_token": bpt,
        "tpot_slo_ms": TPOT_SLO_MS,
        "max_sessions_at_tpot_slo": (best["concurrency"] if best
                                     else 0),
        "decode_tok_per_s_at_max": (best["attained_tok_per_s"]
                                    if best else 0.0),
        "ladder": points,
    }


def run_serving_curve(budget, rates, n_requests, page_size,
                      max_model_len):
    """int8-arm open-loop curve at the r11 baseline's offered rates —
    the perfguard-comparable surface.  Client-observed via AsyncOmni
    (this server end-loads its streams, so ttft here is conservative:
    it reads as the full generation time)."""
    from vllm_omni_tpu.config.stage import StageConfig
    from vllm_omni_tpu.entrypoints.async_omni import AsyncOmni

    omni = AsyncOmni(stage_configs=[StageConfig(
        stage_id=0, stage_type="llm",
        engine_args={
            "model_factory": "tests.helpers:tiny_lm_factory",
            "num_pages": 64, "page_size": page_size,
            "max_model_len": max_model_len, "max_num_seqs": 16,
            "max_queue_depth": 64,
            "kv_cache_dtype": "int8", "kv_hbm_budget_bytes": budget,
            "slo_ttft_ms": CURVE_SLO.ttft_ms,
            "slo_tpot_ms": CURVE_SLO.tpot_ms,
            "warmup": True,
        },
        engine_input_source=[-1], final_output=True,
        final_output_type="text",
        default_sampling_params={"temperature": 0.0},
    )])
    curve = []
    try:
        for i, rate in enumerate(rates):
            wl = build_workload(
                poisson_arrivals(rate, n_requests, seed=100 + i),
                catalog=CHAT_CATALOG, seed=200 + i, vocab_size=60,
                id_prefix=f"curve{i}")
            records = run_inproc(omni, wl, timeout_s=600.0)
            point = summarize(records, offered_rps=rate, slo=CURVE_SLO)
            errs = validate_curve_point(point)
            assert not errs, f"curve point schema violations: {errs}"
            curve.append(point)
            print(f"  [int8 curve] rps={rate}: goodput="
                  f"{point['goodput_tok_per_s']} tok/s "
                  f"attainment={point['slo_attainment']}")
    finally:
        omni.shutdown()
    return curve


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-speed run: short ladder, tiny curve, no "
                         "perfguard gate")
    ap.add_argument("--trials", type=int, default=None,
                    help="independent repeats (fresh engines each); "
                         "the median-by-goodput trial is committed "
                         "(default: 3, smoke: 1)")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per serving-curve rate point "
                         "(default: 24, smoke: 8)")
    ap.add_argument("--budget-bytes", type=int, default=64 * 1024,
                    help="shared HBM page-pool budget for BOTH arms")
    ap.add_argument("--decode-len", type=int, default=None,
                    help="ladder session decode length (default: 64, "
                         "smoke: 16)")
    ap.add_argument("--baseline", default="BENCH_r11_unified.json")
    ap.add_argument("--no-gate", action="store_true")
    ap.add_argument("--out", default="BENCH_r20_kvquant.json")
    args = ap.parse_args()

    page_size, max_model_len = 4, 96
    # full-run sessions are 16 + 64 = 80 tokens = 20 pages: the bf16
    # pool (64 pages at the default budget) thrashes from N=4, the
    # int8 pool (120 pages) holds through N=6
    decode_len = args.decode_len or (16 if args.smoke else 64)
    rungs = [2, 4] if args.smoke else [2, 4, 6, 8, 10]
    rates = (4.0,) if args.smoke else (4.0, 8.0, 16.0)
    n_req = args.requests or (8 if args.smoke else 24)
    n_trials = args.trials or (1 if args.smoke else 3)

    def run_trial():
        arms = {}
        for dtype, label in (("auto", "bf16"), ("int8", "int8")):
            arms[label] = run_ladder(dtype, label, args.budget_bytes,
                                     rungs, decode_len, page_size,
                                     max_model_len)
        curve = run_serving_curve(args.budget_bytes, rates, n_req,
                                  page_size, max_model_len)
        return arms, curve

    trials = []
    for i in range(n_trials):
        arms, curve = run_trial()
        goodput = sum(p["goodput_tok_per_s"] for p in curve)
        trials.append((arms, curve, goodput))
        print(f"trial {i + 1}/{n_trials}: curve_goodput={goodput:.1f} "
              f"sessions int8={arms['int8']['max_sessions_at_tpot_slo']}"
              f" bf16={arms['bf16']['max_sessions_at_tpot_slo']}")

    # commit the median-by-goodput trial: one internally-consistent
    # artifact (not field-wise medians no single run produced)
    ranked = sorted(trials, key=lambda t: t[2])
    arms, curve, _ = ranked[len(ranked) // 2]

    ratio = arms["int8"]["kv_pages"] / max(arms["bf16"]["kv_pages"], 1)
    assert ratio >= 1.8, (
        f"int8 pool only {ratio:.2f}x the bf16 pages in the same "
        "budget (contract: >= 1.8x)")
    if not args.smoke:
        # the headline: the quantized pool holds MORE concurrent
        # sessions at the same p99 TPOT target
        assert (arms["int8"]["max_sessions_at_tpot_slo"]
                > arms["bf16"]["max_sessions_at_tpot_slo"]), (
            "int8 arm did not hold more sessions at the TPOT SLO: "
            f"{arms['int8']['max_sessions_at_tpot_slo']} vs "
            f"{arms['bf16']['max_sessions_at_tpot_slo']}")

    doc = {
        "bench": "BENCH_r20_kvquant",
        "smoke": args.smoke,
        "hbm_budget_bytes": args.budget_bytes,
        "session": {"prompt_len": SESSION_PROMPT,
                    "decode_len": decode_len,
                    "page_size": page_size},
        "tpot_slo_ms": TPOT_SLO_MS,
        "capacity_ratio_int8_over_bf16": round(ratio, 3),
        "arms": arms,
        "trials": [{
            "curve_goodput_tok_per_s": round(g, 2),
            "int8_max_sessions": a["int8"]["max_sessions_at_tpot_slo"],
            "bf16_max_sessions": a["bf16"]["max_sessions_at_tpot_slo"],
        } for a, _, g in trials],
        # top level: the perfguard-comparable surface (same offered
        # rates the r11 unified baseline committed)
        "serving_curve": curve,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    print(f"[kvquant] pages int8={arms['int8']['kv_pages']} "
          f"bf16={arms['bf16']['kv_pages']} (x{ratio:.2f}) "
          f"sessions@{TPOT_SLO_MS:.0f}ms "
          f"int8={arms['int8']['max_sessions_at_tpot_slo']} "
          f"bf16={arms['bf16']['max_sessions_at_tpot_slo']}")
    print(f"wrote {args.out}")

    if args.smoke or args.no_gate:
        return 0
    print(f"gating {args.out} vs {args.baseline}")
    return subprocess.call([sys.executable, "scripts/perfguard.py",
                            args.baseline, args.out])


if __name__ == "__main__":
    sys.exit(main())
