#!/usr/bin/env sh
# omnipulse gate: the detection-and-attribution layer end to end —
# the windowed burn-rate math against its hand oracle, the fake-clock
# alert lifecycle matrix (pending / for-duration / firing / resolve /
# flap / probe-error immunity), the space-saving attribution sketch's
# proven error bounds under 10k-tenant adversarial churn, the
# per-reason dump cooldown, AND the live e2e: an overload wave on a
# tiny in-proc engine drives the fast-burn alert pending -> firing,
# drops exactly one schema-valid evidence bundle on disk, resolves
# after the wave, and a mid-flight /metrics probe validates clean with
# the alerts_firing / alert_transitions_total / per-tenant attribution
# series live.
#
# Standalone face of the same coverage tier-1 carries (tests/alerts is
# a fast directory, unlike the slow-tiered tests/metrics), sitting next
# to scripts/debugz.sh, scripts/loadgen.sh, scripts/controlplane.sh and
# scripts/omnilint.sh as a pre-merge gate:
#
#   scripts/alerts.sh               # the whole omnipulse contract
#   scripts/alerts.sh -k burn       # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the e2e engine is a tiny random-weight model; the gate
# must never touch a real chip a colocated serving process owns
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/alerts/ \
    tests/introspection/test_flight_recorder.py \
    -q -p no:cacheprovider -m "not slow" "$@"
