#!/usr/bin/env sh
# omnilint CI gate: exits non-zero on any NEW finding (beyond the
# committed analysis/baseline.json and inline suppressions) across ALL
# rule families OL1-OL9 — including the omnirace concurrency rules
# (OL7 lock-discipline, OL8 lock-order, OL9 blocking-under-lock;
# scripts/racecheck.sh runs just those plus the runtime detector).
#
# The tier-1 pytest run exercises the same check through
# tests/analysis/test_selflint.py; this wrapper is the standalone /
# pre-commit face.  Deliberate contract changes regenerate the baseline:
#
#   python -m vllm_omni_tpu.analysis --update-baseline \
#       vllm_omni_tpu bench.py scripts
#
# then commit the baseline.json diff for review like any code change.
set -eu
cd "$(dirname "$0")/.."
exec python -m vllm_omni_tpu.analysis "$@" vllm_omni_tpu bench.py scripts
