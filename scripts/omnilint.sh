#!/usr/bin/env sh
# omnilint CI gate: exits non-zero on any NEW finding (beyond the
# committed analysis/baseline.json and inline suppressions).
#
# The tier-1 pytest run exercises the same check through
# tests/analysis/test_selflint.py; this wrapper is the standalone /
# pre-commit face.  Deliberate contract changes regenerate the baseline:
#
#   python -m vllm_omni_tpu.analysis --update-baseline \
#       vllm_omni_tpu bench.py scripts
#
# then commit the baseline.json diff for review like any code change.
set -eu
cd "$(dirname "$0")/.."
exec python -m vllm_omni_tpu.analysis "$@" vllm_omni_tpu bench.py scripts
