#!/usr/bin/env sh
# omnilint CI gate: exits non-zero on any NEW finding (beyond the
# committed analysis/baseline.json and inline suppressions) across ALL
# rule families OL1-OL13 — the omnirace concurrency rules (OL7-OL9;
# scripts/racecheck.sh runs just those plus the runtime detector), the
# omniflow package-wide rules (OL10 hostile-input taint, OL11
# recompile-hazard), and the omnileak path-sensitive rules (OL12
# resource-lifecycle, OL13 typestate) included — AND on any stale suppression: a
# `# omnilint: disable=OLx` comment that no longer suppresses anything
# (or a baseline entry nothing produces) is dead armor that would
# silently bless the next regression, so the audit is a hard gate.
#
# OMNI_LINT_SARIF=path additionally writes a SARIF 2.1.0 document of
# the new findings for CI annotation (GitHub code scanning, reviewdog).
#
# The tier-1 pytest run exercises the same checks through
# tests/analysis/test_selflint.py; this wrapper is the standalone /
# pre-commit face.  Deliberate contract changes regenerate the baseline:
#
#   python -m vllm_omni_tpu.analysis --update-baseline \
#       vllm_omni_tpu bench.py scripts
#
# then commit the baseline.json diff for review like any code change.
set -eu
cd "$(dirname "$0")/.."

if [ -n "${OMNI_LINT_SARIF:-}" ]; then
    set -- --sarif-out "$OMNI_LINT_SARIF" "$@"
fi

# stale-suppression audit rides the SAME analysis pass as the gate
# (--stale-audit) so the package is analyzed once and the audit judges
# exactly the inputs the gate ran with; only meaningful on full-family
# runs, so an explicit --rules invocation skips it (racecheck-style
# subset callers)
case "$*" in
    *--rules*) ;;
    *) set -- --stale-audit "$@" ;;
esac
python -m vllm_omni_tpu.analysis "$@" vllm_omni_tpu bench.py scripts
