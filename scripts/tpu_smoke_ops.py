"""Native-TPU smoke test for the Pallas op library.

Runs each kernel compiled (not interpreted) on the attached chip and checks
numerics against the pure-JAX references.  Usage (from repo root):

    python scripts/tpu_smoke_ops.py
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from vllm_omni_tpu.ops import (  # noqa: E402
    apply_rope,
    apply_rope_ref,
    attention_ref,
    compute_rope_freqs,
    flash_attention,
    paged_attention,
    paged_attention_ref,
    rms_norm,
    rms_norm_ref,
    write_kv_cache,
)
from vllm_omni_tpu.ops.paged_attention import init_kv_cache  # noqa: E402


def check(name, got, want, atol):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = np.max(np.abs(got - want))
    ok = err <= atol and not np.isnan(err)
    print(f"{'PASS' if ok else 'FAIL'} {name}: max_err={err:.2e} (atol={atol})")
    return ok


def main():
    print("devices:", jax.devices())
    rng = jax.random.PRNGKey(0)
    ok = True

    # rmsnorm bf16
    x = jax.random.normal(rng, (1024, 1024), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (1024,), jnp.bfloat16)
    t0 = time.perf_counter()
    y = rms_norm(x, w, use_pallas=True)
    y.block_until_ready()
    print(f"  rmsnorm compile+run {time.perf_counter()-t0:.1f}s")
    ok &= check("rmsnorm", y, rms_norm_ref(x, w), 0.05)

    # fused residual
    r = jax.random.normal(jax.random.PRNGKey(2), x.shape, jnp.bfloat16)
    y2, r2 = rms_norm(x, w, residual=r, use_pallas=True)
    yr, rr = rms_norm_ref(x, w, residual=r)
    ok &= check("rmsnorm_fused", y2, yr, 0.05)
    ok &= check("rmsnorm_residual", r2, rr, 0.05)

    # rope
    t, h, d = 512, 16, 128
    xq = jax.random.normal(rng, (t, h, d), jnp.bfloat16)
    cos, sin = compute_rope_freqs(jnp.arange(t), d)
    ok &= check(
        "rope", apply_rope(xq, cos, sin, use_pallas=True),
        apply_rope_ref(xq, cos, sin), 0.05,
    )

    # flash attention (non-causal, GQA, ragged)
    b, sq, skv, H, Hkv, D = 2, 517, 517, 8, 4, 128
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, sq, H, D), jnp.bfloat16)
    k = jax.random.normal(k2, (b, skv, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(k3, (b, skv, Hkv, D), jnp.bfloat16)
    t0 = time.perf_counter()
    o = flash_attention(q, k, v, use_pallas=True)
    o.block_until_ready()
    print(f"  flash compile+run {time.perf_counter()-t0:.1f}s")
    ok &= check("flash_noncausal", o, attention_ref(q, k, v), 0.05)
    ok &= check(
        "flash_causal",
        flash_attention(q, k, v, causal=True, use_pallas=True),
        attention_ref(q, k, v, causal=True), 0.05,
    )
    o_l, lse = flash_attention(q, k, v, return_lse=True, use_pallas=True)
    _, lse_ref = attention_ref(q, k, v, return_lse=True)
    ok &= check("flash_lse", lse, lse_ref, 0.05)

    # paged decode
    bsz, H, Hkv, D, page = 8, 8, 4, 128, 16
    (kc, vc), = init_kv_cache(1, 128, page, Hkv, D, jnp.bfloat16)
    ctx = np.array([33, 64, 1, 100, 16, 7, 90, 55])
    max_pages = 8
    bt = np.arange(bsz * max_pages, dtype=np.int32).reshape(bsz, max_pages) % 128
    # scatter random kv at the mapped slots
    for i in range(bsz):
        n = int(ctx[i])
        kn = jax.random.normal(jax.random.PRNGKey(10 + i), (n, Hkv, D), jnp.bfloat16)
        vn = jax.random.normal(jax.random.PRNGKey(50 + i), (n, Hkv, D), jnp.bfloat16)
        slots = []
        for p_i in range((n + page - 1) // page):
            base = int(bt[i, p_i]) * page
            slots += [base + o_ for o_ in range(min(page, n - p_i * page))]
        kc, vc = write_kv_cache(kc, vc, kn, vn, jnp.asarray(slots, jnp.int32))
    qd = jax.random.normal(rng, (bsz, H, D), jnp.bfloat16)
    t0 = time.perf_counter()
    od = paged_attention(
        qd, kc, vc, jnp.asarray(bt), jnp.asarray(ctx), use_pallas=True
    )
    od.block_until_ready()
    print(f"  paged compile+run {time.perf_counter()-t0:.1f}s")
    want = paged_attention_ref(qd, kc, vc, jnp.asarray(bt), jnp.asarray(ctx))
    ok &= check("paged_decode", od, want, 0.05)

    print("ALL PASS" if ok else "SOME FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
