#!/usr/bin/env python
"""Mixed spec+logprobs+embeds serving smoke on the PR 7 harness.

Drives ONE async tiny-model engine open-loop (Poisson arrivals from
``loadgen.workload``, ``RequestRecord``/``summarize`` accounting from
``loadgen.runner``) with the traffic mix the unified-dispatch refactor
exists for: speculative-decode greedy tenants, logprobs tenants, and
embeds-as-input tenants, all interleaved.  Emits a serving-curve point
per offered rate plus the engine's ``async_fallback`` counters and the
per-step device-dispatch count.

Under the split executor (pre PR 11) every one of these request classes
drained the async pipeline (``async_fallback_total{reason}``); after
the refactor the spec/logprobs/embeds/collect_hidden reasons are
structurally impossible — ``--check-fallback`` asserts exactly that and
is wired into scripts/ragged.sh as the CI smoke.

    JAX_PLATFORMS=cpu python scripts/mixed_smoke.py \
        --rates 4,8 --requests 24 --check-fallback --out curve.json
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.engine import EngineConfig, LLMEngine
from vllm_omni_tpu.loadgen.runner import (
    RequestRecord,
    SLOTargets,
    summarize,
    validate_curve_point,
)
from vllm_omni_tpu.loadgen.workload import poisson_arrivals
from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.models.qwen3_omni import mtp
from vllm_omni_tpu.sampling_params import SamplingParams

#: reasons that must be structurally impossible after the unified
#: refactor (the retired fallback matrix)
FORBIDDEN_REASONS = ("spec", "logprobs", "collect_hidden", "embeds",
                     "prefill")


def build_engine(params, cfg, k: int):
    draft_fn = mtp.tiny_factory(params, cfg, k) if k else None
    eng = LLMEngine(params, cfg, EngineConfig(
        num_pages=128, page_size=4, max_model_len=128, max_num_seqs=8,
        max_num_batched_tokens=64, dtype=jnp.float32, seed=0,
        async_scheduling=True, unified_batching=True,
        num_speculative_tokens=k), draft_fn=draft_fn)
    return eng


def make_workload(n: int, rate: float, seed: int, embed_table):
    """n mixed arrivals: round-robin spec-greedy / logprobs / embeds /
    sampled tenants, deterministic prompts per index."""
    offs = poisson_arrivals(rate, n, seed=seed)
    rng = np.random.default_rng(seed)
    reqs = []
    for i, at in enumerate(offs):
        plen = int(rng.integers(4, 12))
        prompt = [int(x) for x in rng.integers(1, 60, size=plen)]
        kind = ("spec", "logprobs", "embeds", "sampled")[i % 4]
        sp = dict(temperature=0.0, max_tokens=8, ignore_eos=True)
        kwargs = {}
        if kind == "logprobs":
            sp["logprobs"] = 3
        elif kind == "embeds":
            kwargs["prompt_embeds"] = np.asarray(embed_table)[prompt]
            prompt = [0] * plen
        elif kind == "sampled":
            sp.update(temperature=0.8, seed=7 + i)
        reqs.append((at, f"{kind}-{i}", kind, prompt, sp, kwargs))
    return reqs


def run_point(params, cfg, rate: float, n: int, k: int) -> dict:
    eng = build_engine(params, cfg, k)
    # prime the jit shape caches with the same mix (measured points
    # must reflect steady-state serving, not first-shape XLA compiles)
    for _, rid, _, prompt, sp, kwargs in make_workload(
            n, 100.0, seed=13, embed_table=params["embed"]["w"]):
        eng.add_request(prompt, SamplingParams(**sp),
                        request_id=f"warm-{rid}", **kwargs)
    while eng.has_unfinished_requests:
        eng.step()
    eng.async_fallback.clear()
    work = make_workload(n, rate, seed=13, embed_table=params["embed"]["w"])
    recs: dict[str, RequestRecord] = {}
    t0 = time.monotonic()
    pending = list(work)
    seen_first: set[str] = set()
    while pending or eng.has_unfinished_requests:
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            at, rid, kind, prompt, sp, kwargs = pending.pop(0)
            recs[rid] = RequestRecord(
                request_id=rid, tenant=kind, scenario=kind,
                arrival_s=at, fired_s=now)
            eng.add_request(prompt, SamplingParams(**sp),
                            request_id=rid, **kwargs)
        if not eng.has_unfinished_requests:
            if pending:
                time.sleep(max(pending[0][0] - (time.monotonic() - t0),
                               0.0))
            continue
        outs = eng.step()
        now = time.monotonic() - t0
        # first-token stamps for TTFT (engine outputs surface only at
        # finish; scan the live table for first emissions)
        for q in (eng.scheduler.running,):
            for req in q:
                if req.output_token_ids and req.request_id in recs \
                        and req.request_id not in seen_first:
                    seen_first.add(req.request_id)
                    recs[req.request_id].first_s = now
        for o in outs:
            rec = recs.get(o.request_id)
            if rec is None:
                continue
            if o.is_error:
                rec.status = "error"
                rec.end_s = now
                continue
            toks = o.outputs[0].token_ids
            if rec.first_s is None:
                rec.first_s = now
            rec.end_s = now
            rec.tokens_out = len(toks)
            rec.status = "ok"
            if o.request_id.startswith("logprobs"):
                lps = o.outputs[0].logprobs
                assert lps and len(lps) >= len(toks), \
                    f"{o.request_id}: logprobs missing"
    point = summarize(list(recs.values()), offered_rps=rate,
                      slo=SLOTargets(ttft_ms=2000.0, tpot_ms=500.0))
    bad = validate_curve_point(point)
    assert not bad, bad
    point["async_fallback"] = dict(eng.async_fallback)
    point["dispatches"] = eng.runner.dispatch_count
    point["engine_steps"] = eng._steps_completed
    point["spec_stats"] = dict(getattr(eng.runner, "spec_stats", {}))
    return point


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="4,8")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--spec-k", type=int, default=2)
    ap.add_argument("--out", default=None)
    ap.add_argument("--check-fallback", action="store_true",
                    help="assert the retired fallback reasons stay zero")
    args = ap.parse_args()

    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    curve = []
    failed = []
    for rate in (float(r) for r in args.rates.split(",")):
        point = run_point(params, cfg, rate, args.requests, args.spec_k)
        curve.append(point)
        fb = point["async_fallback"]
        print(f"rate={rate}: goodput={point['goodput_tok_per_s']} tok/s "
              f"p99_tpot={point['tpot_ms']['p99']}ms "
              f"completed={point['completed']}/{point['num_requests']} "
              f"dispatches={point['dispatches']} fallback={fb}",
              flush=True)
        for reason in FORBIDDEN_REASONS:
            if fb.get(reason):
                failed.append((rate, reason, fb[reason]))
    doc = {"scenario": "mixed spec+logprobs+embeds",
           "serving_curve": curve}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    if args.check_fallback and failed:
        print(f"FORBIDDEN fallback reasons fired: {failed}",
              file=sys.stderr)
        return 1
    ok = all(p["completed"] == p["num_requests"] for p in curve)
    if not ok:
        print("requests failed to complete", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
