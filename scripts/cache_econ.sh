#!/usr/bin/env sh
# omniscope gate: the fleet cache-economics layer end to end — the
# radix digest's fingerprint consistency through insert / evict /
# tier-demotion / park-restore cycles with the node cap enforced, the
# CacheEconomics board's duplicate-prefix accounting against a
# hand-oracled 3-replica fixture, torn-read immunity on /debug/kv and
# /debug/cache under a mutating writer thread, the prefix_hit_rate_low
# fake-clock alert lifecycle, the shared-prefix workload's determinism,
# and the cache-blind baseline bench in smoke mode (2 prefill x 2
# decode in-proc fleet, mid-flight /metrics probe, bounded digests).
#
# Standalone face of the same coverage tier-1 carries (tests/cache is
# a fast directory), sitting next to scripts/alerts.sh,
# scripts/disagg.sh and scripts/omnilint.sh as a pre-merge gate:
#
#   scripts/cache_econ.sh               # the whole omniscope contract
#   scripts/cache_econ.sh -k digest     # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the bench engine is a tiny random-weight model; the gate
# must never touch a real chip a colocated serving process owns
env JAX_PLATFORMS=cpu python -m pytest \
    tests/cache/ \
    -q -p no:cacheprovider -m "not slow" "$@"
exec env JAX_PLATFORMS=cpu python scripts/cache_bench.py --smoke \
    --out /tmp/BENCH_r16_cacheblind_smoke.json
