#!/usr/bin/env sh
# omniscope + omniaffinity gate: the fleet cache-economics layer end
# to end — the radix digest's fingerprint consistency through insert /
# evict / tier-demotion / park-restore cycles with the node cap
# enforced, the CacheEconomics board's duplicate-prefix accounting
# against a hand-oracled 3-replica fixture, torn-read immunity on
# /debug/kv and /debug/cache under a mutating writer thread, the
# prefix_hit_rate_low fake-clock alert lifecycle, the shared-prefix
# workload's determinism, both bench modes in smoke (cache-blind AND
# prefix-affinity 2 prefill x 2 decode in-proc fleets, mid-flight
# /metrics probes, bounded digests), and the pre-registered
# omniaffinity win over the committed baseline artifacts: hit-rate
# and goodput improve, p99 TTFT does not regress
# (scripts/affinity_gate.py, perfguard-backed).
#
# Standalone face of the same coverage tier-1 carries (tests/cache is
# a fast directory), sitting next to scripts/alerts.sh,
# scripts/disagg.sh and scripts/omnilint.sh as a pre-merge gate:
#
#   scripts/cache_econ.sh               # the whole omniscope contract
#   scripts/cache_econ.sh -k digest     # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the bench engine is a tiny random-weight model; the gate
# must never touch a real chip a colocated serving process owns
env JAX_PLATFORMS=cpu python -m pytest \
    tests/cache/ \
    -q -p no:cacheprovider -m "not slow" "$@"
env JAX_PLATFORMS=cpu python scripts/cache_bench.py --smoke \
    --out /tmp/BENCH_r16_cacheblind_smoke.json
env JAX_PLATFORMS=cpu python scripts/cache_bench.py --smoke --affinity \
    --out /tmp/BENCH_r19_affinity_smoke.json
# the committed full-run artifacts carry the pre-registered win
exec python scripts/affinity_gate.py
