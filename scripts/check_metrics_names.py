#!/usr/bin/env python
"""Guard the Prometheus metric surface against silent drift.

Thin shim: the check now lives in omnilint as rule **OL6 metric-drift**
(``vllm_omni_tpu/analysis/rules/metric_drift.py``) so the full gate
(``scripts/omnilint.sh`` / ``python -m vllm_omni_tpu.analysis``) runs it
alongside OL1-OL5.  This entry point stays for existing CI invocations
and for ``tests/metrics/test_prometheus.py``, which load it by path.

Run standalone (``python scripts/check_metrics_names.py``; exits nonzero
on violation).  No jax import — safe for any CI lane.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from vllm_omni_tpu.analysis.rules.metric_drift import (  # noqa: E402
    run_check,
    synthetic_engine_snapshot,
    synthetic_summary,
)

__all__ = ["run_check", "synthetic_engine_snapshot", "synthetic_summary",
           "main"]


def main() -> int:
    errors = run_check()
    if errors:
        for e in errors:
            print(f"METRIC VIOLATION: {e}", file=sys.stderr)
        return 1
    print("metric surface clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
