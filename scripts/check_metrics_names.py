#!/usr/bin/env python
"""Guard the Prometheus metric surface against silent drift.

Asserts that every metric declared in ``metrics/prometheus.METRIC_SPECS``
matches ``vllm_omni_tpu_[a-z_]+`` and that a rendered exposition (from a
synthetic aggregator summary + engine snapshot covering every series)
parses back clean — every sample declared, named correctly, and carrying
the ``stage`` label where its spec requires one.

Run standalone (``python scripts/check_metrics_names.py``; exits nonzero
on violation) or through the mirror pytest
(``tests/metrics/test_prometheus.py``) which calls the same entry point.

No jax import — safe for any CI lane.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def synthetic_summary() -> dict:
    """An aggregator summary exercising every stage/edge series."""
    return {
        "stages": {
            0: {"num_requests": 3, "tokens_in": 30, "tokens_out": 12,
                "tps": 41.5},
            1: {"num_requests": 3, "tokens_in": 12, "tokens_out": 12,
                "tps": 9.0},
        },
        "edges": {"0->1": {"transfers": 3, "bytes": 4096, "ms": 1.25}},
        "e2e": {"num_finished": 3, "window": 3, "p50_ms": 101.0,
                "p90_ms": 250.0, "p99_ms": 251.0},
    }


def synthetic_engine_snapshot() -> dict:
    """An engine snapshot exercising every engine series (LLM histograms
    + scheduler/KV gauges + diffusion counters)."""
    hist = {"buckets": [[10.0, 1], [100.0, 2], [float("inf"), 3]],
            "sum": 123.0, "count": 3, "p50": 40.0, "p90": 100.0,
            "p99": 110.0}
    return {
        "gauges": {"num_waiting": 1, "num_running": 2},
        "counters": {"num_steps": 7, "tokens_generated": 12,
                     "prefill_tokens": 30},
        "ttft_ms": hist, "tpot_ms": hist, "itl_ms": hist,
        "step_ms": hist,
        "scheduler": {"waiting": 1, "running": 2, "preemptions": 1,
                      "rejections": 0},
        "kv": {"pages_total": 64, "pages_used": 8, "utilization": 0.125},
        "prefix_cache": {"enabled": True, "hits": 2, "hit_tokens": 16},
        "diffusion": {"requests_total": 3, "batches_total": 2,
                      "gen_seconds": hist},
    }


def run_check() -> list[str]:
    from vllm_omni_tpu.metrics.prometheus import (
        render_exposition,
        validate_exposition,
        validate_specs,
    )

    errors = validate_specs()
    text = render_exposition(
        synthetic_summary(),
        {0: synthetic_engine_snapshot(), 1: synthetic_engine_snapshot()},
        device={"hbm_bytes": 16 * 2**30},
    )
    errors += validate_exposition(text)
    return errors


def main() -> int:
    errors = run_check()
    if errors:
        for e in errors:
            print(f"METRIC VIOLATION: {e}", file=sys.stderr)
        return 1
    print("metric surface clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
