#!/usr/bin/env sh
# Disaggregation gate: the fault-tolerant prefill/decode topology end
# to end — TPLA sharding + integrity/deadline units on the handoff
# protocol, router units (health ejection/re-admission, least-loaded
# dispatch, drain quiesce, degradation ladder, bounded failover,
# idempotent redelivery), the tiny-model failover matrix (prefill
# death mid-stream -> replay bit-identical to the colocated oracle,
# handoff loss/corruption -> decode-side recompute, tier loss ->
# degraded-colocated, drain-mode quiesce, deadline 504), the open-loop
# chaos run asserting goodput degrades gracefully, and finally the
# standalone two-prefill/one-decode in-proc topology smoke under a
# seeded replica-kill fault plan.
#
# Standalone face of the same coverage tier-1 carries (tests/disagg is
# a fast directory), sitting next to scripts/faultmatrix.sh and
# scripts/loadgen.sh as a pre-merge gate:
#
#   scripts/disagg.sh                 # the whole disaggregation contract
#   scripts/disagg.sh -k failover     # pass-through pytest args
set -eu
cd "$(dirname "$0")/.."
# JAX on CPU: the matrix kills replicas on purpose; it must never touch
# a real TPU chip a colocated serving process owns
env JAX_PLATFORMS=cpu python -m pytest tests/disagg/ \
    -q -p no:cacheprovider -m "not slow" "$@"
# topology smoke: serve through a 2x1 split under a seeded mid-stream
# replica kill; exits nonzero unless every stream matches the
# colocated oracle bit for bit
exec env JAX_PLATFORMS=cpu \
    OMNI_TPU_FAULTS="seed=42;replica0:fail_step=3" \
    python -m vllm_omni_tpu.disagg --prefill 2 --decode 1 --requests 4
