"""Chunked prefill: scheduler continuation chunks + token-identical engine
output vs unchunked (VERDICT r1 next-step #6; reference behavior inherited
from vLLM's scheduler by OmniARScheduler, core/sched/omni_ar_scheduler.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.core.scheduler import ARScheduler, SchedulerConfig
from vllm_omni_tpu.engine.llm_engine import EngineConfig, LLMEngine
from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.ops.attention import attention_ref, flash_attention
from vllm_omni_tpu.request import Request, RequestStatus
from vllm_omni_tpu.sampling_params import SamplingParams


def _mk_req(rid, n, max_tokens=4):
    return Request(
        request_id=rid,
        prompt_token_ids=list(range(1, n + 1)),
        sampling_params=SamplingParams(temperature=0.0, max_tokens=max_tokens),
        eos_token_id=None,
    )


# ---------------------------------------------------------------- op level
def test_flash_attention_q_offsets_matches_ref():
    key = jax.random.PRNGKey(0)
    b, sq, skv, h, hkv, d = 3, 8, 32, 4, 2, 16
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, skv, hkv, d), jnp.float32)
    v = jax.random.normal(kv_, (b, skv, hkv, d), jnp.float32)
    offsets = jnp.asarray([0, 5, 17], jnp.int32)
    ctx = offsets + sq
    kv_mask = (jnp.arange(skv)[None, :] < ctx[:, None]).astype(jnp.int32)

    got = flash_attention(q, k, v, causal=True, kv_mask=kv_mask,
                          q_offsets=offsets)
    want = attention_ref(q, k, v, causal=True, kv_mask=kv_mask,
                         q_offsets=offsets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_q_offsets_pallas_kernel():
    # exercise the Pallas kernel path explicitly (interpret mode on CPU)
    key = jax.random.PRNGKey(1)
    b, sq, skv, h, hkv, d = 2, 16, 64, 4, 2, 32
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, skv, hkv, d), jnp.float32)
    v = jax.random.normal(kv_, (b, skv, hkv, d), jnp.float32)
    offsets = jnp.asarray([3, 40], jnp.int32)
    ctx = offsets + sq
    kv_mask = (jnp.arange(skv)[None, :] < ctx[:, None]).astype(jnp.int32)
    got = flash_attention(q, k, v, causal=True, kv_mask=kv_mask,
                          q_offsets=offsets, use_pallas=True,
                          block_q=8, block_k=16)
    want = attention_ref(q, k, v, causal=True, kv_mask=kv_mask,
                         q_offsets=offsets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


# ------------------------------------------------------------- model level
def test_chunked_forward_matches_full_prefill():
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    from vllm_omni_tpu.ops.paged_attention import init_kv_cache

    page = 4
    prompt = list(np.random.default_rng(0).integers(1, 100, size=13))
    n = len(prompt)

    # full prefill oracle
    caches_a = init_kv_cache(cfg.num_layers, 16, page, cfg.num_kv_heads,
                             cfg.head_dim, jnp.float32)
    toks = jnp.asarray([prompt], jnp.int32)
    pos = jnp.arange(n)[None, :]
    slots = jnp.arange(n)[None, :]
    full_hidden, caches_a = tfm.forward_prefill(
        params, cfg, toks, pos, caches_a, slots)

    # chunked: 6 + 7, second chunk via forward_prefill_chunked
    caches_b = init_kv_cache(cfg.num_layers, 16, page, cfg.num_kv_heads,
                             cfg.head_dim, jnp.float32)
    c1 = 6
    h1, caches_b = tfm.forward_prefill(
        params, cfg, toks[:, :c1], pos[:, :c1], caches_b, slots[:, :c1])
    n2 = n - c1
    tables = jnp.arange(4)[None, :]  # pages 0..3 cover 16 slots
    h2, caches_b = tfm.forward_prefill_chunked(
        params, cfg, toks[:, c1:], pos[:, c1:], caches_b, slots[:, c1:],
        tables, jnp.asarray([n], jnp.int32), jnp.asarray([c1], jnp.int32))

    np.testing.assert_allclose(
        np.asarray(h2[0]), np.asarray(full_hidden[0, c1:]),
        atol=1e-4, rtol=1e-4)
    # caches identical too
    for (ka, va), (kb, vb) in zip(caches_a, caches_b):
        np.testing.assert_allclose(np.asarray(ka), np.asarray(kb), atol=1e-5)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb), atol=1e-5)


# --------------------------------------------------------------- scheduler
def test_scheduler_chunks_long_prompt():
    kv = KVCacheManager(num_pages=64, page_size=4)
    sched = ARScheduler(SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=16, max_model_len=256,
        enable_chunked_prefill=True), kv)
    req = _mk_req("r0", 40)
    sched.add_request(req)

    out1 = sched.schedule()
    assert len(out1.prefills) == 1
    assert out1.prefills[0].num_new_tokens == 16
    assert out1.prefills[0].start_pos == 0
    finished = sched.update_from_output(out1, {})
    assert not finished and req.num_computed_tokens == 16

    out2 = sched.schedule()
    assert out2.prefills[0].start_pos == 16
    assert out2.prefills[0].num_new_tokens == 16
    sched.update_from_output(out2, {})

    out3 = sched.schedule()
    assert out3.prefills[0].start_pos == 32
    assert out3.prefills[0].num_new_tokens == 8
    # final chunk: the runner samples; simulate it
    finished = sched.update_from_output(out3, {"r0": 7})
    assert req.num_computed_tokens == 40
    assert req.output_token_ids == [7]


def test_scheduler_mid_prefill_preemption_recomputes():
    kv = KVCacheManager(num_pages=8, page_size=4)  # 32 slots total
    sched = ARScheduler(SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=8, max_model_len=64,
        enable_chunked_prefill=True), kv)
    a = _mk_req("a", 24)
    sched.add_request(a)
    out = sched.schedule()
    assert out.prefills[0].num_new_tokens == 8
    sched.update_from_output(out, {})
    # burn the pool so the continuation cannot fit: add a second request
    # that grabs the remaining pages
    b = _mk_req("b", 8)
    sched.add_request(b)
    out = sched.schedule()
    # a continues (8 more), b admitted if pages remain
    sched.update_from_output(out, {})
    # force page exhaustion for a's final chunk by shrinking free pool
    while kv.num_free_pages:
        kv._free.pop()
    out = sched.schedule()
    # a (head of running) cannot fit its chunk: preempts b first, else self
    assert a.num_computed_tokens in (0, 16, 24) or a.status is \
        RequestStatus.PREEMPTED


# ------------------------------------------------------------- engine e2e
@pytest.mark.parametrize("budget", [8, 16])
def test_engine_chunked_token_identical(budget):
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(2), cfg, jnp.float32)
    prompt = list(np.random.default_rng(3).integers(1, 100, size=37))
    sp = SamplingParams(temperature=0.0, max_tokens=8)

    def run(chunked, btok):
        eng = LLMEngine(params, cfg, EngineConfig(
            num_pages=64, page_size=4, max_model_len=128, max_num_seqs=4,
            max_num_batched_tokens=btok, dtype=jnp.float32, seed=0,
            enable_chunked_prefill=chunked,
        ))
        outs = eng.generate([prompt], sp)
        assert outs[0].finished and not outs[0].is_error, \
            outs[0].error_message
        return outs[0].outputs[0].token_ids

    want = run(False, 2048)
    got = run(True, budget)
    assert got == want


def test_engine_chunked_multi_request_parity():
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(4), cfg, jnp.float32)
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(1, 100, size=n)) for n in (30, 5, 21)]
    sp = SamplingParams(temperature=0.0, max_tokens=6)

    def run(chunked, btok):
        eng = LLMEngine(params, cfg, EngineConfig(
            num_pages=64, page_size=4, max_model_len=128, max_num_seqs=4,
            max_num_batched_tokens=btok, dtype=jnp.float32, seed=0,
            enable_chunked_prefill=chunked,
        ))
        outs = eng.generate(prompts, sp)
        return [o.outputs[0].token_ids for o in outs]

    assert run(True, 16) == run(False, 2048)


def _resume_after_preempt(prefix_caching: bool):
    kv = KVCacheManager(num_pages=64, page_size=4,
                        enable_prefix_caching=prefix_caching)
    sched = ARScheduler(SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=8, max_model_len=256,
        enable_chunked_prefill=True), kv)
    req = _mk_req("r", 10, max_tokens=32)
    sched.add_request(req)
    # prefill in chunks of 8, then decode a few tokens
    out = sched.schedule(); sched.update_from_output(out, {})
    out = sched.schedule(); sched.update_from_output(out, {"r": 1})
    for t in (2, 3, 4, 5, 6):
        out = sched.schedule()
        assert len(out.decodes) == 1
        sched.update_from_output(out, {"r": t})
    assert req.num_tokens == 16
    # preempt: pages free (registering full prompt pages when caching)
    sched._preempt(req)
    assert req.num_computed_tokens == 0
    return sched, req


def test_resumed_request_chunks_generated_suffix():
    """A preempted request recomputes prompt + generated tokens in chunks,
    not one decode step at a time (code-review finding: the continuation
    branch must gate on num_tokens, not num_prompt_tokens)."""
    sched, req = _resume_after_preempt(prefix_caching=False)
    # resume: admission chunk of 8, then the *running* branch must chunk
    # the remaining 8 (which includes generated tokens) in ONE prefill
    out = sched.schedule()
    assert len(out.prefills) == 1 and out.prefills[0].num_new_tokens == 8
    sched.update_from_output(out, {})
    out = sched.schedule()
    assert len(out.prefills) == 1 and len(out.decodes) == 0
    assert out.prefills[0].start_pos == 8
    # chunk covers through num_tokens-1... the final recompute chunk ends
    # at num_tokens (16), whose last row resamples the next token
    assert out.prefills[0].num_new_tokens == 8


def test_resumed_request_reuses_cached_prefix():
    """With automatic prefix caching, preemption registers the full
    prompt pages; resume adopts them and recomputes ONLY the
    tail (prompt remainder + generated tokens) in one chunk."""
    sched, req = _resume_after_preempt(prefix_caching=True)
    out = sched.schedule()
    # 8 prompt tokens rode the cache: only tokens 8..15 recompute
    assert req.num_computed_tokens == 8
    assert len(out.prefills) == 1
    assert out.prefills[0].start_pos == 8
    assert out.prefills[0].num_new_tokens == 8
    assert sched.kv.prefix_hit_tokens == 8
    sched.update_from_output(out, {"r": 7})
    assert req.num_computed_tokens == 16


def test_intake_accepts_long_prompt_when_chunked():
    kv = KVCacheManager(num_pages=64, page_size=4)
    sched = ARScheduler(SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=16, max_model_len=256,
        enable_chunked_prefill=True), kv)
    req = _mk_req("long", 100)
    sched.add_request(req)
    assert req.status is RequestStatus.WAITING
