"""KV block-pool accounting (mirrors the reference scheduler's block
lifecycle incl. transfer pinning, omni_ar_scheduler.py:444-594)."""

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.request import Request


def _req(rid="r0", n=10):
    return Request(request_id=rid, prompt_token_ids=list(range(n)))


def test_allocate_and_slots():
    kv = KVCacheManager(num_pages=8, page_size=4)
    req = _req(n=10)
    table = kv.allocate(req, 10)
    assert len(table) == 3  # ceil(10/4)
    assert kv.num_free_pages == 5
    slots = kv.slot_mapping(req, 10)
    assert len(slots) == 10
    assert slots[0] == table[0] * 4
    assert slots[4] == table[1] * 4
    assert slots[9] == table[2] * 4 + 1


def test_incremental_growth():
    kv = KVCacheManager(num_pages=4, page_size=4)
    req = _req(n=4)
    kv.allocate(req, 4)
    req.num_computed_tokens = 4
    # next token needs a new page
    table = kv.allocate(req, 1)
    assert len(table) == 2
    assert kv.slot_mapping(req, 1) == [table[1] * 4]


def test_free_returns_pages():
    kv = KVCacheManager(num_pages=4, page_size=4)
    req = _req(n=16)
    assert kv.allocate(req, 16) is not None
    assert kv.num_free_pages == 0
    kv.free(req)
    assert kv.num_free_pages == 4


def test_out_of_pages():
    kv = KVCacheManager(num_pages=2, page_size=4)
    r1, r2 = _req("a", 8), _req("b", 4)
    assert kv.allocate(r1, 8) is not None
    assert not kv.can_allocate(r2, 4)
    assert kv.allocate(r2, 4) is None


def test_pin_for_transfer_delays_free():
    kv = KVCacheManager(num_pages=4, page_size=4)
    req = _req(n=10)
    kv.allocate(req, 10)
    snapshot = kv.pin_for_transfer(req, 6)  # 6 tokens -> 2 pages
    assert len(snapshot) == 2
    kv.free(req)
    # 3 pages allocated, 2 pinned -> only 1 + 1 untouched free
    assert kv.num_free_pages == 2
    kv.ack_transfer(req.request_id)
    assert kv.num_free_pages == 4


def test_ack_with_live_table_keeps_pages():
    kv = KVCacheManager(num_pages=4, page_size=4)
    req = _req(n=8)
    kv.allocate(req, 8)
    kv.pin_for_transfer(req, 8)
    kv.ack_transfer(req.request_id)  # request still running
    assert kv.num_free_pages == 2
    kv.free(req)
    assert kv.num_free_pages == 4
