"""Scheduler behavioral contract (ports the reference's OmniARScheduler /
OmniGenerationScheduler semantics, core/sched/*.py)."""

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.core.scheduler import (
    ARScheduler,
    GenerationScheduler,
    KVTransferConfig,
    SchedulerConfig,
)
from vllm_omni_tpu.request import KVTransferState, Request, RequestStatus
from vllm_omni_tpu.sampling_params import SamplingParams


def _mk(cfg=None, pages=64, page_size=4, cls=ARScheduler):
    cfg = cfg or SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                                 max_model_len=64)
    return cls(cfg, KVCacheManager(pages, page_size))


def _req(rid, n=8, max_tokens=4, **kw):
    return Request(request_id=rid, prompt_token_ids=list(range(n)),
                   sampling_params=SamplingParams(max_tokens=max_tokens), **kw)


def test_prefill_then_decode_lifecycle():
    s = _mk()
    s.add_request(_req("a", n=8, max_tokens=2))
    out = s.schedule()
    assert len(out.prefills) == 1 and not out.decodes
    assert out.prefills[0].num_new_tokens == 8
    finished = s.update_from_output(out, {"a": 42})
    assert not finished
    req = s.running[0]
    assert req.output_token_ids == [42]
    assert req.num_computed_tokens == 8

    out2 = s.schedule()
    assert len(out2.decodes) == 1 and not out2.prefills
    d = out2.decodes[0]
    assert d.start_pos == 8 and d.num_new_tokens == 1
    finished = s.update_from_output(out2, {"a": 7})
    assert len(finished) == 1  # max_tokens=2 reached
    assert finished[0].status == RequestStatus.FINISHED_LENGTH
    assert not s.has_unfinished


def test_eos_stops():
    s = _mk()
    req = _req("a", n=4, max_tokens=10)
    req.eos_token_id = 99
    s.add_request(req)
    out = s.schedule()
    finished = s.update_from_output(out, {"a": 99})
    assert finished and finished[0].status == RequestStatus.FINISHED_STOPPED
    assert finished[0].finish_reason == "stop"


def test_token_budget_defers_waiting():
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=10,
                          max_model_len=64)
    s = _mk(cfg)
    s.add_request(_req("a", n=8))
    s.add_request(_req("b", n=8))  # doesn't fit in the same step
    out = s.schedule()
    assert len(out.prefills) == 1
    s.update_from_output(out, {"a": 1})
    out2 = s.schedule()
    # b prefills now, a decodes
    assert {sc.request.request_id for sc in out2.prefills} == {"b"}
    assert {sc.request.request_id for sc in out2.decodes} == {"a"}


def test_max_num_seqs_limit():
    cfg = SchedulerConfig(max_num_seqs=2, max_num_batched_tokens=1024,
                          max_model_len=64)
    s = _mk(cfg)
    for rid in "abc":
        s.add_request(_req(rid, n=4))
    out = s.schedule()
    assert len(out.prefills) == 2
    assert len(s.waiting) == 1


def test_preemption_recompute_on_page_exhaustion():
    # 4 pages of 4 slots = 16 tokens total; two 8-token requests fill it,
    # the first decode token forces a preemption
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64)
    s = _mk(cfg, pages=4, page_size=4)
    s.add_request(_req("a", n=8, max_tokens=8))
    s.add_request(_req("b", n=8, max_tokens=8))
    out = s.schedule()
    assert len(out.prefills) == 2
    s.update_from_output(out, {"a": 1, "b": 1})
    out2 = s.schedule()
    assert out2.preempted, "one request must be preempted on page exhaustion"
    victim = out2.preempted[0]
    assert victim.status == RequestStatus.PREEMPTED
    assert victim in s.waiting
    # the survivor still decoded
    assert len(out2.decodes) == 1
    # recompute policy, radix-tempered: the victim's in-flight progress
    # is discarded, but the radix index evicts DEEPEST-first, so the
    # victim's first prompt page survives the survivor's allocation and
    # is re-adopted at re-admission (the flat chained-hash map evicted
    # the chain head and restarted from 0 — docs/kv_cache.md)
    assert victim.num_computed_tokens == 4
    assert len(s.kv.block_table(victim.request_id)) == 1


def test_kv_transfer_trigger_on_prefill_finished():
    cfg = SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=64, max_model_len=64,
        kv_transfer=KVTransferConfig(trigger="prefill_finished"),
    )
    s = _mk(cfg)
    s.add_request(_req("a", n=8, max_tokens=4))
    out = s.schedule()
    s.update_from_output(out, {"a": 5})
    req = s.running[0]
    assert req.kv_transfer == KVTransferState.ACTIVE
    # the transfer rides the *next* schedule() so the runner extracts at
    # the start of its step (reference: gpu_ar_model_runner.py:100-106)
    out2 = s.schedule()
    assert out2.kv_transfer_requests
    _, block_ids, seq_len = out2.kv_transfer_requests[0]
    # only computed tokens are in the cache (the sampled token's KV is
    # written next step)
    assert seq_len == 8
    assert len(block_ids) == 2  # ceil(8/4)
    # ACK frees the pin
    s.update_from_output(out2, {"a": 6}, kv_extracted_req_ids={"a"})
    assert req.kv_transfer == KVTransferState.DONE


def test_kv_transfer_special_token_trigger():
    cfg = SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=64, max_model_len=64,
        kv_transfer=KVTransferConfig(trigger="special_token",
                                     special_token_id=77),
    )
    s = _mk(cfg)
    s.add_request(_req("a", n=4, max_tokens=8))
    out = s.schedule()
    s.update_from_output(out, {"a": 5})
    assert s.running[0].kv_transfer == KVTransferState.PENDING
    out2 = s.schedule()
    s.update_from_output(out2, {"a": 77})
    assert s.running[0].kv_transfer == KVTransferState.ACTIVE


def test_generation_scheduler_one_shot():
    s = _mk(cls=GenerationScheduler)
    s.add_request(_req("a", n=12))
    s.add_request(_req("b", n=6))
    out = s.schedule()
    assert len(out.prefills) == 2
    assert all(sc.num_new_tokens == sc.request.num_prompt_tokens
               for sc in out.prefills)
    finished = s.update_from_output(out, {})
    assert len(finished) == 2
    assert not s.has_unfinished
    # all pages returned
    assert s.kv.num_free_pages == 64


def test_unschedulable_prompt_rejected_at_intake():
    # budget 10 < prompt 12 with chunked prefill off -> intake error, not
    # an engine-starving waiting-queue pin
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=10,
                          max_model_len=64)
    s = _mk(cfg)
    s.add_request(_req("a", n=12))
    assert not s.has_unfinished
    errored = s.drain_errored()
    assert len(errored) == 1
    assert errored[0].status == RequestStatus.FINISHED_ERROR


def test_prompt_larger_than_kv_pool_rejected():
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64)
    s = _mk(cfg, pages=2, page_size=4)  # pool holds 8 tokens
    s.add_request(_req("a", n=12))
    assert s.drain_errored()


def test_ack_after_finish_marks_done():
    cfg = SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=64, max_model_len=64,
        kv_transfer=KVTransferConfig(trigger="prefill_finished"),
    )
    s = _mk(cfg)
    req = _req("a", n=4, max_tokens=1)
    s.add_request(req)
    out = s.schedule()
    finished = s.update_from_output(out, {"a": 5})  # finishes (max_tokens=1)
    assert finished and not s.has_unfinished
    assert req.kv_transfer == KVTransferState.ACTIVE
    # ACK lands after the request left running/waiting
    from vllm_omni_tpu.core.scheduler import SchedulerOutput
    s.update_from_output(SchedulerOutput(), {}, {"a"})
    assert req.kv_transfer == KVTransferState.DONE


def test_chunked_prefill_flag_accepted():
    # chunked prefill is implemented (tests/core/test_chunked_prefill.py);
    # the flag constructs a working scheduler
    cfg = SchedulerConfig(enable_chunked_prefill=True)
    s = _mk(cfg)
    assert s.config.enable_chunked_prefill


def test_abort():
    s = _mk()
    s.add_request(_req("a", n=4))
    s.abort_request("a")
    assert not s.has_unfinished


def test_multi_step_window_retired():
    """The multi-step window is retired (PR 11): the knob is accepted
    as a no-op, every decode row is window 1 with exactly one slot —
    no window-ahead page reservation survives."""
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64, multi_step_decode=4)
    s = _mk(cfg)
    s.add_request(_req("a", n=8, max_tokens=6))
    s.update_from_output(s.schedule(), {"a": 1})  # prefill, 1 token out

    out = s.schedule()
    d = out.decodes[0]
    assert d.window == 1
    assert len(d.slot_mapping) == 1
    finished = s.update_from_output(out, {"a": 2})
    assert not finished


def test_spec_verify_in_flight_holds_request():
    """Async spec pipelining: while a k+1-candidate verify dispatch is
    in flight (num_inflight_tokens > 1) the request's next KV position
    is unknown — schedule() must HOLD it (no row emitted) until the
    lagged retire lands; plain decode rows (one in-flight token) keep
    pipelining ahead."""
    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64, num_speculative_tokens=3)
    s = _mk(cfg)
    s.add_request(_req("a", n=4, max_tokens=16))
    out = s.schedule()
    s.note_async_dispatch(out)
    retired = s.update_from_async_retire(out, {"a": 1})
    assert not retired
    req = s.running[0]
    req.spec_draft_tokens = [5, 6, 7]

    out2 = s.schedule()          # verify row: 1 + 3 candidates
    assert out2.decodes and out2.decodes[0].num_new_tokens == 4
    s.note_async_dispatch(out2)
    assert req.num_inflight_tokens == 4

    held = s.schedule()          # verify in flight -> held, not rescheduled
    assert held.num_scheduled == 0
    assert req in s.running

    # lagged retire: 2 of 4 candidates accepted -> rewind keeps exactly
    # the accepted prefix and the request schedules again
    s.update_from_async_retire(out2, {"a": [2, 3]})
    assert req.num_inflight_tokens == 0
    assert req.output_token_ids == [1, 2, 3]
    assert req.num_computed_tokens == req.num_tokens - 1
    req.spec_draft_tokens = []
    out3 = s.schedule()
    assert out3.decodes and out3.decodes[0].num_new_tokens == 1
    assert out3.decodes[0].start_pos == req.num_computed_tokens


def test_preemption_and_rejection_counters():
    """Lifetime counters surfaced by /metrics (observability PR)."""
    s = _mk()
    assert s.num_preemptions == 0 and s.num_rejections == 0
    s.add_request(_req("too-long", n=100))  # > max_model_len -> reject
    assert s.num_rejections == 1
    s._preempt(_req("victim", n=4))
    assert s.num_preemptions == 1


def test_restored_park_resumes_as_decode():
    """Resume-as-decode: a restored preemption victim whose only
    outstanding position is the sampling one re-enters through the
    DECODE path — the executable the uninterrupted stream would have
    run — not a 1-token prefill chunk (the two agree only to the last
    ULP, which flips greedy argmaxes on near-flat logits;
    docs/kv_cache.md)."""
    import numpy as np

    from vllm_omni_tpu.kvcache.policy import OffloadPolicy
    from vllm_omni_tpu.kvcache.tiers import TieredKVStore

    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64, kv_offload=True)
    kv = KVCacheManager(4, 4, enable_prefix_caching=False,
                        tiers=TieredKVStore(),
                        policy=OffloadPolicy(mode="always"))
    s = ARScheduler(cfg, kv)
    s.add_request(_req("a", n=8, max_tokens=2))
    s.add_request(_req("b", n=8, max_tokens=8))
    out = s.schedule()          # both prefill: 2 pages each, pool full
    assert len(out.prefills) == 2
    s.update_from_output(out, {"a": 1, "b": 1})

    out2 = s.schedule()         # a's decode page preempts b -> parked
    assert out2.preempted and out2.preempted[0].request_id == "b"
    victim = out2.preempted[0]
    assert victim.additional_information.get("_parked_len") == 8
    # simulate the engine's same-step extraction drain: the payload
    # lands in the host tier and the in-flight marker clears
    offloads, _ = kv.take_pending_moves()
    parks = [o for o in offloads if o.key.endswith(victim.request_id)]
    assert parks, "preemption with kv_offload must queue a park"
    for o in parks:
        kv.tiers.put(o.key, [(np.zeros(2, np.float32),
                              np.zeros(2, np.float32))])
        kv.note_park_extracted(o.key)
    s.update_from_output(out2, {"a": 2})  # a finishes -> pages free

    out3 = s.schedule()         # b restores; 1 token outstanding
    assert not out3.prefills, \
        "restored victim must not re-enter through the prefill path"
    assert [d.request.request_id for d in out3.decodes] == ["b"]
    d = out3.decodes[0]
    assert d.num_new_tokens == 1 and d.window == 1
    assert d.start_pos == victim.num_computed_tokens == 8
    assert kv.restored_tokens == 8
    # the resumed row continues its stream like any running decode
    s.update_from_output(out3, {"b": 2})
    assert victim.output_token_ids == [1, 2]
    assert victim.status == RequestStatus.RUNNING


def test_parked_payload_lost_closes_park_interval():
    """Payload-lost recompute goes through drop_park: the host-tier
    page·second interval (per-tenant attribution, metrics/
    attribution.py) stops at the shed instead of accruing phantom
    residency through the request's whole recompute+decode life."""
    from vllm_omni_tpu.kvcache.policy import OffloadPolicy
    from vllm_omni_tpu.kvcache.tiers import TieredKVStore

    cfg = SchedulerConfig(max_num_seqs=4, max_num_batched_tokens=64,
                          max_model_len=64, kv_offload=True)
    kv = KVCacheManager(4, 4, enable_prefix_caching=False,
                        tiers=TieredKVStore(),
                        policy=OffloadPolicy(mode="always"))
    s = ARScheduler(cfg, kv)
    s.add_request(_req("a", n=8, max_tokens=2))
    s.add_request(_req("b", n=8, max_tokens=8))
    out = s.schedule()
    s.update_from_output(out, {"a": 1, "b": 1})
    out2 = s.schedule()         # a's decode page preempts b -> parked
    victim = out2.preempted[0]
    assert victim.request_id == "b"
    # extraction drains, but the payload never lands in the host tier
    # (shed before the restore): parked_available stays False
    offloads, _ = kv.take_pending_moves()
    for o in offloads:
        if o.key.endswith(victim.request_id):
            kv.note_park_extracted(o.key)
    s.update_from_output(out2, {"a": 2})  # a finishes -> pages free
    assert victim.request_id in kv._park_time
    out3 = s.schedule()         # payload lost -> full recompute
    assert [p.request.request_id for p in out3.prefills] == ["b"]
    # the park interval is CLOSED and the park marker is gone
    assert victim.request_id not in kv._park_time
    assert "_parked_len" not in victim.additional_information
