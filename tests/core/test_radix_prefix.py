"""Radix prefix index: randomized oracle cross-check + tier plumbing.

The radix index (kvcache/radix.py) replaced the flat chained-hash map
inside ``KVCacheManager``.  Under NO eviction pressure the two must be
behaviorally identical — same hits, same hit-token counts, same
refcounts, no page leaks — so a compact reimplementation of the old
flat map drives the same randomized request stream as the real manager
and every divergence is a bug.  Under pressure the flat map's behavior
was the thing being FIXED (mid-chain eviction orphaning suffixes), so
the pressure phase checks structural invariants and page conservation
instead of equivalence.

Also the satellite regression for the pin/evict race: a page pinned by
one request's in-flight transfer must be unevictable even when another
sharer's free() drops its last cache reference.
"""

import hashlib
import random

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.kvcache import OffloadPolicy, TieredKVStore
from vllm_omni_tpu.kvcache.radix import RadixPrefixIndex
from vllm_omni_tpu.request import Request


def _req(rid, ids):
    return Request(request_id=rid, prompt_token_ids=list(ids))


# --------------------------------------------------------------- oracle
class FlatPrefixOracle:
    """The OLD flat chained-hash prefix cache, boiled down to its
    match/register/refcount observables (no real pages — it scores
    hits on the same prompts the manager sees)."""

    def __init__(self, page_size):
        self.page_size = page_size
        self._cached: dict[str, str] = {}   # hash -> producing owner
        self._ref: dict[str, int] = {}      # hash -> live refs
        self._adopted: dict[str, list[str]] = {}

    def _hashes(self, ids, max_pages=None):
        out, prev = [], b""
        n = len(ids) // self.page_size
        if max_pages is not None:
            n = min(n, max_pages)
        for p in range(n):
            chunk = ids[p * self.page_size:(p + 1) * self.page_size]
            h = hashlib.blake2b(
                prev + b"," + repr(list(chunk)).encode(),
                digest_size=16).hexdigest()
            out.append(h)
            prev = h.encode()
        return out

    def match(self, rid, ids):
        usable = len(ids) - 1
        hashes = self._hashes(ids, max_pages=usable // self.page_size)
        hit = []
        for h in hashes:
            if h not in self._cached:
                break
            hit.append(h)
        if hit:
            for h in hit:
                self._ref[h] = self._ref.get(h, 0) + 1
            self._adopted[rid] = hit
        return len(hit) * self.page_size

    def free(self, rid, ids, computed):
        for h in self._adopted.pop(rid, ()):
            self._ref[h] -= 1
        valid = min(len(ids) // self.page_size,
                    computed // self.page_size)
        for h in self._hashes(ids)[:valid]:
            self._cached.setdefault(h, rid)

    def refcount(self, ids, n_pages):
        return [self._ref.get(h, 0)
                for h in self._hashes(ids)[:n_pages]]


def _page_accounting(kv: KVCacheManager) -> dict:
    """Every page must be exactly one of: free, in a live table (and
    not index-owned), or index-owned."""
    owned = set(kv.index._by_page)
    table_pages = set()
    for t in kv._tables.values():
        table_pages.update(t)
    free = set(kv._free)
    return {"free": free, "tables": table_pages, "index": owned}


def _assert_no_leaks(kv: KVCacheManager):
    acct = _page_accounting(kv)
    # free pages never overlap live storage
    assert not (acct["free"] & acct["tables"]), "free∩tables"
    assert not (acct["free"] & acct["index"]), "free∩index"
    pinned = kv._pinned_pages()
    covered = acct["free"] | acct["tables"] | acct["index"] | pinned
    assert covered == set(range(kv.num_pages)), (
        f"leaked pages: {set(range(kv.num_pages)) - covered}")
    assert not kv.index.check_invariants()


# ------------------------------------------------- randomized equivalence
def test_radix_matches_flat_oracle_no_pressure():
    """Same random stream, no eviction pressure: identical hits,
    identical per-page refcounts, zero leaks."""
    rng = random.Random(1234)
    page = 4
    kv = KVCacheManager(num_pages=4096, page_size=page)
    oracle = FlatPrefixOracle(page)
    # small alphabet + shared stems => heavy prefix overlap
    stems = [[rng.randrange(8) for _ in range(rng.randrange(4, 24))]
             for _ in range(6)]
    live: dict[str, Request] = {}
    for i in range(400):
        op = rng.random()
        if op < 0.6 or not live:
            stem = rng.choice(stems)
            ids = (list(stem)
                   + [rng.randrange(8)
                      for _ in range(rng.randrange(1, 12))])
            rid = f"r{i}"
            req = _req(rid, ids)
            got = kv.match_prefix(req)
            want = oracle.match(rid, ids)
            assert got == want, f"hit divergence at {i}: {got} != {want}"
            assert kv.allocate(req, len(ids) - got) is not None
            req.num_computed_tokens = len(ids)
            live[rid] = req
        else:
            rid = rng.choice(sorted(live))
            req = live.pop(rid)
            kv.free(req)
            oracle.free(rid, req.prompt_token_ids,
                        req.num_computed_tokens)
        _assert_no_leaks(kv)
        # spot-check refcounts on a shared stem's pages
        stem = stems[0]
        nodes = kv.index.match(stem, max_pages=len(stem) // page)
        want_refs = oracle.refcount(stem, len(nodes))
        assert [n.ref for n in nodes] == want_refs
    for req in live.values():
        kv.free(req)
    _assert_no_leaks(kv)
    assert kv.prefix_hits > 0 and kv.prefix_hit_tokens > 0


def test_radix_invariants_under_pressure():
    """Tiny pool, constant eviction: structural invariants + page
    conservation hold on every step (equivalence with the flat map is
    OUT of scope here — mid-chain orphaning is what got fixed)."""
    rng = random.Random(99)
    page = 4
    kv = KVCacheManager(num_pages=16, page_size=page)
    stems = [[rng.randrange(4) for _ in range(12)] for _ in range(3)]
    live: dict[str, Request] = {}
    for i in range(300):
        if rng.random() < 0.55 or not live:
            stem = rng.choice(stems)
            ids = list(stem) + [rng.randrange(4)
                                for _ in range(rng.randrange(1, 8))]
            req = _req(f"p{i}", ids)
            kv.match_prefix(req)
            remaining = len(ids) - req.num_computed_tokens
            if kv.can_allocate(req, remaining) \
                    and kv.allocate(req, remaining) is not None:
                req.num_computed_tokens = len(ids)
                live[req.request_id] = req
            else:
                kv.free(req)
        else:
            kv.free(live.pop(rng.choice(sorted(live))))
        _assert_no_leaks(kv)
    for req in live.values():
        kv.free(req)
    _assert_no_leaks(kv)
    # the pool must be fully recoverable
    assert kv.reset_prefix_cache() >= 0
    assert kv.num_free_pages == kv.num_pages


def test_deep_eviction_keeps_prefix_over_extension():
    """The fix over the flat map: under pressure the EXTENSION page is
    reclaimed first and the shared prefix stays matchable."""
    kv = KVCacheManager(num_pages=4, page_size=4)
    a = _req("a", list(range(12)))          # 3 pages, all full: register
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    assert kv.index.hbm_pages() == 3
    # pressure: a fresh request needs 3 pages -> 1 free + 2 evictions
    b = _req("b", [50, 51, 52, 53, 54, 55, 56, 57, 58])
    assert kv.allocate(b, 9) is not None
    # the SURVIVING cached page is the depth-1 PREFIX — eviction took
    # the two extensions first — so a follow-up sharing the stem still
    # hits 4 tokens (the flat map's LRU popped insertion order, i.e.
    # the chain head, orphaning the whole chain)
    assert kv.index.hbm_pages() == 1
    c = _req("c", list(range(12)))
    assert kv.match_prefix(c) == 4
    assert c.num_computed_tokens == 4
    survivor = kv.index._by_page[kv.block_table("c")[0]]
    assert survivor.tokens == (0, 1, 2, 3)


# --------------------------------------------------- pin/evict regression
def test_pinned_shared_page_is_unevictable():
    """Satellite fix: R1 pins a SHARED cached page for an in-flight
    transfer; R2 (the other sharer) frees — the page's last cache ref
    drops, but it must NOT enter the evictable pool until the ACK."""
    kv = KVCacheManager(num_pages=4, page_size=4)
    prod = _req("prod", list(range(8)))     # 2 full pages register
    kv.allocate(prod, 8)
    prod.num_computed_tokens = 8
    kv.free(prod)
    r1, r2 = _req("r1", list(range(8)) + [9]), _req("r2", list(range(8)) + [9])
    assert kv.match_prefix(r1) == 8
    assert kv.match_prefix(r2) == 8
    shared = kv.block_table("r1")
    assert kv.block_table("r2") == shared
    pinned = kv.pin_for_transfer(r1, 8)     # transfer in flight
    assert pinned == shared
    kv.free(r1)
    kv.free(r2)                             # last sharer gone
    # both shared pages are pinned: NOT free, NOT evictable
    assert kv.num_free_pages == 2
    # allocation pressure must not reclaim them mid-read
    big = _req("big", list(range(100, 116)))
    table = kv.allocate(big, 8)             # wants 2 pages: the free ones
    assert table is not None
    assert not (set(table) & set(pinned)), \
        "evict-under-pressure handed out a pinned page"
    assert kv.allocate(_req("more", [1, 2, 3, 4]), 4) is None
    # ACK releases the pin; the cached pages become evictable again
    kv.ack_transfer("r1")
    assert kv.num_free_pages == 2
    c = _req("c", list(range(8)) + [7])
    assert kv.match_prefix(c) == 8          # still cached, content kept
    kv.free(big)
    kv.free(c)
    assert kv.reset_prefix_cache() == 2
    assert kv.num_free_pages == kv.num_pages


def test_pin_refcounts_stack_across_requests():
    """Two transfers pinning the same page: one ACK must not release
    the other's pin."""
    kv = KVCacheManager(num_pages=4, page_size=4)
    prod = _req("prod", list(range(8)))
    kv.allocate(prod, 8)
    prod.num_computed_tokens = 8
    kv.free(prod)
    r1, r2 = _req("r1", list(range(9))), _req("r2", list(range(9)))
    kv.match_prefix(r1)
    kv.match_prefix(r2)
    kv.pin_for_transfer(r1, 8)
    kv.pin_for_transfer(r2, 8)
    kv.free(r1)
    kv.free(r2)
    kv.ack_transfer("r1")
    assert kv.num_free_pages == 2           # r2's pin still holds
    kv.ack_transfer("r2")
    assert kv.num_free_pages == 4


# ------------------------------------------------------- tiered plumbing
def _offload_kv(**kw):
    tiers = TieredKVStore(**kw)
    kv = KVCacheManager(num_pages=4, page_size=4, tiers=tiers,
                        policy=OffloadPolicy(mode="always"))
    return kv, tiers


def _drain_offloads(kv, tiers):
    """Engine-drain stand-in: park each queued payload and clear the
    in-flight marks, exactly like LLMEngine._drain_kv_moves."""
    for off in kv.pending_offloads:
        tiers.put(off.key, [])              # content irrelevant here
        kv.note_park_extracted(off.key)
    kv.pending_offloads.clear()


def test_eviction_offload_queues_extract_and_keeps_node_matchable():
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    b = _req("b", [9, 9, 9, 9, 9, 9, 9, 9, 9])
    assert kv.allocate(b, 9) is not None    # 1 free page + 2 evictions
    assert len(kv.pending_offloads) == 2
    for off in kv.pending_offloads:
        assert off.n_tokens == 4 and len(off.pages) == 1
    _drain_offloads(kv, tiers)
    kv.free(b)
    # cold nodes are still matchable: the hot depth-1 prefix adopts
    # directly, the cold depth-2 node comes back via a queued restore
    c = _req("c", list(range(12)))
    matched = kv.match_prefix(c)
    assert matched == 8
    assert len(kv.pending_restores) == 1
    r = kv.pending_restores[0]
    assert r.n_tokens == 4 and r.request_id == "c"
    assert kv.restored_tokens == 4


def test_same_pass_evict_then_match_trusts_inflight_extraction():
    """A node evicted cold earlier in the SAME schedule pass (its
    extraction queued but not yet drained) must still match: the
    engine drains extractions before restore fetches, so the payload
    exists by fetch time.  Dropping it would orphan the payload the
    drain later stores."""
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    b = _req("b", [9] * 9)
    assert kv.allocate(b, 9) is not None    # queues 2 offloads
    assert len(kv.pending_offloads) == 2
    assert not tiers.has(kv.pending_offloads[0].key)  # NOT drained yet
    kv.free(b)
    c = _req("c", list(range(12)))
    # same pass: tiers.has() is False but the key is in flight
    assert kv.match_prefix(c) == 8
    assert len(kv.pending_restores) == 1
    _assert_no_leaks(kv)


def test_park_and_restore_lifecycle():
    kv, tiers = _offload_kv()
    a = _req("a", list(range(10)))
    kv.allocate(a, 10)
    a.num_computed_tokens = 10
    parked = kv.park_request(a)
    # parks the committed run, always leaving >= 1 token to compute on
    # resume (its forward produces the logits to sample from)
    assert parked == 9
    assert kv.park_in_flight(a)
    off = kv.pending_offloads[-1]
    assert off.key == "park/a" and off.n_tokens == 9
    kv.free(a)
    a.num_computed_tokens = 0
    # payload not extracted yet -> not restorable
    assert not kv.parked_available(a)
    tiers.put(off.key, [])
    kv.note_park_extracted(off.key)
    kv.pending_offloads.clear()
    assert not kv.park_in_flight(a) and kv.parked_available(a)
    assert kv.restore_parked(a)
    assert a.num_computed_tokens == 9
    assert "_parked_len" not in a.additional_information
    assert kv.pending_restores[-1].drop_after


def test_restore_truncated_rewinds_and_frees():
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    b = _req("b", [9] * 9)
    kv.allocate(b, 9)
    _drain_offloads(kv, tiers)
    kv.free(b)
    c = _req("c", list(range(12)))
    assert kv.match_prefix(c) == 8
    # drain finds the cold payload gone: keep the hot 4-token prefix
    kv.restore_truncated(c, 4)
    assert c.num_computed_tokens == 4
    assert len(kv.block_table("c")) == 1
    kv.free(c)
    _assert_no_leaks(kv)


def test_restore_failure_unwinds_node_off_garbage_page():
    """A cold node whose payload vanished between match and drain must
    NOT stay bound to its (never-injected, garbage) HBM page — a later
    match would adopt uninitialized KV.  The unwind marks it cold
    again; the has() check then prunes it for good."""
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    kv.allocate(_req("b", [9] * 9), 9)      # evicts 2 nodes cold
    _drain_offloads(kv, tiers)
    kv.free(_req("b", [9] * 9))
    c = _req("c", list(range(12)))
    assert kv.match_prefix(c) == 8
    entry = kv.pending_restores[0]
    node = entry.nodes[0]
    assert node.page is not None            # rebound, awaiting inject
    tiers.drop(entry.key)                   # payload vanishes pre-drain
    kv.restore_failed_entries(c, [entry], entry.start_tokens)
    assert node.page is None, "failed node left on a garbage page"
    assert c.num_computed_tokens == entry.start_tokens
    # the garbage page went back to the pool, not leaked
    kv.free(c)
    _assert_no_leaks(kv)
    # and a later match no longer trusts the lost payload
    d = _req("d", list(range(12)))
    assert kv.match_prefix(d) == entry.start_tokens


def test_restore_failure_unwinds_coadopter_off_shared_garbage_page():
    """Two requests admitted in one pass can share a failing restore:
    the first match rebinds the cold node to a fresh page and queues
    the restore; the second sees the node hot and adopts it with NO
    restore entry.  When the fetch fails, BOTH must unwind — and the
    shared garbage page must be freed exactly once, by whichever
    truncation runs last (never while the other table still holds it)."""
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    kv.allocate(_req("b", [9] * 9), 9)      # evicts 2 nodes cold
    _drain_offloads(kv, tiers)
    kv.free(_req("b", [9] * 9))
    c = _req("c", list(range(12)))
    d = _req("d", list(range(12)))
    assert kv.match_prefix(c) == 8          # rebinds the cold node
    assert kv.match_prefix(d) == 8          # co-adopts it HOT
    assert len(kv.pending_restores) == 1, "d must not queue a restore"
    entry = kv.pending_restores[0]
    garbage = entry.nodes[0].page
    assert garbage in kv.block_table("c") and garbage in kv.block_table("d")
    tiers.drop(entry.key)                   # payload vanishes pre-drain
    co = kv.restore_failed_entries(c, [entry], entry.start_tokens)
    assert co == {"d": entry.start_tokens}, \
        "co-adopter must be reported for unwinding"
    # c truncated; the garbage page is still in d's table -> NOT freed
    assert garbage not in kv._free
    assert garbage in kv.block_table("d")
    kv.restore_truncated(d, co["d"])
    assert d.num_computed_tokens == entry.start_tokens
    assert garbage in kv._free              # freed exactly once, now
    assert kv._free.count(garbage) == 1
    kv.free(c)
    kv.free(d)
    _assert_no_leaks(kv)


def test_allocate_failure_is_side_effect_free():
    """A failed allocate must not register a stale (empty or partial)
    table entry: match_prefix treats ANY registered table as already
    matched, so the stale entry would permanently disable prefix
    adoption for that request — it would recompute its whole prompt
    even with its prefix sitting hot in the index."""
    kv = KVCacheManager(num_pages=4, page_size=4)
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)                              # 3 cached nodes + 1 free
    big = _req("big", list(range(12)) + [99] * 8)
    assert kv.allocate(big, 20) is None     # needs 5 pages, pool has 4
    assert "big" not in kv._tables, "stale empty table entry"
    assert kv.match_prefix(big) == 12       # prefix adoption still works
    kv.free(big)
    _assert_no_leaks(kv)
    # partial growth rolls back: force a mid-loop page-source failure
    # (num_free_pages said yes, the pool then came up short)
    kv2 = KVCacheManager(num_pages=4, page_size=4,
                         enable_prefix_caching=False)
    taken = []
    orig_take = kv2._take_free_page

    def flaky_take():
        if len(taken) >= 2:
            return None
        page = orig_take()
        taken.append(page)
        return page

    kv2._take_free_page = flaky_take
    c = _req("c", list(range(12)))
    free_before = sorted(kv2._free)
    assert kv2.allocate(c, 12) is None      # takes 2 pages, 3rd fails
    assert len(taken) == 2
    assert "c" not in kv2._tables
    assert sorted(kv2._free) == free_before, "partial growth leaked"


def test_reset_prefix_cache_purges_cold_tiers():
    kv, tiers = _offload_kv()
    a = _req("a", list(range(12)))
    kv.allocate(a, 12)
    a.num_computed_tokens = 12
    kv.free(a)
    kv.allocate(_req("b", [9] * 9), 9)      # evicts two nodes cold
    offs = list(kv.pending_offloads)
    _drain_offloads(kv, tiers)
    assert all(tiers.has(o.key) for o in offs)
    kv.reset_prefix_cache()
    assert not any(tiers.has(o.key) for o in offs), \
        "cold payloads must be purged"
    assert kv.index.hbm_pages() == 0
