"""Automatic prefix caching (vLLM-core APC semantics over the paged
pool): content-addressed page reuse must be token-identical to cold
prefill through the real engine, shared pages must refcount across
concurrent tables, and cached pages must evict under pressure without
shrinking capacity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.request import Request
from vllm_omni_tpu.sampling_params import SamplingParams


def _req(rid, ids, **kw):
    return Request(request_id=rid, prompt_token_ids=list(ids),
                   sampling_params=SamplingParams(**kw))


# --------------------------------------------------------- manager unit
def test_match_requires_producer_free():
    kv = KVCacheManager(num_pages=16, page_size=4)
    a = _req("a", range(1, 11))
    assert kv.match_prefix(a) == 0          # cold cache
    kv.allocate(a, 10)
    a.num_computed_tokens = 10
    b = _req("b", range(1, 11))
    assert kv.match_prefix(b) == 0          # producer still live
    kv.free(a)
    c = _req("c", range(1, 11))
    assert kv.match_prefix(c) == 8          # 2 full pages of 4
    assert c.num_computed_tokens == 8
    assert len(kv.block_table("c")) == 2


def test_shared_pages_refcount_across_tables():
    kv = KVCacheManager(num_pages=16, page_size=4)
    a = _req("a", range(1, 11))
    kv.allocate(a, 10); a.num_computed_tokens = 10
    kv.free(a)
    b = _req("b", range(1, 11))
    c = _req("c", range(1, 11))
    assert kv.match_prefix(b) == 8
    assert kv.match_prefix(c) == 8
    assert kv.block_table("b")[:2] == kv.block_table("c")[:2]
    # shared pages are not evictable while referenced
    free_before = kv.num_free_pages
    kv.free(b)
    kv.free(c)
    # after both release, the cached pages are evictable again
    assert kv.num_free_pages >= free_before


def test_divergent_prompt_matches_only_common_prefix():
    kv = KVCacheManager(num_pages=16, page_size=4)
    a = _req("a", [1, 2, 3, 4, 5, 6, 7, 8, 9])
    kv.allocate(a, 9); a.num_computed_tokens = 9
    kv.free(a)
    # same first page, different second page
    b = _req("b", [1, 2, 3, 4, 99, 98, 97, 96, 95])
    assert kv.match_prefix(b) == 4


def test_cached_pages_evict_under_pressure():
    kv = KVCacheManager(num_pages=4, page_size=4)
    a = _req("a", range(1, 17))          # fills all 4 pages
    kv.allocate(a, 16); a.num_computed_tokens = 16
    kv.free(a)
    assert kv.num_free_pages == 4        # cached but allocatable
    # a new unrelated request takes every page — cache evicts silently
    b = _req("b", range(100, 116))
    table = kv.allocate(b, 16)
    assert table is not None and len(table) == 4
    # the old prefix is gone now
    c = _req("c", range(1, 17))
    b.num_computed_tokens = 16
    kv.free(b)
    # b's pages registered for ITS prompt; a's hashes were evicted
    assert kv.match_prefix(c) == 0


def test_embeds_prompts_never_match():
    kv = KVCacheManager(num_pages=16, page_size=4)
    a = _req("a", range(1, 11))
    a.prompt_embeds = np.zeros((10, 8), np.float32)
    kv.allocate(a, 10); a.num_computed_tokens = 10
    kv.free(a)
    b = _req("b", range(1, 11))
    b.prompt_embeds = np.zeros((10, 8), np.float32)
    assert kv.match_prefix(b) == 0


# ------------------------------------------------------------ engine e2e
def test_cache_hit_is_token_identical():
    """The hot path (cached prefix + chunked continuation) must produce
    the same tokens as the cold path, and different prompts must not
    cross-contaminate."""
    from vllm_omni_tpu.engine.llm_engine import EngineConfig, LLMEngine
    from vllm_omni_tpu.models.common import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(vocab_size=128)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)

    def run(engine, rid, ids):
        outs = engine.generate(
            [list(ids)], SamplingParams(temperature=0.0, max_tokens=6))
        return outs[0].outputs[0].token_ids

    prompt = list(range(1, 40))          # several full pages
    other = list(range(50, 89))

    cold = LLMEngine(params, cfg, EngineConfig(
        num_pages=64, page_size=8, enable_prefix_caching=False))
    want = run(cold, "w", prompt)
    want_other = run(cold, "x", other)

    hot = LLMEngine(params, cfg, EngineConfig(
        num_pages=64, page_size=8, enable_prefix_caching=True))
    first = run(hot, "a", prompt)        # cold fill, registers pages
    assert first == want
    assert hot.scheduler.kv.prefix_hit_tokens == 0
    second = run(hot, "b", prompt)       # cache hit
    assert second == want
    assert hot.scheduler.kv.prefix_hit_tokens > 0
    # unrelated prompt: no contamination from the cached pages
    assert run(hot, "c", other) == want_other
    # shared-prefix-divergent-tail prompt reuses only the common pages
    variant = prompt[:16] + [101, 102, 103]
    v_cold = run(cold, "y", variant)
    assert run(hot, "d", variant) == v_cold


def test_pinned_shared_page_survives_until_ack():
    """A transfer-pinned shared cache page must not become evictable (a
    new allocation would overwrite KV mid-transfer) and must release
    exactly once at ACK (code-review scenario)."""
    kv = KVCacheManager(num_pages=4, page_size=4)
    a = _req("a", range(1, 9))
    kv.allocate(a, 8); a.num_computed_tokens = 8
    kv.free(a)                            # 2 pages registered
    b = _req("b", range(1, 9))
    assert kv.match_prefix(b) == 4        # adopts page 0 (7 usable)
    shared = kv.block_table("b")[0]
    kv.pin_for_transfer(b, 4)             # pin the shared page
    kv.free(b)                            # producer gone, ref -> 0
    # pinned page must NOT be allocatable: exhaust everything else
    grabber = _req("g", range(100, 116))
    t = kv.allocate(grabber, 12)          # 3 pages max available
    assert t is not None and shared not in t
    assert not kv.can_allocate(_req("h", [1]), 1)
    # ACK releases it (back to evictable — allocatable again)
    kv.ack_transfer("b")
    assert kv.can_allocate(_req("h", [1]), 1)
    h = _req("h", [1, 2])
    th = kv.allocate(h, 2)
    assert th == [shared]


@pytest.mark.slow  # two-engine stage pipeline; APC logic covered by the token-identical test
def test_stats_summary_reports_cache_hits():
    from vllm_omni_tpu.entrypoints.omni import Omni

    omni = Omni(model="qwen3-tts-tiny")
    prompt = list(range(1, 40))
    omni.generate([prompt], [{"temperature": 0.0, "max_tokens": 4}])
    omni.generate([prompt], [{"temperature": 0.0, "max_tokens": 4}])
    summ = omni.stats_summary()
    pc = summ["stages"][0].get("prefix_cache")
    assert pc is not None and pc["hit_tokens"] > 0
