"""Weighted-fair overload scheduling (docs/control_plane.md):
deficit-round-robin ordering hand-oracle, starvation freedom,
priority-ordered shedding, and the deferral ledger — pure host-side
scheduler math, no jax compute.
"""

import pytest

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.core.scheduler import ARScheduler, SchedulerConfig
from vllm_omni_tpu.metrics.stats import (
    DEFAULT_PRIORITY,
    MAX_PRIORITY,
    MIN_PRIORITY,
    sanitize_priority,
)
from vllm_omni_tpu.request import Request, RequestStatus
from vllm_omni_tpu.sampling_params import SamplingParams


def _sched(**kw):
    kw.setdefault("max_num_seqs", 8)
    kw.setdefault("max_num_batched_tokens", 64)
    kw.setdefault("wfq_scheduling", True)
    kw.setdefault("wfq_quantum_tokens", 8)
    cfg = SchedulerConfig(**kw)
    return ARScheduler(cfg, KVCacheManager(256, 16))


def _req(rid, tenant, priority=None, n_prompt=8, max_tokens=4):
    info = {"tenant": tenant}
    if priority is not None:
        info["priority"] = priority
    return Request(request_id=rid,
                   prompt_token_ids=list(range(1, n_prompt + 1)),
                   sampling_params=SamplingParams(max_tokens=max_tokens),
                   additional_information=info)


# ------------------------------------------------------- sanitization
def test_sanitize_priority_hostile_input():
    assert sanitize_priority(None) == DEFAULT_PRIORITY
    assert sanitize_priority("") == DEFAULT_PRIORITY
    assert sanitize_priority("banana") == DEFAULT_PRIORITY
    assert sanitize_priority(object()) == DEFAULT_PRIORITY
    assert sanitize_priority(10**9) == MAX_PRIORITY
    assert sanitize_priority(-10**9) == MIN_PRIORITY
    assert sanitize_priority("6") == 6
    assert sanitize_priority(" 2.9 ") == 2
    assert sanitize_priority(float("nan")) == DEFAULT_PRIORITY
    assert sanitize_priority("nan") == DEFAULT_PRIORITY
    # regression: "inf" parses as a float and int(inf) raises
    # OverflowError — one hostile header must clamp, never crash the
    # scheduler for every tenant
    assert sanitize_priority("inf") == MAX_PRIORITY
    assert sanitize_priority("-inf") == MIN_PRIORITY
    assert sanitize_priority("1e400") == MAX_PRIORITY
    assert sanitize_priority(float("inf")) == MAX_PRIORITY


def test_request_priority_property_defaults_neutral():
    assert _req("r", "t").priority == DEFAULT_PRIORITY
    assert _req("r", "t", priority="7").priority == 7
    assert _req("r", "t", priority="evil\n").priority == DEFAULT_PRIORITY


# ------------------------------------------------------- DRR ordering
def test_drr_hand_oracle():
    """quantum 8, costs 8: a weight-8 tenant drains its whole queue in
    round one (deficit 64); the weight-1 tenant places exactly one
    request per round and is deferred in each round it waits."""
    s = _sched()
    for i in range(4):
        s.add_request(_req(f"a{i}", "alpha", 8))
        s.add_request(_req(f"b{i}", "beta", 1))
    s._wfq_order()
    assert [r.request_id for r in s.waiting] == \
        ["a0", "a1", "a2", "a3", "b0", "b1", "b2", "b3"]
    # beta held in rounds 1-3 (placed b0..b2 one per round, b3 ends
    # its queue so round 4 holds nothing)
    assert s.wfq_deferred == {"beta": 3}


def test_equal_weights_interleave_round_robin():
    s = _sched()
    for i in range(3):
        s.add_request(_req(f"a{i}", "alpha", 1))
        s.add_request(_req(f"b{i}", "beta", 1))
    s._wfq_order()
    order = [r.request_id for r in s.waiting]
    # equal weights, quantum == cost: one request per tenant per round
    # — strict alternation, FIFO within each tenant
    assert order == ["a0", "b0", "a1", "b1", "a2", "b2"]
    assert s.wfq_deferred == {"alpha": 2, "beta": 2}


def test_neutral_default_drains_whole_queues_per_round():
    """No client priorities at all: the neutral weight's quantum
    (8 x 4 = 32 tokens) covers each tenant's queue in one visit, so
    ordering degenerates to per-tenant FIFO blocks with no deferrals."""
    s = _sched()
    for i in range(3):
        s.add_request(_req(f"a{i}", "alpha"))
        s.add_request(_req(f"b{i}", "beta"))
    s._wfq_order()
    assert [r.request_id for r in s.waiting] == \
        ["a0", "a1", "a2", "b0", "b1", "b2"]
    assert s.wfq_deferred == {}


def test_wfq_off_keeps_strict_arrival_order():
    s = _sched(wfq_scheduling=False)
    ids = []
    for i in range(3):
        s.add_request(_req(f"a{i}", "alpha", 1))
        s.add_request(_req(f"b{i}", "beta", 8))
        ids += [f"a{i}", f"b{i}"]
    s.schedule()
    # everything admitted in arrival order (budget covers all)
    assert [r.request_id for r in s.running] == ids
    assert s.wfq_deferred == {}


def test_single_tenant_is_fifo_even_with_wfq_on():
    s = _sched()
    for i in range(4):
        s.add_request(_req(f"r{i}", "alpha", (i % 2) * 7 + 1))
    before = [r.request_id for r in s.waiting]
    s._wfq_order()
    assert [r.request_id for r in s.waiting] == before


def test_resuming_requests_keep_the_queue_head():
    s = _sched()
    s.add_request(_req("fresh-hi", "alpha", 8))
    victim = _req("victim", "beta", 1)
    s.add_request(victim)
    # simulate a preemption re-insert: progress + front position
    s.waiting.remove(victim)
    victim.status = RequestStatus.PREEMPTED
    s.waiting.insert(0, victim)
    s._wfq_order()
    assert s.waiting[0] is victim, \
        "a preemption victim must never rot behind fresh arrivals"


def test_admission_follows_wfq_order_under_seat_pressure():
    # quantum 2 < cost 8: the weight-1 tenant needs 4 rounds per
    # request while weight-8 covers one per round — arrival order
    # (beta first) loses to weight under contention
    s = _sched(max_num_seqs=2, max_num_batched_tokens=16,
               wfq_quantum_tokens=2)
    s.add_request(_req("b0", "beta", 1))
    s.add_request(_req("a0", "alpha", 8))
    s.add_request(_req("a1", "alpha", 8))
    out = s.schedule()
    scheduled = [x.request.request_id for x in out.prefills]
    assert scheduled == ["a0", "a1"], \
        "the weight-8 tenant owns the contended seats"
    assert s.wfq_deferred.get("beta", 0) >= 1


def test_starvation_freedom():
    """Every admitted tenant makes progress: with weights 8:1 and one
    seat, the weight-1 tenant still finishes work in bounded rounds."""
    s = _sched(max_num_seqs=1, max_num_batched_tokens=8)
    for i in range(6):
        s.add_request(_req(f"a{i}", "alpha", 8, max_tokens=1))
        s.add_request(_req(f"b{i}", "beta", 1, max_tokens=1))
    finished = []
    for _ in range(60):
        out = s.schedule()
        for sched in out.prefills + out.decodes:
            req = sched.request
            req.num_computed_tokens += sched.num_new_tokens
            req.status = RequestStatus.FINISHED_STOPPED
            finished.append(req.request_id)
            s.running.remove(req)
            s._free_request(req)
        if not s.has_unfinished:
            break
    assert not s.has_unfinished, "WFQ must drain the whole queue"
    beta_done = [f for f in finished if f.startswith("b")]
    assert len(beta_done) == 6, "low priority must progress, not starve"
    # ...but the weight-8 tenant finished its work strictly earlier
    assert finished.index("a5") < finished.index("b5")
    assert s.wfq_deferred.get("beta", 0) > 0


# ------------------------------------------------- priority-ordered shed
def test_full_queue_sheds_lowest_priority_not_arrival():
    s = _sched(max_queue_depth=3)
    s.add_request(_req("lo0", "beta", 1))
    s.add_request(_req("hi0", "alpha", 8))
    s.add_request(_req("lo1", "beta", 1))
    # queue full; a priority-8 arrival displaces the NEWEST priority-1
    s.add_request(_req("hi1", "alpha", 8))
    ids = [r.request_id for r in s.waiting]
    assert ids == ["lo0", "hi0", "hi1"]
    assert s.shed_counts == {("queue_depth", "beta"): 1}
    shed = s.drain_errored()
    assert [r.request_id for r in shed] == ["lo1"]
    assert shed[0].additional_information["error_kind"] == "shed"


def test_equal_priority_arrival_is_shed_fcfs():
    s = _sched(max_queue_depth=2)
    s.add_request(_req("r0", "alpha", 4))
    s.add_request(_req("r1", "beta", 4))
    s.add_request(_req("r2", "alpha", 4))
    assert [r.request_id for r in s.waiting] == ["r0", "r1"]
    assert s.shed_counts == {("queue_depth", "alpha"): 1}


def test_progressed_requests_are_never_displaced():
    s = _sched(max_queue_depth=2)
    parked = _req("parked", "beta", 1)
    s.add_request(parked)
    parked.num_computed_tokens = 4     # restored/preempted progress
    s.add_request(_req("lo", "beta", 1))
    s.add_request(_req("hi", "alpha", 8))
    ids = [r.request_id for r in s.waiting]
    assert "parked" in ids and "hi" in ids and "lo" not in ids


def test_preemption_victims_are_never_displaced():
    """Regression: _preempt RESETS num_computed_tokens to 0, so a
    preemption victim (with streamed output the client already saw)
    must be recognized by STATUS/output, not progress — shedding it
    would abort a live partially-streamed response."""
    s = _sched(max_queue_depth=2)
    victim = _req("victim", "beta", 1)
    s.add_request(victim)
    # simulate _preempt's re-insert: output exists, progress reset
    victim.append_output_token(5)
    victim.num_computed_tokens = 0
    victim.status = RequestStatus.PREEMPTED
    s.add_request(_req("hi", "alpha", 8))
    s.add_request(_req("hi2", "alpha", 8))   # queue full at 2
    ids = [r.request_id for r in s.waiting]
    assert "victim" in ids, \
        "a preemption victim must never be the priority-shed target"
    assert s.shed_counts.get(("queue_depth", "alpha")) == 1


def test_wfq_shed_off_without_flag():
    s = _sched(wfq_scheduling=False, max_queue_depth=1)
    s.add_request(_req("lo", "beta", 1))
    s.add_request(_req("hi", "alpha", 8))
    assert [r.request_id for r in s.waiting] == ["lo"], \
        "without WFQ the classic FCFS shed stands"


# ------------------------------------------------------------- metrics
def test_deferred_ledger_caps_tenant_cardinality():
    from vllm_omni_tpu.metrics.stats import MAX_TENANT_SERIES

    s = _sched()
    # more tenants than the cardinality cap, one request each, plus a
    # heavy competitor so every round defers someone
    for i in range(MAX_TENANT_SERIES + 8):
        s.add_request(_req(f"t{i}", f"tenant{i}", 1, n_prompt=32))
    s.add_request(_req("big", "whale", 8, n_prompt=8))
    for _ in range(4):
        s._wfq_order()
    assert len(s.wfq_deferred) <= MAX_TENANT_SERIES + 1


def test_deferred_counts_render_on_metrics():
    from vllm_omni_tpu.metrics.prometheus import (
        render_exposition,
        validate_exposition,
    )

    snap = {"wfq": {"deferred_by_tenant": {"alpha": 0, "beta": 3}}}
    text = render_exposition({}, {0: snap})
    assert ('vllm_omni_tpu_wfq_deferred_requests_total'
            '{stage="0",tenant="beta"} 3') in text
    assert validate_exposition(text) == []
