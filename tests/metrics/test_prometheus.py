"""Prometheus exposition: rendering, parsing, and the metric-surface
guard (mirror of scripts/check_metrics_names.py, so CI catches drift
even when nobody runs the script)."""

import importlib.util
import os
import re

from vllm_omni_tpu.metrics.prometheus import (
    METRIC_PREFIX,
    METRIC_SPECS,
    NAME_RE,
    render_exposition,
    validate_exposition,
    validate_specs,
)


def _load_check_script():
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "scripts", "check_metrics_names.py")
    spec = importlib.util.spec_from_file_location("check_metrics_names",
                                                  os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_spec_name_matches_naming_rule():
    for name in METRIC_SPECS:
        assert NAME_RE.fullmatch(METRIC_PREFIX + name), name
        # the rule bans digits — "e2e"-style names must not creep in
        assert not re.search(r"\d", name), name
    assert validate_specs() == []


def test_check_script_passes():
    mod = _load_check_script()
    assert mod.run_check() == []
    assert mod.main() == 0


def test_render_covers_required_series():
    mod = _load_check_script()
    text = render_exposition(mod.synthetic_summary(),
                             {0: mod.synthetic_engine_snapshot()},
                             device={"hbm_bytes": 123})
    assert validate_exposition(text) == []
    for needle in (
        'vllm_omni_tpu_ttft_ms_bucket{stage="0",le="+Inf"} 3',
        'vllm_omni_tpu_tpot_ms_sum{stage="0"} 123',
        'vllm_omni_tpu_itl_ms_count{stage="0"} 3',
        'vllm_omni_tpu_scheduler_waiting{stage="0"} 1',
        'vllm_omni_tpu_engine_step_host_ms_count{stage="0"} 3',
        'vllm_omni_tpu_engine_step_device_ms_count{stage="0"} 3',
        'vllm_omni_tpu_engine_step_overlap_ratio{stage="0"} 0.75',
        'vllm_omni_tpu_kv_page_utilization{stage="0"} 0.125',
        'vllm_omni_tpu_request_latency_ms{quantile="0.5"} 101',
        'vllm_omni_tpu_transfer_bytes_total{from_stage="0",to_stage="1"} 4096',
        'vllm_omni_tpu_prefix_cache_hits_total{stage="0"} 2',
        "vllm_omni_tpu_hbm_bytes 123",
    ):
        assert needle in text, f"missing series: {needle}\n{text}"
    # HELP/TYPE headers present exactly once per metric
    assert text.count("# TYPE vllm_omni_tpu_ttft_ms histogram") == 1


def test_validate_rejects_undeclared_and_unlabeled():
    clean = 'vllm_omni_tpu_scheduler_waiting{stage="0"} 1\n'
    assert validate_exposition(clean) == []
    # undeclared metric name
    errs = validate_exposition("vllm_omni_tpu_rogue_metric 1\n")
    assert errs and "not declared" in errs[0]
    # declared metric missing its required stage label
    errs = validate_exposition("vllm_omni_tpu_scheduler_waiting 1\n")
    assert errs and "missing required label 'stage'" in errs[0]
    # wrong prefix
    errs = validate_exposition("other_scheduler_waiting 1\n")
    assert errs and "prefix" in errs[0]
