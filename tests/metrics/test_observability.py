"""Observability (VERDICT r1 next-step #10): jax.profiler fan-out,
per-stage stats.jsonl, and the online serving bench."""

import glob
import json
import os
import threading

import pytest

from vllm_omni_tpu.config.stage import StageConfig


def _llm_stage(stage_id=0, sources=None, final=True):
    return StageConfig(
        stage_id=stage_id,
        stage_type="llm",
        engine_args={
            "model_factory": "tests.helpers:tiny_lm_factory",
            "num_pages": 64, "page_size": 4, "max_model_len": 128,
        },
        engine_input_source=sources if sources is not None else [-1],
        final_output=final,
        final_output_type="text",
        default_sampling_params={"temperature": 0.0, "max_tokens": 4},
    )


# -------------------------------------------------------------- profiler
def test_profiler_fanout_writes_xplane_trace(tmp_path):
    from vllm_omni_tpu.entrypoints.omni import Omni

    omni = Omni(stage_configs=[_llm_stage()])
    trace_dir = str(tmp_path / "traces")
    omni.start_profile(trace_dir)
    omni.generate([[1, 2, 3]])
    omni.stop_profile()
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the dir
    found = glob.glob(
        os.path.join(trace_dir, "stage_0", "**", "*.xplane.pb"),
        recursive=True)
    assert found, f"no xplane trace under {trace_dir}"


def test_profiler_single_process_owner(tmp_path):
    """Two in-proc stages share one process: only one jax trace runs and
    stop/start sequencing stays consistent."""
    from vllm_omni_tpu.entrypoints.omni import Omni

    cfgs = [_llm_stage(0, sources=[-1], final=False),
            _llm_stage(1, sources=[0], final=True)]
    omni = Omni(stage_configs=cfgs)
    d = str(tmp_path / "t2")
    omni.start_profile(d)   # second stage start must be a harmless no-op
    omni.generate([[1, 2, 3]])
    omni.stop_profile()
    assert glob.glob(os.path.join(d, "stage_0", "**", "*.xplane.pb"),
                     recursive=True)
    # and a second full cycle works (owner released)
    omni.start_profile(d + "b")
    omni.stop_profile()


# ------------------------------------------------------ per-stage jsonl
def test_per_stage_stats_jsonl(tmp_path):
    from vllm_omni_tpu.entrypoints.omni import Omni

    prefix = str(tmp_path / "run1")
    cfgs = [_llm_stage(0, sources=[-1], final=False),
            _llm_stage(1, sources=[0], final=True)]
    omni = Omni(stage_configs=cfgs, stats_path=prefix)
    omni.generate([[1, 2, 3], [4, 5]])

    for sid in (0, 1):
        path = f"{prefix}.stage{sid}.stats.jsonl"
        assert os.path.exists(path), path
        recs = [json.loads(l) for l in open(path)]
        assert len(recs) == 2
        assert {r["stage_id"] for r in recs} == {sid}
        assert all(r["tokens_out"] == 4 for r in recs)
    e2e = [json.loads(l) for l in open(f"{prefix}.e2e.stats.jsonl")]
    assert len(e2e) == 2 and all(r["e2e_ms"] >= 0 for r in e2e)


# -------------------------------------------------------- serving bench
@pytest.fixture(scope="module")
def bench_server_url():
    from vllm_omni_tpu.entrypoints.openai.api_server import build_server

    server, state = build_server(
        model="bench-tiny", stage_configs=[_llm_stage()],
        host="127.0.0.1", port=0,
    )
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    server.shutdown()
    state.shutdown()


def test_serving_bench_chat_percentiles(bench_server_url):
    from vllm_omni_tpu.benchmarks.serving import run_bench

    report = run_bench(
        bench_server_url, endpoint="chat", num_requests=6, concurrency=2,
        max_tokens=4, stream=True,
    )
    assert report["num_requests"] == 6
    assert report["num_errors"] == 0
    assert report["requests_per_s"] > 0
    assert report["e2e_ms"]["p50"] > 0
    assert report["e2e_ms"]["p99"] >= report["e2e_ms"]["p50"]
    # streaming gives TTFT
    assert report["ttft_ms"]["p50"] > 0
    assert report["ttft_ms"]["p50"] <= report["e2e_ms"]["p99"]


def test_serving_bench_nonstream(bench_server_url):
    from vllm_omni_tpu.benchmarks.serving import run_bench

    report = run_bench(
        bench_server_url, endpoint="chat", num_requests=3, concurrency=3,
        max_tokens=4, stream=False,
    )
    assert report["num_errors"] == 0 and "ttft_ms" not in report


def test_serving_bench_cli(bench_server_url, capsys):
    from vllm_omni_tpu.entrypoints.cli.main import main

    rc = main(["bench-serve", "--base-url", bench_server_url,
               "--num-requests", "2", "--concurrency", "1",
               "--max-tokens", "3"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["num_requests"] == 2 and report["num_errors"] == 0


def test_async_engine_stats_heartbeat():
    """The serving engine loop harvests per-stage stats continuously
    (reference: do_log_stats keep-alive) — /metrics shows stage counters
    without waiting for an offline generate() to finish."""
    import time

    from vllm_omni_tpu.entrypoints.async_omni import AsyncOmni

    omni = AsyncOmni(stage_configs=[_llm_stage()])
    omni._stats_interval = 0.2
    try:
        import asyncio

        async def run():
            outs = []
            async for o in omni.generate([1, 2, 3], {"max_tokens": 4}):
                outs.append(o)
            return outs

        loop = asyncio.new_event_loop()
        outs = loop.run_until_complete(run())
        assert outs and not outs[0].is_error
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if omni.metrics.summary()["stages"][0]["num_requests"] >= 1:
                break
            time.sleep(0.1)
        assert omni.metrics.summary()["stages"][0]["num_requests"] >= 1
        loop.close()
    finally:
        omni.shutdown()
