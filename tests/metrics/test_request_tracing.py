"""Per-request distributed tracing end-to-end: one trace id spanning a
two-stage pipeline (in-proc and cross-process), Perfetto-loadable
trace-event JSON output, and the Prometheus /metrics scrape surface."""

import json
import threading

import httpx
import pytest

from vllm_omni_tpu.config.stage import StageConfig, StageRuntime

# cross-process children must never grab a real accelerator
_CPU_ENV = {"JAX_PLATFORMS": "cpu", "OMNI_TPU_PALLAS_INTERPRET": "1"}


def _llm_stage(stage_id=0, sources=None, final=True, process=False,
               connectors=None):
    return StageConfig(
        stage_id=stage_id,
        stage_type="llm",
        runtime=StageRuntime(process=process,
                             device_env=dict(_CPU_ENV)),
        engine_args={
            "model_factory": "tests.helpers:tiny_lm_factory",
            "num_pages": 64, "page_size": 4, "max_model_len": 128,
        },
        engine_input_source=sources if sources is not None else [-1],
        final_output=final,
        final_output_type="text",
        default_sampling_params={"temperature": 0.0, "max_tokens": 4},
        output_connectors=connectors or {},
    )


def _load_trace(prefix):
    doc = json.load(open(f"{prefix}.trace.json"))
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


# --------------------------------------------------------------- in-proc
def test_two_stage_trace_single_id_spans_both_stages(tmp_path):
    from vllm_omni_tpu.entrypoints.omni import Omni

    prefix = str(tmp_path / "run")
    cfgs = [_llm_stage(0, sources=[-1], final=False),
            _llm_stage(1, sources=[0], final=True)]
    omni = Omni(stage_configs=cfgs, trace_path=prefix)
    outs = omni.generate([[1, 2, 3], [4, 5]])
    assert len(outs) == 2 and not any(o.is_error for o in outs)

    events = _load_trace(prefix)
    trace_ids = {e["args"]["trace_id"] for e in events}
    assert len(trace_ids) == 2  # one per request
    for rid in ("omni-0", "omni-1"):
        evs = [e for e in events if e["args"]["request_id"] == rid]
        # each request carries exactly ONE trace id across the pipeline
        assert len({e["args"]["trace_id"] for e in evs}) == 1
        names = {e["name"] for e in evs}
        assert {"queue_wait", "prefill", "decode", "sampling",
                "transfer", "stage", "request"} <= names
        # spans from BOTH stages (pid = stage_id + 1) plus the
        # orchestrator's whole-lifetime request span (pid 0)
        assert {0, 1, 2} <= {e["pid"] for e in evs}
        # the decode span records its window
        dec = next(e for e in evs if e["name"] == "decode")
        assert dec["args"]["window"] >= 1
    # JSONL rides alongside (same spans, one per line)
    lines = open(f"{prefix}.trace.jsonl").read().splitlines()
    assert len(lines) == len(events)
    assert all("trace_id" in json.loads(l) for l in lines)


def test_trace_disabled_writes_nothing(tmp_path):
    from vllm_omni_tpu.entrypoints.omni import Omni
    from vllm_omni_tpu.tracing import get_recorder

    omni = Omni(stage_configs=[_llm_stage()])
    get_recorder().drain()
    outs = omni.generate([[1, 2, 3]])
    assert outs and not outs[0].is_error
    # no trace context -> no spans recorded anywhere
    assert len(get_recorder()) == 0


def test_transfer_span_records_bytes_with_connector(tmp_path, monkeypatch):
    """A serialized connector edge attributes bytes + encode/decode time
    to the request's transfer span."""
    from vllm_omni_tpu.entrypoints.omni import Omni

    monkeypatch.setenv("OMNI_TPU_FORCE_CONNECTOR_SERIALIZATION", "1")
    prefix = str(tmp_path / "conn")
    cfgs = [_llm_stage(0, sources=[-1], final=False,
                       connectors={"1": {"connector": "inproc"}}),
            _llm_stage(1, sources=[0], final=True)]
    omni = Omni(stage_configs=cfgs, trace_path=prefix)
    omni.generate([[1, 2, 3]])
    events = _load_trace(prefix)
    transfers = [e for e in events if e["name"] == "transfer"]
    assert transfers and all(e["args"]["edge"] == "0->1"
                             for e in transfers)
    assert any(e["args"]["bytes"] > 0 for e in transfers)
    # the aggregator saw the same edge
    assert omni.metrics.summary()["edges"]["0->1"]["bytes"] > 0


# --------------------------------------------------------- cross-process
def test_cross_process_stage_carries_same_trace_id(tmp_path):
    """stage 1 runs in a spawned worker process: its engine spans ship
    back over the command channel and merge under the SAME trace id —
    the acceptance bar for disaggregated-stage tracing."""
    from vllm_omni_tpu.entrypoints.omni import Omni

    prefix = str(tmp_path / "xproc")
    cfgs = [_llm_stage(0, sources=[-1], final=False),
            _llm_stage(1, sources=[0], final=True, process=True)]
    omni = Omni(stage_configs=cfgs, trace_path=prefix)
    try:
        outs = omni.generate([[1, 2, 3]])
    finally:
        omni.shutdown()
    assert len(outs) == 1 and not outs[0].is_error

    events = _load_trace(prefix)
    assert len({e["args"]["trace_id"] for e in events}) == 1
    # engine spans recorded INSIDE the worker process (stage 1 = pid 2)
    worker_names = {e["name"] for e in events if e["pid"] == 2}
    assert {"queue_wait", "prefill", "decode"} <= worker_names
    # orchestrator-side spans cover the handoff + lifetime
    orch_names = {e["name"] for e in events if e["pid"] == 0}
    assert "request" in orch_names


# ------------------------------------------------------- /metrics scrape
@pytest.fixture(scope="module")
def metrics_server_url():
    from vllm_omni_tpu.entrypoints.openai.api_server import build_server

    server, state = build_server(
        model="metrics-tiny", stage_configs=[_llm_stage()],
        host="127.0.0.1", port=0,
    )
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    server.shutdown()
    state.shutdown()


def test_metrics_prometheus_scrape(metrics_server_url):
    from vllm_omni_tpu.metrics.prometheus import validate_exposition

    # generate traffic so the latency histograms are populated
    for _ in range(2):
        r = httpx.post(f"{metrics_server_url}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4, "temperature": 0,
        }, timeout=120)
        assert r.status_code == 200

    r = httpx.get(f"{metrics_server_url}/metrics", timeout=30)
    assert r.status_code == 200
    assert r.headers["content-type"].startswith("text/plain")
    text = r.text
    # parses clean against the declared metric surface
    assert validate_exposition(text) == []

    def value(needle):
        line = next(l for l in text.splitlines() if l.startswith(needle))
        return float(line.rsplit(" ", 1)[1])

    # TTFT/TPOT histograms populated by the traffic above
    assert value('vllm_omni_tpu_ttft_ms_count{stage="0"}') >= 2
    assert value('vllm_omni_tpu_tpot_ms_count{stage="0"}') >= 2
    assert value('vllm_omni_tpu_itl_ms_count{stage="0"}') >= 2
    assert value('vllm_omni_tpu_tokens_generated_total{stage="0"}') >= 8
    # scheduler queue depth + KV utilization gauges present
    assert 'vllm_omni_tpu_scheduler_waiting{stage="0"}' in text
    assert 'vllm_omni_tpu_scheduler_running{stage="0"}' in text
    assert value('vllm_omni_tpu_kv_pages_total{stage="0"}') == 64
    assert 'vllm_omni_tpu_kv_page_utilization{stage="0"}' in text
    assert value("vllm_omni_tpu_requests_finished_total") >= 2


def test_metrics_json_format_kept(metrics_server_url):
    r = httpx.get(f"{metrics_server_url}/metrics?format=json", timeout=30)
    assert r.status_code == 200
    body = r.json()
    assert "stages" in body and "e2e" in body and "device" in body
    # step-level engine snapshots ride the JSON face too
    assert "engines" in body
    assert "kv" in body["engines"]["0"] or "kv" in body["engines"].get(0, {})
