"""Live roofline attribution (metrics/roofline.py): geometry math,
tracker sanity, and the engine-wired gauges on a tiny live model."""

import jax
import jax.numpy as jnp
import pytest

from vllm_omni_tpu.metrics.roofline import (
    ModelGeometry,
    RooflineTracker,
    ctx_positions,
)
from vllm_omni_tpu.models.common import transformer as tfm


@pytest.fixture(scope="module")
def geometry():
    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    return ModelGeometry.from_transformer_config(cfg, dtype_bytes=4)


# ------------------------------------------------------------- geometry
def test_ctx_positions_causal_sum():
    # 4 tokens appended from position 0: 1+2+3+4 attended positions
    assert ctx_positions(0, 4) == 10.0
    # 1 decode token at position 8 attends over 9 positions
    assert ctx_positions(8, 1) == 9.0
    assert ctx_positions(5, 0) == 0.0


def test_geometry_costs_positive_and_scale(geometry):
    g = geometry
    assert g.flops_per_token > 0 and g.weight_bytes > 0
    assert g.kv_bytes_per_pos > 0
    f1 = g.step_flops(1, ctx_positions(8, 1), 1)
    f8 = g.step_flops(8, ctx_positions(0, 8), 1)
    assert f8 > f1, "more computed tokens must cost more FLOPs"
    assert g.step_bytes(8, ctx_positions(0, 8)) \
        > g.step_bytes(1, ctx_positions(8, 1)) - g.kv_bytes_per_pos * 8


def test_prefill_denser_than_decode(geometry):
    """The structural roofline ordering: a prefill-shaped step (many
    new tokens per dispatch) has strictly higher arithmetic intensity
    than a single-token decode step — weights are read once per
    dispatch either way, so FLOPs/byte grows with the token count.
    This is the geometry-level face of the prefill/decode MBU/MFU
    ordering; the live gauges inherit it modulo wall-clock noise."""
    g = geometry
    prefill = g.arithmetic_intensity(32, ctx_positions(0, 32), 1)
    decode = g.arithmetic_intensity(1, ctx_positions(32, 1), 1)
    assert prefill > decode
    # per-STEP achieved bytes: a prefill step moves at least as much
    # (same weight read + strictly more KV writes)
    assert g.step_bytes(32, ctx_positions(0, 32)) \
        >= g.step_bytes(1, ctx_positions(32, 1))


def test_moe_counts_active_params_only():
    dense = tfm.TransformerConfig.tiny(vocab_size=64)
    import dataclasses

    moe = dataclasses.replace(dense, moe=True, num_experts=8,
                              num_experts_per_tok=2)
    g_dense = ModelGeometry.from_transformer_config(dense, 4)
    g_moe = ModelGeometry.from_transformer_config(moe, 4)
    # 2 of 8 experts active: flops reflect the ROUTED cost, not 8x
    assert g_moe.flops_per_token < 4 * g_dense.flops_per_token


# -------------------------------------------------------------- tracker
def test_tracker_bounds_and_phase_split(geometry):
    t = RooflineTracker(geometry, peak_tflops=0.5, peak_gbps=50.0)
    # equal wall budget: the prefill-shaped step achieves >= the
    # decode step on both axes (strictly more work, same denominator)
    pre = t.on_step(prefill_tokens=32, prefill_ctx=ctx_positions(0, 32),
                    decode_tokens=0, decode_ctx=0.0, sampled_rows=1,
                    wall_s=0.01)
    dec = t.on_step(prefill_tokens=0, prefill_ctx=0.0, decode_tokens=1,
                    decode_ctx=ctx_positions(32, 1), sampled_rows=1,
                    wall_s=0.01)
    for r in (pre, dec):
        assert 0.0 < r["mfu"] <= 1.0
        assert 0.0 < r["mbu"] <= 1.0
    assert pre["phase"] == "prefill" and dec["phase"] == "decode"
    assert pre["mbu"] >= dec["mbu"]
    assert pre["mfu"] >= dec["mfu"]
    # a token-packed step carrying BOTH row kinds reports as "mixed" —
    # its (mostly decode) bytes must not bias the prefill gauge
    mix = t.on_step(prefill_tokens=8, prefill_ctx=ctx_positions(0, 8),
                    decode_tokens=3, decode_ctx=3 * 20.0,
                    sampled_rows=4, wall_s=0.01)
    assert mix["phase"] == "mixed"
    snap = t.snapshot()
    assert snap["window_steps"] == 3
    assert set(snap["mbu"]) == {"prefill", "decode", "mixed"}
    assert 0.0 < snap["mfu"] <= 1.0
    assert len(snap["recent"]) == 3
    assert t.snapshot(recent=0)["recent"] == [], \
        "recent=0 means NO per-step list, not the whole window"


def test_tracker_clamps_and_skips_degenerate(geometry):
    t = RooflineTracker(geometry, peak_tflops=1e-12, peak_gbps=1e-9)
    r = t.on_step(prefill_tokens=64, prefill_ctx=ctx_positions(0, 64),
                  decode_tokens=0, decode_ctx=0.0, sampled_rows=64,
                  wall_s=1e-6)
    assert r["mfu"] == 1.0 and r["mbu"] == 1.0, "clamped, never > 1"
    assert t.on_step(prefill_tokens=0, prefill_ctx=0, decode_tokens=0,
                     decode_ctx=0, sampled_rows=0, wall_s=0.01) is None
    assert t.on_step(prefill_tokens=1, prefill_ctx=1, decode_tokens=0,
                     decode_ctx=0, sampled_rows=1, wall_s=0.0) is None
    # unknown peaks (0.0): utilization reads 0, never a ZeroDivision
    t0 = RooflineTracker(geometry, peak_tflops=0.0, peak_gbps=0.0)
    r = t0.on_step(prefill_tokens=4, prefill_ctx=10.0, decode_tokens=0,
                   decode_ctx=0.0, sampled_rows=1, wall_s=0.01)
    assert r["mfu"] == 0.0 and r["mbu"] == 0.0


# ------------------------------------------------------- live engine e2e
def test_live_engine_gauges_render_and_bound():
    """MFU/MBU gauge sanity on a live tiny engine: both phases present,
    every value in (0, 1], the flight records carry the v3 fields, and
    the /metrics render is validate-clean with the new series."""
    from vllm_omni_tpu.engine import EngineConfig, LLMEngine
    from vllm_omni_tpu.metrics.prometheus import (
        render_exposition,
        validate_exposition,
    )
    from vllm_omni_tpu.sampling_params import SamplingParams

    cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    eng = LLMEngine(params, cfg, EngineConfig(
        num_pages=64, page_size=4, max_model_len=128, max_num_seqs=4,
        dtype=jnp.float32))
    eng.generate([[1, 2, 3, 4, 5, 6, 7, 8]] * 2,
                 SamplingParams(temperature=0.0, max_tokens=6))
    snap = eng.metrics_snapshot()
    rf = snap["roofline"]
    assert 0.0 < rf["mfu"] <= 1.0
    assert set(rf["mbu"]) == {"prefill", "decode"}
    for v in rf["mbu"].values():
        assert 0.0 < v <= 1.0
    assert rf["window_steps"] > 0
    # flight records: record schema v3 fields on every executed step
    recs = [r for r in eng.flight.tail() if r.get("mfu") is not None]
    assert recs, "executed steps must carry roofline attribution"
    for r in recs:
        assert 0.0 < r["mfu"] <= 1.0
        assert r["roofline_phase"] in ("prefill", "decode")
        assert isinstance(r["trace_ids"], list)
    # /debug/engine rolling window
    from vllm_omni_tpu.introspection.debugz import engine_debug

    doc = engine_debug(eng)
    assert doc["roofline"]["recent"], "the /debug window must be live"
    # exposition: new series render and validate clean
    text = render_exposition({}, {0: snap})
    assert validate_exposition(text) == []
    assert 'vllm_omni_tpu_engine_step_mfu{stage="0"}' in text
    assert 'vllm_omni_tpu_engine_step_mbu{stage="0",phase="decode"}' \
        in text
    assert 'vllm_omni_tpu_engine_step_mbu{stage="0",phase="prefill"}' \
        in text
