"""Prefill context parallelism + VAE patch parallelism (SURVEY §2.11 rows
'prefill context parallel' and 'VAE patch parallel' — r1 had neither)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax import shard_map
from jax.sharding import PartitionSpec as P

from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.ops.attention import attention_ref
from vllm_omni_tpu.parallel import cp
from vllm_omni_tpu.parallel.context import ring_attention

# multi-device compile-heavy suite: slow tier
pytestmark = pytest.mark.slow


def _mesh(n=8, axis="sp"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


# ------------------------------------------------------- causal ring attn
def test_causal_ring_attention_matches_dense():
    b, s, h, d = 2, 64, 4, 16
    n = 8
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    want = attention_ref(q, k, v, causal=True)

    mesh = _mesh(n)
    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    )
    got = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_causal_ring_rejects_joint_stream():
    mesh = _mesh(2)
    q = jnp.zeros((1, 8, 2, 8))
    with pytest.raises(ValueError, match="joint"):
        shard_map(
            lambda q_: ring_attention(q_, q_, q_, "sp", joint_k=q_[:, :2],
                                      joint_v=q_[:, :2], causal=True),
            mesh=mesh, in_specs=(P(None, "sp"),),
            out_specs=P(None, "sp"), check_vma=False,
        )(q)


# --------------------------------------------------------- cp prefill fwd
def test_forward_hidden_cp_matches_dense():
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(1), cfg, jnp.float32)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(1, 100, (2, 64)), jnp.int32)
    want = tfm.forward_hidden(params, cfg, toks)
    got = cp.forward_hidden_cp(params, cfg, toks, _mesh(8))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_forward_hidden_cp_mrope():
    import dataclasses

    cfg = dataclasses.replace(tfm.TransformerConfig.tiny(),
                              mrope_sections=(4, 2, 2))
    params = tfm.init_params(jax.random.PRNGKey(2), cfg, jnp.float32)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(1, 100, (1, 32)), jnp.int32)
    want = tfm.forward_hidden(params, cfg, toks)
    got = cp.forward_hidden_cp(params, cfg, toks, _mesh(8))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_forward_hidden_cp_rejects_ragged():
    cfg = tfm.TransformerConfig.tiny()
    params = tfm.init_params(jax.random.PRNGKey(1), cfg, jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        cp.forward_hidden_cp(params, cfg, jnp.zeros((1, 30), jnp.int32),
                             _mesh(8))


# ------------------------------------------------------- vae patch decode
def test_patch_parallel_vae_decode_matches_single_device():
    from vllm_omni_tpu.models.qwen_image import vae as vae_mod

    cfg = vae_mod.VAEConfig.tiny()
    params = vae_mod.init_decoder(jax.random.PRNGKey(0), cfg, jnp.float32)
    lat = jax.random.normal(jax.random.PRNGKey(1),
                            (1, 16, 8, cfg.latent_channels), jnp.float32)
    want = np.asarray(vae_mod.decode(params, cfg, lat))
    got = cp.patch_parallel_decode(
        lambda p, l: vae_mod.decode(p, cfg, l), params, lat, _mesh(8),
        out_sharded=False)
    # GSPMD halo exchange must reproduce the single-device conv exactly
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_patch_parallel_video_vae_decode():
    from vllm_omni_tpu.models.common import causal_vae as vvae

    cfg = vvae.CausalVAEConfig.tiny()
    params = vvae.init_params(jax.random.PRNGKey(0), cfg, encoder=False)
    lat = jax.random.normal(jax.random.PRNGKey(1),
                            (1, 3, 16, 8, cfg.latent_channels), jnp.float32)
    want = np.asarray(vvae.decode(params, cfg, lat))

    from jax.sharding import NamedSharding, PartitionSpec as P2

    mesh = _mesh(8)
    lat_s = jax.device_put(
        lat, NamedSharding(mesh, P2(None, None, "sp", None, None)))
    params_r = jax.device_put(params, NamedSharding(mesh, P2()))
    got = jax.jit(
        lambda p, l: vvae.decode(p, cfg, l),
        out_shardings=NamedSharding(mesh, P2()),
    )(params_r, lat_s)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)
