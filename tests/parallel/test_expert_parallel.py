"""Expert parallelism: MoE expert weights sharded over the mesh "ep" axis
must reproduce single-device numerics — GSPMD partitions the expert einsums
and inserts the combine psum (the XLA analogue of the reference's
all-to-all EP dispatch, SURVEY.md §2.11)."""

import jax
import pytest

pytestmark = pytest.mark.slow  # multi-device compile-heavy; the dryrun MoE-EP leg covers this path
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.parallel.mesh import MeshConfig, build_mesh
from vllm_omni_tpu.parallel.sharding import shard_moe_params as _shard_moe_params


def test_ep_sharded_forward_matches_single_device(devices8):
    cfg = tfm.TransformerConfig.tiny_moe()  # 4 experts
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    ids = jnp.asarray([[5, 3, 9, 1, 7, 2, 8, 4]], jnp.int32)

    want = tfm.forward_hidden(params, cfg, ids)

    mesh = build_mesh(MeshConfig(expert_parallel_size=4), devices8[:4])
    sharded = _shard_moe_params(params, mesh)
    got = jax.jit(
        lambda p, i: tfm.forward_hidden(p, cfg, i)
    )(sharded, ids)

    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ep_with_dp_mesh(devices8):
    """ep=4 x dp=2 mesh: batch over dp, experts over ep."""
    cfg = tfm.TransformerConfig.tiny_moe()
    params = tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 8)), jnp.int32
    )
    want = tfm.forward_hidden(params, cfg, ids)

    mesh = build_mesh(
        MeshConfig(data_parallel_size=2, expert_parallel_size=4), devices8
    )
    sharded = _shard_moe_params(params, mesh)
    ids_sharded = jax.device_put(ids, NamedSharding(mesh, P("dp", None)))
    got = jax.jit(lambda p, i: tfm.forward_hidden(p, cfg, i))(
        sharded, ids_sharded
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
