"""Every diffusion pipeline must HONOR the mesh or REFUSE it.

Mesh-vs-single-device output equality for Wan (video SP — the sequences
where SP matters most), SD3 (dp+cfg), Flux (dp), StableAudio (dp+SP), and
refusal errors for axes a pipeline cannot run (VERDICT r2 weak #3: a
silently ignored ``mesh=`` is worse than an error).  8-device CPU mesh
from tests/conftest.py.  Qwen-Image's own mesh parity lives in
test_pipeline_mesh.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_omni_tpu.diffusion.request import (
    OmniDiffusionRequest,
    OmniDiffusionSamplingParams,
)
from vllm_omni_tpu.parallel.mesh import MeshConfig, build_mesh

# multi-device compile-heavy suite: slow tier
pytestmark = pytest.mark.slow


def _mesh(**deg):
    cfg = MeshConfig(
        data_parallel_size=deg.get("dp", 1),
        cfg_parallel_size=deg.get("cfg", 1),
        ulysses_degree=deg.get("ulysses", 1),
        ring_degree=deg.get("ring", 1),
        tensor_parallel_size=deg.get("tp", 1),
    )
    n = 1
    for v in deg.values():
        n *= v
    return build_mesh(cfg, jax.devices()[:n])


def _assert_images_equal(a, b, atol=1):
    np.testing.assert_allclose(
        np.asarray(a, np.int32), np.asarray(b, np.int32), atol=atol)


def test_wan_t2v_mesh_matches_single_device():
    from vllm_omni_tpu.models.wan.pipeline import (
        WanPipelineConfig,
        WanT2VPipeline,
    )

    cfg = WanPipelineConfig.tiny()
    sp = OmniDiffusionSamplingParams(
        height=32, width=32, num_frames=5, num_inference_steps=2,
        guidance_scale=4.0, seed=11)
    req = lambda: OmniDiffusionRequest(  # noqa: E731
        prompt=["a dog", "the sea"], sampling_params=sp,
        request_ids=["a", "b"])
    single = WanT2VPipeline(cfg, dtype=jnp.float32, seed=0)
    want = [o.data for o in single.forward(req())]
    meshed = WanT2VPipeline(
        cfg, dtype=jnp.float32, seed=0,
        mesh=_mesh(cfg=2, ulysses=2))
    got = [o.data for o in meshed.forward(req())]
    for w, g in zip(want, got):
        _assert_images_equal(g, w)


def test_wan_refuses_tp_axis():
    from vllm_omni_tpu.models.wan.pipeline import (
        WanPipelineConfig,
        WanT2VPipeline,
    )

    with pytest.raises(ValueError, match="does not support mesh axes"):
        WanT2VPipeline(WanPipelineConfig.tiny(), mesh=_mesh(tp=2))


def test_sd3_mesh_matches_single_device():
    from vllm_omni_tpu.models.sd3.pipeline import (
        SD3Pipeline,
        SD3PipelineConfig,
    )

    cfg = SD3PipelineConfig.tiny()
    sp = OmniDiffusionSamplingParams(
        height=32, width=32, num_inference_steps=2, guidance_scale=4.0,
        seed=5)
    req = lambda: OmniDiffusionRequest(  # noqa: E731
        prompt=["x", "y"], sampling_params=sp, request_ids=["a", "b"])
    single = SD3Pipeline(cfg, dtype=jnp.float32, seed=0)
    want = [o.data for o in single.forward(req())]
    meshed = SD3Pipeline(cfg, dtype=jnp.float32, seed=0,
                         mesh=_mesh(dp=2, cfg=2))
    got = [o.data for o in meshed.forward(req())]
    for w, g in zip(want, got):
        _assert_images_equal(g, w)


def test_sd3_refuses_sp_axis():
    from vllm_omni_tpu.models.sd3.pipeline import (
        SD3Pipeline,
        SD3PipelineConfig,
    )

    with pytest.raises(ValueError, match="does not support mesh axes"):
        SD3Pipeline(SD3PipelineConfig.tiny(), mesh=_mesh(ulysses=2))


def test_flux_dp_matches_single_device():
    from vllm_omni_tpu.models.flux.pipeline import (
        FluxPipeline,
        FluxPipelineConfig,
    )

    cfg = FluxPipelineConfig.tiny()
    sp = OmniDiffusionSamplingParams(
        height=32, width=32, num_inference_steps=2, guidance_scale=3.5,
        seed=9)
    req = lambda: OmniDiffusionRequest(  # noqa: E731
        prompt=["x", "y"], sampling_params=sp, request_ids=["a", "b"])
    single = FluxPipeline(cfg, dtype=jnp.float32, seed=0)
    want = [o.data for o in single.forward(req())]
    meshed = FluxPipeline(cfg, dtype=jnp.float32, seed=0, mesh=_mesh(dp=2))
    got = [o.data for o in meshed.forward(req())]
    for w, g in zip(want, got):
        _assert_images_equal(g, w)


def test_flux_refuses_cfg_axis():
    from vllm_omni_tpu.models.flux.pipeline import (
        FluxPipeline,
        FluxPipelineConfig,
    )

    with pytest.raises(ValueError, match="does not support mesh axes"):
        FluxPipeline(FluxPipelineConfig.tiny(), mesh=_mesh(cfg=2))


def test_stable_audio_mesh_matches_single_device():
    from vllm_omni_tpu.models.stable_audio.pipeline import (
        StableAudioPipeline,
        StableAudioPipelineConfig,
    )

    cfg = StableAudioPipelineConfig.tiny()
    sp = OmniDiffusionSamplingParams(
        num_inference_steps=2, guidance_scale=1.0, seed=4,
        extra={"seconds_total": 0.25})
    req = lambda: OmniDiffusionRequest(  # noqa: E731
        prompt=["beep", "boop"], sampling_params=sp,
        request_ids=["a", "b"])
    single = StableAudioPipeline(cfg, dtype=jnp.float32, seed=0)
    want = [o.data for o in single.forward(req())]
    meshed = StableAudioPipeline(cfg, dtype=jnp.float32, seed=0,
                                 mesh=_mesh(dp=2, ulysses=2))
    got = [o.data for o in meshed.forward(req())]
    for w, g in zip(want, got):
        np.testing.assert_allclose(g, w, atol=2e-4)
